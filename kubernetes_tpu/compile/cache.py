"""Persistent compile cache: the declared ladder + XLA artifacts on disk.

Two layers, both keyed by spec hash + jaxlib version + backend platform:

1. **Ladder registry** (`ladder.json`): which SolveSpecs this deployment
   has ever compiled, with their observed compile times. A fresh process
   loads it and warms exactly that ladder instead of rediscovering it
   one mid-drain stall at a time.
2. **XLA artifacts**: the jax persistent compilation cache
   (`jax_compilation_cache_dir`) holds the compiled HLO keyed by jax's
   own fingerprint, so the re-warm pays trace time only (~5-20x cheaper
   than trace+compile). Where the backend supports executable
   serialization (`jax.experimental.serialize_executable`), whole
   executables round-trip through `exec/<hash>.bin` as well — the
   serializer is injectable so tests exercise the round-trip with a
   stubbed backend and no real XLA dependency.

Everything here is best-effort: a missing/corrupt/version-mismatched
cache degrades to a cold warmup, never to an error.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Dict, List, Optional, Sequence, Tuple

from ..analysis.lockorder import audited_lock
from .ladder import SolveSpec

logger = logging.getLogger("kubernetes_tpu.compile")

LADDER_FILE = "ladder.json"
EXEC_DIR = "exec"

#: env var naming the cache root; unset = no persistence (in-memory plan only)
CACHE_DIR_ENV = "KTPU_COMPILE_CACHE_DIR"


def _environment_key() -> Dict[str, str]:
    """Version/platform key the cache is valid for: a jaxlib upgrade or a
    backend switch invalidates serialized artifacts wholesale."""
    try:
        import jax
        import jaxlib

        platform = "unknown"
        try:
            platform = jax.default_backend()
        except Exception:
            pass
        return {
            "jax": getattr(jax, "__version__", "unknown"),
            "jaxlib": getattr(jaxlib, "__version__", "unknown"),
            "platform": platform,
        }
    except Exception:  # jax absent (pure-host tooling): cache still works
        return {"jax": "none", "jaxlib": "none", "platform": "none"}


class JaxExecutableSerializer:
    """Default executable serializer: jax.experimental.serialize_executable
    (pickle-based AOT round-trip). Raises NotImplementedError when the
    installed jax/backend can't do it — callers treat that as 'no
    executable layer', keeping the ladder + XLA-cache layers working."""

    def serialize(self, compiled) -> bytes:
        from jax.experimental import serialize_executable

        payload, _, _ = serialize_executable.serialize(compiled)
        return payload

    def deserialize(self, blob: bytes):  # pragma: no cover - needs real AOT
        raise NotImplementedError(
            "deserialization needs the original in_tree/out_tree; use the "
            "ladder re-warm path instead"
        )


class PersistentCompileCache:
    """On-disk ladder registry + artifact store rooted at `path`."""

    def __init__(self, path: str, serializer=None):
        self.path = path
        self.serializer = serializer
        self._lock = audited_lock("compile-persist")
        self.enabled_xla_cache = False

    # -- construction --------------------------------------------------------

    @classmethod
    def from_env(cls) -> Optional["PersistentCompileCache"]:
        path = os.environ.get(CACHE_DIR_ENV, "")
        return cls(path) if path else None

    # -- XLA persistent cache hookup -----------------------------------------

    def enable_xla_cache(self, min_compile_secs: float = 0.5) -> bool:
        """Point jax's persistent compilation cache at <path>/xla (unless
        the process already configured one — bench.py does). Best-effort."""
        try:
            import jax

            if getattr(jax.config, "jax_compilation_cache_dir", None):
                self.enabled_xla_cache = True  # someone already set it up
                return True
            d = os.path.join(self.path, "xla")
            os.makedirs(d, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", d)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", min_compile_secs
            )
            self.enabled_xla_cache = True
            return True
        except Exception:
            return False

    # -- ladder registry ------------------------------------------------------

    def _ladder_path(self) -> str:
        return os.path.join(self.path, LADDER_FILE)

    def save_ladder(self, records: Sequence[Tuple[SolveSpec, float]]) -> bool:
        """Persist the declared ladder: [(spec, compile_seconds)]. Merges
        with what's already on disk (two schedulers sharing a cache dir
        union their ladders) and is atomic (tmp+rename)."""
        with self._lock:
            existing: Dict[str, Dict] = {}
            current = self._read()
            if current is not None:
                existing = {e["hash"]: e for e in current.get("specs", [])}
            for spec, secs in records:
                h = spec.hash_hex()
                prev = existing.get(h)
                entry = {
                    "hash": h,
                    "spec": spec.to_dict(),
                    "compile_s": round(float(secs), 4),
                }
                if prev is not None:
                    # keep the larger observed compile time: it's the cold
                    # cost a fresh process should budget for
                    entry["compile_s"] = max(entry["compile_s"], prev.get("compile_s", 0.0))
                existing[h] = entry
            doc = {
                "version": 1,
                "environment": _environment_key(),
                "specs": sorted(existing.values(), key=lambda e: e["hash"]),
            }
            try:
                os.makedirs(self.path, exist_ok=True)
                tmp = self._ladder_path() + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(doc, f, indent=1)
                os.replace(tmp, self._ladder_path())
                return True
            except OSError:
                return False

    def _read(self) -> Optional[Dict]:
        try:
            with open(self._ladder_path()) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def load_ladder(self) -> List[Tuple[SolveSpec, float]]:
        """The persisted ladder, or [] when absent/corrupt/from a different
        jaxlib+backend (a version bump means none of the XLA artifacts are
        reusable — warming the old ladder would be cold anyway, and its
        shapes may no longer match the encoders)."""
        doc = self._read()
        if doc is None or doc.get("version") != 1:
            return []
        if doc.get("environment") != _environment_key():
            logger.info(
                "compile cache at %s is for %s (now %s): ignoring",
                self.path, doc.get("environment"), _environment_key(),
            )
            return []
        out = []
        for entry in doc.get("specs", []):
            try:
                out.append(
                    (SolveSpec.from_dict(entry["spec"]), float(entry.get("compile_s", 0.0)))
                )
            except Exception:
                continue  # one bad entry must not void the ladder
        return out

    def clear(self) -> None:
        """Drop every persisted artifact (docs: `rm -rf` equivalent, used
        after encoder changes that shift shapes/semantics)."""
        import shutil

        with self._lock:
            shutil.rmtree(self.path, ignore_errors=True)

    # -- serialized executables ----------------------------------------------

    def _exec_path(self, spec: SolveSpec) -> str:
        return os.path.join(self.path, EXEC_DIR, spec.hash_hex() + ".bin")

    def save_executable(self, spec: SolveSpec, compiled) -> bool:
        """Serialize one compiled executable (best-effort; False when the
        serializer/backend can't). `compiled` is whatever the serializer
        understands — a jax.stages.Compiled for the default."""
        ser = self.serializer
        if ser is None:
            ser = self.serializer = JaxExecutableSerializer()
        try:
            blob = ser.serialize(compiled)
        except Exception:
            return False
        try:
            os.makedirs(os.path.join(self.path, EXEC_DIR), exist_ok=True)
            tmp = self._exec_path(spec) + ".tmp"
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, self._exec_path(spec))
            return True
        except OSError:
            return False

    def load_executable(self, spec: SolveSpec):
        """Deserialize a previously saved executable, or None (missing
        file, serializer unable, version mismatch)."""
        ser = self.serializer
        if ser is None:
            ser = self.serializer = JaxExecutableSerializer()
        try:
            with open(self._exec_path(spec), "rb") as f:
                blob = f.read()
        except OSError:
            return None
        try:
            return ser.deserialize(blob)
        except Exception:
            return None
