"""Compile-plan subsystem: every XLA compilation decision in one place.

The drain loop must NEVER block on the XLA compiler. Lazily-jitted
programs do exactly that: each fresh (shape bucket, jit-static) signature
mid-drain is a multi-second trace+compile stall on a remote-attached TPU
(round-5 verdict: `dispatch_s: 2.39` and `spec_misses` on the quadratic
config, `mirror_rebuilds: 1` on the gang config). Production JAX serving
stacks solve this with padded shape buckets + ahead-of-time lowering + a
persistent compilation cache (the jax AOT / `jax.export` idiom); this
package applies the same discipline to the scheduler's pods×nodes solve:

* `ladder`  — the shape-ladder policy: the ONE bucket quantizer
  (`pow2_bucket` / `node_axis_bucket`, previously private to
  state/tensors) plus `SolveSpec`, the canonical description of one XLA
  program signature (shape buckets × jit statics), and `ShapeLadder`,
  which rounds raw sizes up to declared rungs so tail batches and
  term-light batches re-execute an existing program instead of tracing a
  fresh one.
* `plan`    — `CompilePlan`: the registry of declared specs with
  hit/miss/compile telemetry. A spec miss after warmup is the failure
  mode this subsystem exists to kill; the plan counts it, logs it, and
  the inline jit fallback still compiles it (correctness never waits on
  coverage).
* `cache`   — `PersistentCompileCache`: the declared ladder serialized
  to disk keyed by spec hash + jaxlib version/backend, plus the XLA
  persistent compilation-cache hookup and (where the backend supports
  it) serialized compiled executables — a process restart re-warms the
  previous ladder from disk instead of rediscovering it.
* `warmup`  — `WarmupService`: lowers + executes the declared ladder
  against the live mirror banks at driver startup and re-warms on
  growth events (bucket growth, mirror rebuilds) on a background
  thread, so the drain loop never meets a cold signature.
"""

from .ladder import (
    ShapeLadder,
    SolveSpec,
    node_axis_bucket,
    pow2_bucket,
)
from .plan import CompilePlan
from .cache import PersistentCompileCache
from .warmup import WarmupService

__all__ = [
    "CompilePlan",
    "PersistentCompileCache",
    "ShapeLadder",
    "SolveSpec",
    "WarmupService",
    "node_axis_bucket",
    "pow2_bucket",
]
