"""CLI entry (cmd/kube-scheduler/app/server.go:64 NewSchedulerCommand).

Flags mirror the reference's surface where the concept maps; two run
modes replace the in-cluster deployment:

  extender — serve the batch solver as an HTTP SchedulerExtender (+ the
             /metrics//healthz mux): the production story for fronting an
             unmodified kube-scheduler (BASELINE deployment).
  sim      — kubemark-style self-contained run: fake apiserver, generated
             cluster, informers, scheduling loop; prints a summary. The
             integration smoke test of the full standalone stack.

Usage:
  python -m kubernetes_tpu --mode extender --port 10250
  python -m kubernetes_tpu --mode sim --nodes 200 --pods 1000
  python -m kubernetes_tpu --config cfg.json --policy-config-file policy.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Optional


def load_token_auth_file(path: str) -> dict:
    """Parse a kube-apiserver --token-auth-file (CSV lines
    token,user[,group1|group2]) → {token: UserInfo}. Real CSV parsing
    (quoted fields may contain commas, as the reference's
    NewCSVTokenAuthenticator gets from encoding/csv); malformed lines —
    fewer than two fields, or an empty token/user — are a configuration
    error reported with the line number, never a silent skip or an
    IndexError."""
    import csv

    from .apiserver import UserInfo

    tokens = {}
    with open(path, newline="") as f:
        reader = csv.reader(f)
        while True:
            try:
                row = next(reader)
            except StopIteration:
                break
            except csv.Error as e:
                # reader-level parse errors (unterminated quote, NUL byte)
                # must surface as the same clean configuration error the
                # malformed-row path produces, not a _csv.Error traceback
                raise ValueError(f"{path}:{reader.line_num}: {e}") from e
            lineno = reader.line_num
            parts = [p.strip() for p in row]
            if not parts or not any(parts):
                continue  # blank line
            if parts[0].startswith("#"):
                continue  # comment
            if len(parts) < 2 or not parts[0] or not parts[1]:
                raise ValueError(
                    f"{path}:{lineno}: expected 'token,user[,group1|group2]' "
                    f"with a non-empty token and user, got {','.join(row)!r}"
                )
            groups = tuple(g for g in (parts[2].split("|") if len(parts) > 2
                                       else ()) if g)
            tokens[parts[0]] = UserInfo(parts[1], groups)
    return tokens


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="kubernetes-tpu-scheduler",
        description="TPU-native batch scheduler (kube-scheduler equivalent)",
    )
    p.add_argument("--mode", choices=["extender", "sim"], default="sim")
    p.add_argument("--config", help="KubeSchedulerConfiguration JSON file")
    p.add_argument("--policy-config-file", help="Policy JSON file (overrides provider)")
    p.add_argument("--algorithm-provider", default="DefaultProvider")
    p.add_argument("--feature-gates", default="", help="A=true,B=false")
    p.add_argument("--scheduler-name", default="default-scheduler")
    p.add_argument("--address", default="127.0.0.1")
    p.add_argument("--port", type=int, default=10250, help="extender serving port")
    p.add_argument("--metrics-port", type=int, default=10251)
    p.add_argument(
        "--serve-api", type=int, default=0, metavar="PORT",
        help="sim: also serve the apiserver over HTTP (REST list+watch) on "
             "this port so out-of-process clients/replicas can integrate",
    )
    p.add_argument(
        "--token-auth-file", default="",
        help="with --serve-api: require bearer tokens and enforce RBAC "
             "(401/403). CSV lines token,user,group1|group2 — the "
             "kube-apiserver --token-auth-file format; bootstrap RBAC "
             "roles/bindings are installed at startup",
    )
    p.add_argument("--batch-size", type=int, default=256)
    p.add_argument(
        "--mesh", default="auto",
        help="multi-chip: 'auto' shards the solve over all visible devices "
             "when more than one is present, 'off' forces single-device, an "
             "integer uses that many devices (parallel.node_mesh)",
    )
    p.add_argument("--deterministic", action="store_true")
    p.add_argument(
        "--profile-dir",
        help="sim mode: write a JAX profiler trace (TensorBoard format) for "
        "the run — the device-side half of the reference's "
        "pprof/EnableProfiling surface (cmd/kube-scheduler/app/server.go:"
        "307-316); the host-side half is utils.trace's 100ms slow-cycle "
        "logging. Ignored for the long-lived extender server (a whole-"
        "lifetime trace grows without bound and is lost on SIGTERM).",
    )
    p.add_argument(
        "--services-file",
        help="JSON list of core/v1 Services (scheduling-visible selector "
             "subset) backing Policy serviceAffinity/serviceAntiAffinity",
    )
    # sim mode
    p.add_argument("--nodes", type=int, default=100)
    p.add_argument("--pods", type=int, default=500)
    p.add_argument(
        "--hollow-nodes", action="store_true",
        help="sim: run a hollow kubelet per node (kubemark) — pods are "
             "acked Running from the node side, node health is heartbeat-"
             "driven, and --controllers' node kill becomes a kubelet crash",
    )
    p.add_argument(
        "--controllers", action="store_true",
        help="sim: run the controller-manager (ReplicaSet + nodelifecycle); "
             "pods are created BY ReplicaSets, one node is killed mid-run, "
             "evicted replicas are recreated and re-scheduled",
    )
    p.add_argument("--replicas-per-set", type=int, default=50)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--pod-cpu", default="100m", help="sim pod cpu request")
    p.add_argument(
        "--feature-rate", type=float, default=0.0,
        help="fraction of sim pods carrying generated constraints "
             "(affinity/taints/spread; such pods may be legitimately "
             "unschedulable against the generated nodes)",
    )
    # observability (kubernetes_tpu/obs)
    p.add_argument(
        "--serve-metrics", action="store_true",
        help="sim: serve /metrics + /healthz + warmup-gated /readyz on "
             "--metrics-port for the duration of the drain (the extender "
             "mode always serves them)",
    )
    p.add_argument(
        "--trace", action="store_true",
        help="enable the flight recorder (equivalent to KTPU_TRACE=1): "
             "per-thread span rings + per-pod attribution + black box",
    )
    p.add_argument(
        "--trace-out",
        help="sim: export the flight-recorder timeline to this path as "
             "Chrome-trace JSON after the drain (open in Perfetto); "
             "implies --trace",
    )
    return p


def _configurator(args):
    from .config import Configurator, load_component_config
    from .utils.featuregate import FeatureGate

    fg = FeatureGate()
    fg.parse(args.feature_gates)
    service_lister = None
    if getattr(args, "services_file", None):
        from .api.types import service_from_k8s

        with open(args.services_file) as f:
            services = [service_from_k8s(s) for s in json.load(f)]
        service_lister = lambda: services
    mesh = None
    mesh_arg = getattr(args, "mesh", "auto")
    if mesh_arg != "off":
        # multi-chip: route the device solve through the sharded pipeline
        # over all (or --mesh N) visible chips; single chip → plain path
        import jax

        n_dev = len(jax.devices())
        if mesh_arg == "auto":
            # node-capacity buckets guarantee divisibility only for
            # power-of-two shard counts (state/tensors._node_bucket): round
            # an odd device count down rather than assert on every batch
            want = 1 << (n_dev.bit_length() - 1)
        else:
            try:
                want = int(mesh_arg)
            except ValueError:
                raise SystemExit(f"--mesh must be 'auto', 'off' or an integer, got {mesh_arg!r}")
            if want & (want - 1):
                raise SystemExit(f"--mesh {want}: shard count must be a power of two")
        if want > 1:
            from .parallel import node_mesh

            # an explicit --mesh N larger than the device count must FAIL
            # loudly (node_mesh raises), never fall back to single-device
            mesh = node_mesh(want)
    cfgr = Configurator(
        feature_gates=fg,
        batch_size=args.batch_size,
        deterministic=args.deterministic,
        service_lister=service_lister,
        mesh=mesh,
    )
    cc = None
    if args.config:
        cc = load_component_config(args.config)
        if cc.feature_gates:
            fg.set_from_map(cc.feature_gates)
        if args.policy_config_file is None and cc.policy_file:
            args.policy_config_file = cc.policy_file
        if cc.algorithm_provider:
            args.algorithm_provider = cc.algorithm_provider
        if cc.scheduler_name:
            args.scheduler_name = cc.scheduler_name
    if args.policy_config_file:
        with open(args.policy_config_file) as f:
            return cfgr, cfgr.create_from_config(json.load(f)), cc
    return cfgr, cfgr.create_from_provider(args.algorithm_provider), cc


def run_extender(args) -> int:
    from .extender import ExtenderServer
    from .metrics import MetricsServer

    _, sched, _ = _configurator(args)
    sc = sched.solve_config
    srv = ExtenderServer(
        cache=sched.cache, host=args.address, port=args.port,
        enabled_predicates=sc.predicates if sc else None,
        priority_weights=sc.priorities if sc else None,
        rtcr=sc.rtcr if sc else None,
    )
    srv.start()
    msrv = MetricsServer(host=args.address, port=args.metrics_port).start()
    print(f"extender serving on {srv.url} (filter/prioritize/bind/preemption)")
    print(f"metrics on {msrv.url}/metrics, health on {msrv.url}/healthz")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        srv.stop()
        msrv.stop()
    return 0


def run_sim(args) -> int:
    from .apiserver import FakeAPIServer
    from .client import APIBinder, start_scheduler_informers
    from .models.generators import ClusterGen
    from .scheduler.driver import Binder
    from .scheduler.eventhandlers import EventHandlers

    cfgr, sched, cc = _configurator(args)
    msrv = None
    if args.serve_metrics:
        # scrape endpoint for the sim drain: /metrics + /healthz, with
        # /readyz gated on warmup (503 until the compile plan is armed —
        # a scrape-driven harness cannot race a cold scheduler)
        from .metrics import MetricsServer
        from .obs.introspect import census as _census

        msrv = MetricsServer(
            host=args.address, port=args.metrics_port,
            ready_fn=lambda: sched.ready,
            debug_fn=lambda: _census(sched),
        ).start()
        print(
            f"metrics on {msrv.url}/metrics (readyz gated on warmup; "
            f"plane census on {msrv.url}/debug/ktpu)"
        )
    api = FakeAPIServer()
    api_http = None
    if args.serve_api:
        from .apiserver import APIServerHTTP

        authn = authz = None
        if getattr(args, "token_auth_file", ""):
            from .apiserver import (RBACAuthorizer, TokenAuthenticator,
                                    install_bootstrap_rbac)

            try:
                tokens = load_token_auth_file(args.token_auth_file)
            except ValueError as e:
                raise SystemExit(f"--token-auth-file: {e}")
            install_bootstrap_rbac(api)
            authn, authz = TokenAuthenticator(tokens), RBACAuthorizer(api)
        api_http = APIServerHTTP(api, port=args.serve_api,
                                 authenticator=authn, authorizer=authz).start()
        mode = "RBAC-secured" if authn else "open"
        print(f"apiserver HTTP on {api_http.url} (list/watch/create/bind, {mode})")
    sched.binder = Binder(APIBinder(api).bind)
    # scheduler events land in the apiserver's events kind (kubectl get
    # events shows Scheduled/FailedScheduling/Preempted series)
    from .utils.events import Recorder, api_sink

    recorder = Recorder(sink=api_sink(api))
    sched.event_fn = recorder.pod_event_fn()
    # leaderElection.leaderElect (server.go:157 → leaderelection.RunOrDie):
    # acquire the lease before scheduling; renew each cycle, stand down on
    # loss (active-passive replicas, SURVEY §2.3)
    elector = None
    if cc is not None and cc.leader_election.leader_elect:
        import socket

        from .utils.leaderelection import LeaderElector, LeaseLock

        le = cc.leader_election
        elector = LeaderElector(
            LeaseLock(api),
            identity=f"{socket.gethostname()}_{os.getpid()}",
            lease_duration_s=le.lease_duration_s,
            renew_deadline_s=le.renew_deadline_s,
            retry_period_s=le.retry_period_s,
        )
        while not elector.try_acquire_or_renew():
            time.sleep(elector.retry_period_s)
    g = ClusterGen(args.seed)
    nodes, existing = g.cluster(args.nodes, 0, feature_rate=0.3)
    hollow = None
    if args.hollow_nodes:
        from .kubemark import HollowCluster

        # the kubelets register their own Node objects
        hollow = HollowCluster(api, nodes, heartbeat_s=0.5).start()
    else:
        for n in nodes:
            api.create("nodes", n)
    handlers = EventHandlers(sched.cache, sched.queue, args.scheduler_name)
    informers = start_scheduler_informers(api, handlers)
    for inf in informers.values():
        inf.wait_for_sync()
    from .api.types import (
        Container,
        LabelSelector,
        Pod,
        Quantity,
        RESOURCE_CPU,
        RESOURCE_MEMORY,
        ReplicaSet,
    )

    cm = None
    if args.controllers:
        # controller-driven churn: pods are created by ReplicaSets through
        # the apiserver, not pre-filled into the queue
        from .controllers import ControllerManager

        cm = ControllerManager(
            api,
            node_monitor_grace_s=2.0 if args.hollow_nodes else None,
        ).start()
        n_sets = max(1, args.pods // args.replicas_per_set)
        for s in range(n_sets):
            replicas = args.replicas_per_set if s < n_sets - 1 else (
                args.pods - args.replicas_per_set * (n_sets - 1)
            )
            tmpl = Pod(
                name="t", namespace="sim", labels={"app": f"rs-{s}"},
                containers=[Container(name="c", requests={
                    RESOURCE_CPU: Quantity.parse(args.pod_cpu),
                    RESOURCE_MEMORY: Quantity.parse("128Mi"),
                })],
            )
            tmpl.scheduler_name = args.scheduler_name
            api.create("replicasets", ReplicaSet(
                name=f"rs-{s}", namespace="sim", replicas=replicas,
                selector=LabelSelector(match_labels={"app": f"rs-{s}"}),
                template=tmpl,
            ))
    else:
        for i in range(args.pods):
            if args.feature_rate > 0:
                p = g.pod(10_000 + i, feature_rate=args.feature_rate)
            else:
                p = Pod(
                    name=f"sim-{i}", namespace="sim",
                    containers=[Container(name="c", requests={
                        RESOURCE_CPU: Quantity.parse(args.pod_cpu),
                        RESOURCE_MEMORY: Quantity.parse("128Mi"),
                    })],
                )
            # pods must name THIS scheduler or the handlers drop them
            # (eventhandlers.go responsibleForPod)
            p.scheduler_name = args.scheduler_name
            api.create("pods", p)
    t0 = time.perf_counter()
    deadline = time.time() + 300
    idle = 0
    killed = None
    evicted_at_kill = 0
    renew_by = None
    while time.time() < deadline:
        if elector is not None:
            # renew each cycle; a single failed CAS is NOT loss — keep
            # retrying until renewDeadline elapses (leaderelection.go:159)
            if elector.try_acquire_or_renew():
                renew_by = time.monotonic() + elector.renew_deadline_s
            elif renew_by is not None and time.monotonic() >= renew_by:
                # deposed past the renew deadline: stand down
                # (OnStoppedLeading → the reference exits)
                print(json.dumps({"mode": "sim", "error": "lost leader lease"}))
                for inf in informers.values():
                    inf.stop()
                return 1
        sched.queue.flush()
        r = sched.schedule_batch()
        pods, _ = api.list("pods")
        live = [p for p in pods if p.phase != "Failed"]
        clear_of_killed = killed is None or not any(
            p.node_name == killed for p in live
        )
        if (len(live) >= args.pods and all(p.node_name for p in live)
                and clear_of_killed):
            if cm is not None and not killed:
                # kill one node that hosts pods: the lifecycle controller
                # taints + evicts, the ReplicaSets refill, the scheduler
                # re-places on the survivors — the full control loop. With
                # hollow nodes the kill is a kubelet CRASH (heartbeats
                # stop); otherwise the Ready condition is set directly.
                cm.wait_idle()
                victims = {p.node_name for p in live}
                target = sorted(victims)[0]
                if hollow is not None:
                    hollow.kill(target)
                else:
                    node = api.get("nodes", target)
                    node.conditions = [{"type": "Ready", "status": "False"}]
                    api.update("nodes", node)
                killed = target
                evicted_at_kill = sum(1 for p in live if p.node_name == target)
                continue
            break
        # quiescence: nothing scheduled AND nothing left to try — pods stuck
        # in unschedulableQ wait for cluster events that a static sim never
        # produces, so stop instead of spinning out the deadline
        converged = len(live) >= args.pods
        if cm is not None and killed is not None:
            # controller runs only converge when the refill landed clear of
            # the dead node (lifecycle evictions + RS refills still racing)
            converged = converged and clear_of_killed and all(
                p.node_name for p in live
            )
        if r.scheduled == 0 and r.errors == 0 and r.preempted == 0 and converged:
            idle += 1
            active, backoff, _ = sched.queue.counts()
            if idle >= 3 and active == 0 and backoff == 0:
                break
        else:
            idle = 0
        time.sleep(0.01)
    sched.wait_for_binds()
    elapsed = time.perf_counter() - t0
    pods, _ = api.list("pods")
    live = [p for p in pods if p.phase != "Failed"]
    bound = sum(1 for p in live if p.node_name)
    out = {
        "mode": "sim",
        "nodes": args.nodes,
        "pods": len(live),
        "bound": bound,
        "elapsed_s": round(elapsed, 3),
        "pods_per_sec": round(bound / elapsed, 1) if elapsed > 0 else 0,
        "stats": {k: round(v, 4) if isinstance(v, float) else v
                  for k, v in sched.stats.items()},
    }
    if cm is not None:
        out["controllers"] = {
            "replicaset_syncs": cm.replicaset.sync_count,
            "killed_node": killed,
            "evicted": cm.nodelifecycle.evictions,
            "recreated_and_rebound": evicted_at_kill,
            "bound_on_killed_node": sum(1 for p in live if p.node_name == killed),
        }
        cm.stop()
    print(json.dumps(out))
    for inf in informers.values():
        inf.stop()
    if hollow is not None:
        hollow.stop()
    if api_http is not None:
        api_http.stop()
    if msrv is not None:
        msrv.stop()
    if args.trace_out and sched.obs.enabled:
        # flight-recorder timeline for this drain (Chrome-trace JSON;
        # open in Perfetto). Post-drain: resolve_pending may block here.
        print(f"trace -> {sched.dump_trace(args.trace_out)}")
    return 0 if bound == len(live) else 1


def main(argv: Optional[list] = None) -> int:
    import contextlib

    args = build_parser().parse_args(argv)
    if args.trace or args.trace_out:
        # arm the process-global flight recorder BEFORE any scheduler /
        # informer construction so admission-path spans are captured too
        from .obs import RECORDER

        RECORDER.enable(True)
    ctx = contextlib.nullcontext()
    if args.profile_dir and args.mode == "sim":
        import jax

        ctx = jax.profiler.trace(args.profile_dir)
    elif args.profile_dir:
        print("--profile-dir ignored in extender mode", file=sys.stderr)
    with ctx:
        if args.mode == "extender":
            return run_extender(args)
        return run_sim(args)


if __name__ == "__main__":
    sys.exit(main())
