"""The flight recorder core: per-thread span rings, two-phase device
spans, and the black-box cycle ring.

Design constraints, in order:

* **Disabled must cost nothing.** Every instrumentation site guards on
  ``RECORDER.enabled`` (one attribute read) and the ``span()`` call
  itself returns a shared no-op singleton when disabled — no ring write,
  no lock, no allocation beyond the transient call frame.

* **Enabled must not serialize threads.** Each thread writes spans only
  into its OWN fixed-capacity ring (``threading.local``), so the hot
  paths never contend; the only locked structures are the cold ring
  registry (touched once per thread lifetime), the device-span pending
  table (driver thread + export), and the black-box deque (once per
  batch).

* **Hot paths must not force device syncs.** Device spans are two-phase
  (KTPU004: dispatch code may not call ``block_until_ready``):
  ``device_begin`` records the dispatch timestamp and parks the
  dispatched array handle; the end stamp comes either from
  ``device_end`` at the batch's designated fetch point (the result was
  just fetched — stamping is free) or from ``resolve_pending()``, the
  one audited sync point of this module (checkers.repo_config
  sync_allowlist), which blocks on abandoned handles off the hot path
  at export/drain time.

The recorder's internal lock is a PLAIN ``threading.Lock`` on purpose —
like ``analysis.lockorder.LockOrderRegistry``, the diagnostic layer
lives outside the audited lock world so a black-box dump fired from
inside ``LockOrderViolation`` can never feed back into the edge graph
it is reporting on.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger("kubernetes_tpu.obs")

TRACE_ENV = "KTPU_TRACE"
#: pseudo-thread name device spans are merged under (their time is chip
#: time, not any host thread's)
DEVICE_THREAD = "device"

#: spans per thread ring (wraparound drops the oldest); 64k spans cover
#: a 100k-pod drain's batch-level spans with room for per-pod enqueues
DEFAULT_RING_CAPACITY = 1 << 16
#: unresolved device spans parked at once; overflow abandons the oldest
#: (recorded with zero duration) so parked array handles can never pin
#: unbounded device memory
MAX_PENDING_DEVICE = 512
#: black-box cycle records kept (a bounded ring: the LAST N batches)
BLACKBOX_CAPACITY = 256


def trace_env_enabled() -> bool:
    return os.environ.get(TRACE_ENV, "") not in ("", "0", "false", "False")


class _NoopSpan:
    """Shared do-nothing context manager — the disabled fast path returns
    THIS singleton, never a fresh object."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **kw) -> None:
        pass


NOOP_SPAN = _NoopSpan()


class _Span:
    """One live span: records (name, t0, dur, args) into its thread's
    ring on exit. Args are kept as the dict the call site built — no
    copying on the hot path; export serializes them."""

    __slots__ = ("_ring", "name", "args", "t0")

    def __init__(self, ring: "_Ring", name: str, args: Optional[dict]):
        self._ring = ring
        self.name = name
        self.args = args

    def set(self, **kw) -> None:
        """Attach args discovered mid-span (e.g. rows flushed)."""
        if self.args is None:
            self.args = kw
        else:
            self.args.update(kw)

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._ring.add(self.name, self.t0, time.perf_counter() - self.t0, self.args)
        return False


class _Ring:
    """Fixed-capacity span ring owned by ONE thread (lock-free by
    construction: only the owner appends; export snapshots, accepting
    the bounded raciness of reading a live ring — export runs at
    quiesce points in practice)."""

    __slots__ = ("tid", "thread_name", "cap", "buf", "n")

    def __init__(self, tid: int, thread_name: str, cap: int):
        self.tid = tid
        self.thread_name = thread_name
        self.cap = cap
        self.buf: List = [None] * cap
        self.n = 0  # total spans ever recorded (n - len kept = dropped)

    def add(self, name: str, t0: float, dur: float, args: Optional[dict]) -> None:
        self.buf[self.n % self.cap] = (name, t0, dur, args)
        self.n += 1

    def snapshot(self) -> List[Tuple[str, float, float, Optional[dict]]]:
        """Records in chronological order (oldest kept first)."""
        n, cap = self.n, self.cap
        if n <= cap:
            return [r for r in self.buf[:n] if r is not None]
        start = n % cap
        out = self.buf[start:] + self.buf[:start]
        return [r for r in out if r is not None]

    @property
    def dropped(self) -> int:
        return max(self.n - self.cap, 0)


class FlightRecorder:
    def __init__(
        self,
        capacity: int = DEFAULT_RING_CAPACITY,
        enabled: Optional[bool] = None,
        blackbox_capacity: int = BLACKBOX_CAPACITY,
    ):
        #: THE flag every instrumentation site guards on. Plain attribute
        #: read: stale reads during an enable/disable transition only
        #: gain or lose a span.
        self.enabled = trace_env_enabled() if enabled is None else bool(enabled)
        self.capacity = capacity
        self._mu = threading.Lock()  # cold structures only (see module doc)
        self._local = threading.local()
        self._rings: List[_Ring] = []
        self._device_ring = _Ring(tid=0, thread_name=DEVICE_THREAD, cap=capacity)
        # token -> [name, t0, handle, args]; insertion-ordered so overflow
        # abandons the OLDEST parked handle
        self._pending: Dict[int, List] = {}
        self._next_token = 1
        self._epoch = time.perf_counter()
        self._blackbox: deque = deque(maxlen=blackbox_capacity)
        self.dropped_pending = 0

    # -- enable / reset ------------------------------------------------------

    def enable(self, on: bool = True) -> None:
        self.enabled = bool(on)

    def reset(self) -> None:
        """Drop every recorded span / pending device span / black-box
        record (tests; a bench starting a fresh measured window)."""
        with self._mu:
            self._rings = []
            self._local = threading.local()
            self._device_ring = _Ring(0, DEVICE_THREAD, self.capacity)
            self._pending = {}
            self._blackbox.clear()
            self._epoch = time.perf_counter()
            self.dropped_pending = 0

    # -- host spans ----------------------------------------------------------

    def _ring(self) -> _Ring:
        ring = getattr(self._local, "ring", None)
        if ring is None:
            t = threading.current_thread()
            ring = _Ring(tid=t.ident or id(t), thread_name=t.name, cap=self.capacity)
            self._local.ring = ring
            with self._mu:
                self._rings.append(ring)
        return ring

    def span(self, name: str, **args):
        """Context manager timing one stage on the CURRENT thread. When
        disabled returns the shared no-op singleton. Hot per-pod sites
        should guard with ``if rec.enabled:`` so even the kwargs dict is
        never built."""
        if not self.enabled:
            return NOOP_SPAN
        return _Span(self._ring(), name, args or None)

    def instant(self, name: str, **args) -> None:
        """Zero-duration marker (exported as an instant event)."""
        if not self.enabled:
            return
        self._ring().add(name, time.perf_counter(), 0.0, args or None)

    def record(self, name: str, t0: float, **args) -> None:
        """Record a span begun at `t0` (perf_counter) and ending NOW —
        for sites that already time themselves and must not re-indent a
        long body under a context manager."""
        if not self.enabled:
            return
        self._ring().add(name, t0, time.perf_counter() - t0, args or None)

    # -- two-phase device spans ----------------------------------------------

    def device_begin(self, name: str, handle, **args) -> int:
        """Phase 1 (hot path, non-forcing): record the dispatch timestamp
        and park the dispatched array handle. Returns a token for
        ``device_end``; 0 when disabled."""
        if not self.enabled:
            return 0
        t0 = time.perf_counter()
        with self._mu:
            token = self._next_token
            self._next_token += 1
            self._pending[token] = [name, t0, handle, args or None]
            if len(self._pending) > MAX_PENDING_DEVICE:
                # abandon the oldest parked handle: record it with zero
                # duration rather than pin device memory indefinitely
                old_tok = next(iter(self._pending))
                nm, ot0, _h, oargs = self._pending.pop(old_tok)
                oargs = dict(oargs or ())
                oargs["abandoned"] = True
                self._device_ring.add(nm, ot0, 0.0, oargs)
                self.dropped_pending += 1
        return token

    def device_end(self, token: int) -> None:
        """Phase 2 at the batch's designated fetch point: the caller just
        fetched the result (jax.device_get returned), so the program is
        known-complete — stamping 'now' is non-forcing and honest to
        within the fetch's own wall."""
        if not token:
            return
        t_end = time.perf_counter()
        with self._mu:
            rec = self._pending.pop(token, None)
            if rec is None:
                return
            name, t0, _handle, args = rec
            self._device_ring.add(name, t0, t_end - t0, args)

    # ktpu: host-sync-ok the ONE audited resolver of parked device spans
    # (checkers.repo_config sync_allowlist) — runs at export/drain time,
    # never on a hot path
    def resolve_pending(self) -> int:
        """Resolve every still-parked device span by blocking on its
        handle (spans whose batch was abandoned mid-drain — poisoned
        speculative entries — never reach ``device_end``). Returns the
        number resolved."""
        with self._mu:
            pending, self._pending = self._pending, {}
        n = 0
        for name, t0, handle, args in pending.values():
            args = dict(args or ())
            args["resolved_late"] = True
            t_blk = time.perf_counter()
            try:
                handle.block_until_ready()
            except AttributeError:
                pass  # stub arrays in tests: already "ready"
            except Exception:
                args["resolve_error"] = True
            # dispatch→resolve wall would read as phantom device time for
            # a program that finished long before export (poisoned
            # speculative batches): the honest duration is the observed
            # block wall — ~0 for long-finished programs, the remaining
            # device wall for ones still executing at resolution
            dur = time.perf_counter() - t_blk
            with self._mu:
                self._device_ring.add(name, t0, dur, args)
            n += 1
        return n

    def pending_count(self) -> int:
        with self._mu:
            return len(self._pending)

    # -- black box -----------------------------------------------------------

    def record_cycle(self, record: dict) -> None:
        """Append one per-batch cycle record to the bounded black box."""
        if not self.enabled:
            return
        with self._mu:
            self._blackbox.append(record)

    def blackbox_snapshot(self) -> List[dict]:
        with self._mu:
            return list(self._blackbox)

    def dump_blackbox(self, reason: str, path: Optional[str] = None) -> Optional[str]:
        """Write the black-box ring to a JSON artifact and log where it
        landed. Called on audit failure, LockOrderViolation, or an
        uncaught driver exception — the 'invisible mid-drain' bug class
        becomes a log artifact instead of a bisection hunt. Returns the
        path (None when there was nothing to dump)."""
        records = self.blackbox_snapshot()
        if not records:
            return None
        if path is None:
            # dump-dir hygiene: KTPU_BLACKBOX_DIR > KTPU_TRACE_DIR > the
            # system temp dir — NEVER the CWD (crash artifacts were
            # littering repo checkouts; a configured artifacts dir is
            # created on demand so a crash handler can't fail on mkdir)
            import tempfile

            directory = (
                os.environ.get("KTPU_BLACKBOX_DIR")
                or os.environ.get("KTPU_TRACE_DIR")
                or tempfile.gettempdir()
            )
            try:
                os.makedirs(directory, exist_ok=True)
            except OSError:
                directory = tempfile.gettempdir()
            path = os.path.join(
                directory, f"ktpu_blackbox_{reason}_{os.getpid()}.json"
            )
        try:
            with open(path, "w") as f:
                json.dump(
                    {"reason": reason, "cycles": records}, f, default=str
                )
        except OSError as e:
            logger.warning("black-box dump (%s) failed: %s", reason, e)
            return None
        logger.warning(
            "black box dumped: %d cycle record(s) -> %s (reason: %s)",
            len(records), path, reason,
        )
        return path

    def census(self) -> Dict[str, object]:
        """The recorder's steady-state health block (obs/introspect):
        enabled flag, parked two-phase device spans, overflow-abandoned
        count, black-box depth, ring count. Metadata only — never
        resolves (forces) a parked handle."""
        with self._mu:
            return {
                "enabled": self.enabled,
                "pending_device": len(self._pending),
                "dropped_pending": int(self.dropped_pending),
                "blackbox_records": len(self._blackbox),
                "rings": len(self._rings),
            }

    # -- export --------------------------------------------------------------

    def snapshot_rings(self) -> List[Tuple[int, str, List]]:
        """(tid, thread_name, records) per ring, device ring last —
        raw material for obs.export and scripts/trace_export.py."""
        self.resolve_pending()
        with self._mu:
            rings = list(self._rings)
        out = [(r.tid, r.thread_name, r.snapshot()) for r in rings]
        out.append(
            (
                self._device_ring.tid,
                self._device_ring.thread_name,
                self._device_ring.snapshot(),
            )
        )
        return [(tid, name, recs) for tid, name, recs in out if recs]

    @property
    def epoch(self) -> float:
        return self._epoch

    def save_raw(self, path: str) -> str:
        """JSON dump of the raw rings (the format scripts/trace_export.py
        converts/validates offline)."""
        rings = [
            {
                "tid": tid,
                "thread": name,
                "spans": [
                    {"name": n, "ts": t0, "dur": dur, "args": args}
                    for n, t0, dur, args in recs
                ],
            }
            for tid, name, recs in self.snapshot_rings()
        ]
        with open(path, "w") as f:
            json.dump({"epoch": self._epoch, "rings": rings}, f, default=str)
        return path

    def export(self, path: Optional[str] = None) -> dict:
        """Merge every ring into a Chrome-trace-event document (see
        obs.export); write it to `path` when given."""
        from .export import export_trace

        return export_trace(self, path)


#: the process-global recorder every instrumentation site shares — the
#: informer-thread queue spans, the uploader's flush spans, and the
#: driver all land in one timeline (KTPU_TRACE read at import time;
#: Scheduler(trace=True) flips it on explicitly)
RECORDER = FlightRecorder()


def blackbox_dump_hook(reason: str) -> Optional[str]:
    """Module-level dump entry point for callers that must not hold a
    recorder reference (analysis.lockorder's violation path)."""
    return RECORDER.dump_blackbox(reason)
