"""Chrome-trace-event export: merge the recorder's per-thread rings into
the JSON object format Perfetto / chrome://tracing load directly.

Every span becomes a complete ("X") event; zero-duration records become
instants ("i"); thread names ride metadata ("M") events. Timestamps are
microseconds relative to the recorder's epoch, and the event list is
sorted by ts — the format contract tests/test_obs.py pins.
"""

from __future__ import annotations

import json
from typing import List, Optional


def _event(name: str, ts_us: float, dur_us: float, tid: int, args) -> dict:
    if dur_us <= 0.0:
        ev = {"name": name, "cat": "ktpu", "ph": "i", "s": "t",
              "ts": ts_us, "pid": 1, "tid": tid}
    else:
        ev = {"name": name, "cat": "ktpu", "ph": "X",
              "ts": ts_us, "dur": dur_us, "pid": 1, "tid": tid}
    if args:
        ev["args"] = {k: (v if isinstance(v, (int, float, bool, str)) else str(v))
                      for k, v in args.items()}
    return ev


def merge_events(rings, epoch: float) -> List[dict]:
    """rings: [(tid, thread_name, [(name, t0, dur, args), ...]), ...] →
    sorted traceEvents (metadata first, then spans by ts)."""
    meta: List[dict] = []
    events: List[dict] = []
    for tid, thread_name, records in rings:
        meta.append({
            "name": "thread_name", "ph": "M", "pid": 1, "tid": tid,
            "args": {"name": thread_name},
        })
        for name, t0, dur, args in records:
            events.append(
                _event(name, (t0 - epoch) * 1e6, dur * 1e6, tid, args)
            )
    events.sort(key=lambda e: e["ts"])
    return meta + events


def export_trace(recorder, path: Optional[str] = None) -> dict:
    """Build the trace document from a FlightRecorder (resolving parked
    device spans first — the allowlisted off-thread resolution point)."""
    rings = recorder.snapshot_rings()
    doc = {
        "traceEvents": merge_events(rings, recorder.epoch),
        "displayTimeUnit": "ms",
        "otherData": {
            "producer": "kubernetes_tpu flight recorder",
            "dropped_pending_device_spans": recorder.dropped_pending,
        },
    }
    if path is not None:
        with open(path, "w") as f:
            json.dump(doc, f)
    return doc


def raw_to_trace(raw: dict) -> dict:
    """Convert a recorder.save_raw() document to the Chrome-trace format
    (scripts/trace_export.py offline path)."""
    rings = [
        (
            r["tid"],
            r["thread"],
            [(s["name"], s["ts"], s["dur"], s.get("args")) for s in r["spans"]],
        )
        for r in raw.get("rings", [])
    ]
    return {
        "traceEvents": merge_events(rings, raw.get("epoch", 0.0)),
        "displayTimeUnit": "ms",
    }


def validate_trace(doc: dict) -> List[str]:
    """Structural validation of a Chrome-trace document: every event has
    the required fields, span events carry non-negative durations, and
    non-metadata events are sorted by ts. Returns problem strings
    (empty = valid) — shared by tests and perf_smoke's trace mode."""
    problems: List[str] = []
    events = doc.get("traceEvents")
    if not isinstance(events, list) or not events:
        return ["traceEvents missing or empty"]
    last_ts = None
    begins = 0
    ends = 0
    for i, ev in enumerate(events):
        ph = ev.get("ph")
        if ph not in ("X", "M", "i", "B", "E"):
            problems.append(f"event {i}: unknown ph {ph!r}")
            continue
        if ph == "M":
            if ev.get("name") == "thread_name" and not ev.get("args", {}).get("name"):
                problems.append(f"event {i}: thread_name metadata without a name")
            continue
        for fld in ("name", "ts", "pid", "tid"):
            if fld not in ev:
                problems.append(f"event {i}: missing {fld}")
        if ph == "X" and ev.get("dur", -1.0) < 0:
            problems.append(f"event {i}: X event with negative dur")
        if ph == "B":
            begins += 1
        if ph == "E":
            ends += 1
        ts = ev.get("ts")
        if last_ts is not None and ts is not None and ts < last_ts:
            problems.append(f"event {i}: ts goes backwards ({ts} < {last_ts})")
        if ts is not None:
            last_ts = ts
    if begins != ends:
        problems.append(f"unmatched B/E events ({begins} begins, {ends} ends)")
    return problems
