"""Steady-state health plane: live plane-census introspection, always-on
gauges, and sampled shadow audits.

PR 7's flight recorder answers "what happened in this traced window";
this module is the production counterpart — an always-on view of whether
each device-residency plane is *healthy right now*, the kube-scheduler's
`/metrics` discipline (PAPER.md §9) extended to the planes the reference
cannot have:

* **Unified plane census** — ``census(sched)`` assembles one versioned
  JSON document from one lock-disciplined ``census()`` per subsystem:
  the queue (depth split + oldest-pending age on the queue's own clock),
  the ingest slab + staged bank, the term slab + term bank, the cache
  (+ columnar columns/journal), the tensor mirror (bank occupancy, dirty
  rows, fold bookkeeping, the bytes ledger), the compile ladder
  (per-kind rung/hit/miss), the commit pipeline, and the flight
  recorder. Exported three ways: kube-shaped gauges on the existing
  registry (``export_gauges``), the ``/debug/ktpu`` JSON route on
  ``MetricsServer`` (statusz-style, ``SCHEMA_VERSION``-tagged), and
  ``scripts/ktpu_top.py``'s live terminal table.

* **Background health monitor** — ``HealthMonitor`` refreshes the
  gauges on an interval from its own thread. It is KTPU004-clean by
  construction AND by machine check: every census function below is
  ``# ktpu: hot-path``-marked, so a forcing call (``np.asarray``,
  ``float``, ``block_until_ready`` on a device value) inside any of
  them is a lint violation, not a code-review hope. Driver-confined
  state (the tensor mirror) is never read from the monitor thread —
  the DRIVER publishes ``TensorMirror.census()`` into the monitor's
  guarded mailbox at its post-sync safe point
  (``driver_sync_hook``), the same confinement contract every other
  mirror entry point lives by.

* **Sampled shadow audits** — every ``audit_every`` refreshes the
  monitor marks an audit due; the driver executes it at the next
  batch's safe sync point (commit pipeline drained, mirror freshly
  synced): ``device_bank_divergence`` + the columns-vs-banks
  cross-check, exported as ``ktpu_shadow_audit_total{result}`` with
  last-divergence detail in ``/debug/ktpu`` — silent drift shows up in
  minutes instead of at bench-audit time.

Lock discipline: the monitor's shared state is guarded by ONE audited
lock (role "health") that is always innermost — the monitor acquires
plane locks strictly one-at-a-time while holding nothing, and merges
results under the health lock afterwards, so it can never add an edge
cycle to the lock-order graph (KTPU_LOCK_AUDIT drains include it).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from ..analysis.lockorder import audited_lock, register_thread_role
from ..faults.breaker import STATE_VALUE as _BREAKER_STATE_VALUE
from ..metrics import metrics as M

#: /debug/ktpu schema version — bump on any breaking key change; readers
#: (ktpu_top, tests) refuse documents they don't understand.
#: v2: staged-bank blocks grew the `uploader` liveness sub-block
#: (heartbeat/alive/restarts) and the document grew the `faults` plane
#: (per-plane breaker census, kubernetes_tpu/faults).
#: v3: the document grew the `restart` plane (crash-restart plane,
#: kubernetes_tpu/restart): reconciled flag + the last cold-start's
#: phase-timed report, so ktpu_top answers "when did this instance last
#: rebuild, and what did each reconciliation phase cost".
SCHEMA_VERSION = 3

#: every plane block a census document must carry (the six
#: device-residency planes + the cache + the ladder + the recorder +
#: the fault plane's breaker board + the crash-restart plane)
REQUIRED_PLANES = (
    "queue", "ingest", "terms", "cache", "mirror", "compile", "commit",
    "recorder", "faults", "restart",
)

#: per-plane keys validate_census demands when the plane is enabled
_REQUIRED_KEYS = {
    "queue": ("active", "backoff", "unschedulable", "oldest_pending_age_s",
              "nominated", "scheduling_cycle"),
    "ingest": ("capacity", "rows", "free_rows", "refs_total", "dirty_rows",
               "generation", "stats", "bank"),
    "terms": ("capacity", "rows", "free_rows", "entries", "refs_total",
              "dirty_rows", "generation", "stats", "bank"),
    "cache": ("nodes", "pods", "assumed", "pending_deltas", "dirty_nodes",
              "mutation_count", "columns"),
    "mirror": ("node_capacity", "node_rows", "sig_capacity", "sig_rows",
               "pattern_capacity", "pattern_rows", "device_resident",
               "pending_node_rows", "pending_usage_rows", "folded_usage_rows",
               "fold_count", "folds_undonated", "rebuild_count",
               "bytes_shipped"),
    "compile": ("declared_specs", "hits", "misses", "misses_after_warmup",
                "warmed", "kinds"),
    "commit": ("in_flight", "stats", "verdicts"),
    "recorder": ("enabled", "pending_device", "dropped_pending",
                 "blackbox_records"),
    "faults": ("quiet", "breakers"),
    "restart": ("reconciled",),
}


# ---------------------------------------------------------------------------
# plane census functions (each: one lock-disciplined snapshot, hot-path-
# marked so ktpu-lint KTPU004 machine-checks the no-forcing contract)
# ---------------------------------------------------------------------------

# ktpu: hot-path
def queue_census(queue: "PriorityQueue") -> Dict:
    return queue.census()


# ktpu: hot-path
def ingest_census(stage: "PodStage", bank: "StageBank") -> Dict:
    if stage is None:
        return {"enabled": False}
    out = stage.census()
    out["bank"] = bank.census() if bank is not None else None
    return out


# ktpu: hot-path
def terms_census(tstage: "TermStage", term_bank: "TermBankDevice") -> Dict:
    if tstage is None:
        return {"enabled": False}
    out = tstage.census()
    out["bank"] = term_bank.census() if term_bank is not None else None
    return out


# ktpu: hot-path
def cache_census(cache: "SchedulerCache") -> Dict:
    return cache.census()


# ktpu: hot-path
def compile_census(plan: "CompilePlan") -> Dict:
    # health_census, not snapshot(): one short lock hold, no per-spec
    # list built and discarded at refresh cadence
    return plan.health_census()


# ktpu: hot-path
def commit_census(pipe: "CommitPipeline") -> Dict:
    out = pipe.census()
    # arbiter verdict totals ride the registry counter (process-global:
    # advisory when several schedulers share the process, exact in the
    # one-scheduler production shape)
    out["verdicts"] = {
        v: M.commit_arbiter_verdicts.value(v)
        for v in ("place", "defer", "nofit")
    }
    return out


# ktpu: hot-path
def recorder_census(rec) -> Dict:
    return rec.census()


# ktpu: hot-path
def restart_census(sched) -> Dict:
    """The crash-restart plane's block: whether this instance was cold-
    start reconciled (kubernetes_tpu/restart) and, if so, the last
    reconciliation's phase-timed report. Counters and strings only."""
    report = getattr(sched, "restart_report", None)
    if not report:
        return {"reconciled": False}
    return {"reconciled": True, "last": report}


# ktpu: hot-path
def faults_census(sched) -> Dict:
    """The breaker board's block (kubernetes_tpu/faults): per-plane
    state/trips/probes plus the active FaultPlan schedule when injection
    is armed. Counters and strings only."""
    board = getattr(sched, "faults", None)
    if board is None:
        return {"enabled": False}
    doc = board.census()
    fp = getattr(sched, "_fault_plan", None)
    if fp is not None:
        doc["plan"] = fp.census()
    return doc


def mirror_census(mirror) -> Dict:
    """The mirror block — DRIVER-THREAD ONLY (TensorMirror.census's
    confinement contract). The parameter is deliberately untyped: the
    health role never executes this path (census() consumes the
    monitor's published mailbox when a monitor is attached), and typing
    it would hand the role graph a reach the monitor never performs —
    tripping KTPU008 on the very confinement boundary the mailbox
    exists to keep. The monitor consumes it via the published
    mailbox; callers invoking ``census(sched)`` directly must be on the
    driver thread (tests, the drain loop) or accept an advisory read on
    an idle scheduler."""
    return mirror.census()


# ktpu: hot-path
def census(sched, monitor: Optional["HealthMonitor"] = None) -> Dict:
    """The unified plane census: one versioned, JSON-serializable
    document covering every plane (REQUIRED_PLANES). The mirror block
    comes from the monitor's driver-published mailbox when a monitor is
    attached; otherwise it is sampled in place (callers should then be
    on the driver thread — see mirror_census)."""
    mon = monitor if monitor is not None else getattr(sched, "health", None)
    mirror_block = mon.published("mirror") if mon is not None else None
    if mirror_block is None:
        mirror_block = mirror_census(sched.mirror)
    doc = {
        "version": SCHEMA_VERSION,
        "generated_unix": time.time(),
        "ready": bool(sched.ready),
        "planes": {
            "queue": queue_census(sched.queue),
            "ingest": ingest_census(sched.stage, sched.stage_bank),
            "terms": terms_census(sched.tstage, sched.term_bank),
            "cache": cache_census(sched.cache),
            "mirror": mirror_block,
            "compile": compile_census(sched.compile_plan),
            "commit": commit_census(sched._commit_pipe),
            "recorder": recorder_census(sched.obs),
            "faults": faults_census(sched),
            "restart": restart_census(sched),
        },
    }
    if mon is not None:
        doc["monitor"] = mon.census_block()
    return doc


def validate_census(doc: Dict) -> List[str]:
    """Structural problems with a census document (empty list = valid):
    the schema-versioned contract /debug/ktpu readers rely on. Shared by
    the test suite and perf_smoke's health mode, like
    obs.export.validate_trace."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return ["census is not an object"]
    if doc.get("version") != SCHEMA_VERSION:
        problems.append(
            f"version {doc.get('version')!r} != schema {SCHEMA_VERSION}"
        )
    if "ready" not in doc:
        problems.append("missing 'ready'")
    planes = doc.get("planes")
    if not isinstance(planes, dict):
        return problems + ["missing 'planes' object"]
    for name in REQUIRED_PLANES:
        block = planes.get(name)
        if not isinstance(block, dict):
            problems.append(f"plane '{name}' missing")
            continue
        if block.get("enabled") is False:
            continue  # disabled plane: the flag is the whole contract
        for key in _REQUIRED_KEYS.get(name, ()):
            if key not in block:
                problems.append(f"plane '{name}' missing key '{key}'")
    mon = doc.get("monitor")
    if mon is not None:
        for key in ("refreshes", "shadow_audits", "last_divergence"):
            if key not in mon:
                problems.append(f"monitor block missing key '{key}'")
    try:
        import json

        json.dumps(doc, default=str)
    except (TypeError, ValueError) as e:
        problems.append(f"not JSON-serializable: {e}")
    return problems


# ---------------------------------------------------------------------------
# gauge export
# ---------------------------------------------------------------------------

#: (census plane key, gauge label) pairs for the refcounted slabs
_SLAB_PLANES = (("ingest", "ingest"), ("terms", "terms"))


# ktpu: hot-path
def export_gauges(doc: Dict) -> None:
    """Project a census document onto the always-on registry gauges —
    the kube-shaped scrape surface. Called by the health monitor each
    refresh; safe from any thread (the gauges lock themselves)."""
    planes = doc.get("planes", {})
    q = planes.get("queue") or {}
    M.pending_pods.set(q.get("active", 0), "active")
    M.pending_pods.set(q.get("backoff", 0), "backoff")
    M.pending_pods.set(q.get("unschedulable", 0), "unschedulable")
    M.queue_oldest_pending_age.set(q.get("oldest_pending_age_s", 0.0))
    for key, label in _SLAB_PLANES:
        d = planes.get(key)
        if not d or d.get("enabled") is False:
            continue
        M.plane_slab_occupancy.set(d.get("rows", 0), label)
        M.plane_slab_capacity.set(d.get("capacity", 0), label)
        M.plane_free_rows.set(d.get("free_rows", 0), label)
        M.plane_stale_rows.set(d.get("dirty_rows", 0), label)
        M.plane_refs_total.set(d.get("refs_total", 0), label)
        # uploader liveness flag (census schema v2): a started-but-dead
        # drain thread — the plane stays correct via synchronous
        # dispatch-time flushes, but the off-thread win is silently gone,
        # so the monitor flags it even with the fault plane disabled
        up = (d.get("bank") or {}).get("uploader") or {}
        stalled = bool(up.get("started")) and not up.get("alive", True)
        M.uploader_stalled.set(1.0 if stalled else 0.0, label)
    faults = planes.get("faults") or {}
    for plane, b in (faults.get("breakers") or {}).items():
        M.plane_breaker_state.set(
            _BREAKER_STATE_VALUE.get(b.get("state"), 0.0), plane
        )
    cache = planes.get("cache") or {}
    cols = cache.get("columns")
    if cols:
        M.plane_slab_occupancy.set(cols.get("rows", 0), "columns")
        M.plane_slab_capacity.set(cols.get("capacity", 0), "columns")
        M.plane_free_rows.set(cols.get("free_rows", 0), "columns")
        M.plane_stale_rows.set(cols.get("stale_rows", 0), "columns")
        M.cache_journal_depth.set(cols.get("journal_depth", 0))
    mir = planes.get("mirror") or {}
    if mir:
        M.plane_slab_occupancy.set(mir.get("node_rows", 0), "mirror_nodes")
        M.plane_slab_capacity.set(mir.get("node_capacity", 0), "mirror_nodes")
        M.plane_stale_rows.set(
            mir.get("pending_node_rows", 0) + mir.get("pending_usage_rows", 0),
            "mirror_nodes",
        )
        M.plane_slab_occupancy.set(mir.get("sig_rows", 0), "mirror_sigs")
        M.plane_slab_capacity.set(mir.get("sig_capacity", 0), "mirror_sigs")
        M.plane_slab_occupancy.set(
            mir.get("pattern_rows", 0), "mirror_patterns"
        )
        M.plane_slab_capacity.set(
            mir.get("pattern_capacity", 0), "mirror_patterns"
        )
    comp = planes.get("compile") or {}
    for kind, e in (comp.get("kinds") or {}).items():
        M.compile_ladder_rungs.set(e.get("rungs", 0), kind)
    commit = planes.get("commit") or {}
    M.commit_inflight.set(1.0 if commit.get("in_flight") else 0.0)
    rec = planes.get("recorder") or {}
    M.recorder_pending_device.set(rec.get("pending_device", 0))


# ---------------------------------------------------------------------------
# the background health monitor
# ---------------------------------------------------------------------------

class HealthMonitor:
    """Refreshes the steady-state gauges on an interval and schedules
    sampled shadow audits at the driver's safe sync point. Create on
    the DRIVER thread (the constructor publishes the initial mirror
    census); arm with ``start()``; the scheduler's ``close()`` stops it.

    Thread roles:
      * monitor thread — ``refresh()``: plane censuses (each under its
        own lock, one at a time), gauge export, audit-due bookkeeping;
      * driver thread — ``driver_sync_hook()``: mirror-census
        publication + due-audit execution (the ONE place the audit's
        device forcing is legal: commit pipeline drained, mirror
        freshly synced, and ``device_bank_divergence`` is already the
        designed sync point of the resident-state plane);
      * any thread — ``census_block()`` / ``published()`` readers
        (the /debug/ktpu route runs on the metrics mux threads).
    """

    #: default cadence: gauges every 0.25s, one sampled audit per ~minute
    #: (0.25s x 240). The audit is a full-bank device fetch on the driver
    #: thread (~hundreds of ms at smoke scale), so its cadence is an
    #: operator dial, deliberately orders of magnitude slower than the
    #: gauge refresh — "drift shows up in minutes", not a per-batch tax.
    DEFAULT_INTERVAL = 0.25
    DEFAULT_AUDIT_EVERY = 240

    def __init__(
        self,
        sched,
        interval: float = DEFAULT_INTERVAL,
        audit_every: int = DEFAULT_AUDIT_EVERY,
    ):
        self.sched = sched
        self.interval = float(interval)
        self.audit_every = int(audit_every)
        # always-innermost lock (module docstring): role "health"
        self._lock = audited_lock("health")
        self._published: Dict[str, Dict] = {}  # ktpu: guarded-by(self._lock)
        self._audit_counts: Dict[str, int] = {"clean": 0, "divergent": 0}  # ktpu: guarded-by(self._lock)
        self._last_divergence: List[str] = []  # ktpu: guarded-by(self._lock)
        self._last_audit_unix: Optional[float] = None  # ktpu: guarded-by(self._lock)
        self._refreshes = 0  # ktpu: guarded-by(self._lock)
        self._since_audit = 0  # ktpu: guarded-by(self._lock)
        self._audit_due = False  # ktpu: guarded-by(self._lock)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # initial driver-side publication: the ctor runs on the driver
        # thread by contract, so this read honors the mirror confinement
        self.publish("mirror", mirror_census(sched.mirror))

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "HealthMonitor":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="health-monitor", daemon=True
        )
        self._thread.start()
        M.health_monitor_up.set(1.0)
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=5)
        M.health_monitor_up.set(0.0)

    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    # ktpu: thread-entry(health) the monitor loop: censuses + gauges,
    # never the driver-confined mirror (mailbox only)
    def _run(self) -> None:
        register_thread_role("health")
        while not self._stop.wait(self.interval):
            try:
                self.refresh()
            except Exception:  # pragma: no cover - monitor must never kill the process
                import logging

                logging.getLogger("kubernetes_tpu.obs").exception(
                    "health monitor refresh failed"
                )

    # -- publication mailbox (driver -> monitor/readers) ---------------------

    def publish(self, plane: str, snapshot: Dict) -> None:
        with self._lock:
            self._published[plane] = snapshot

    def published(self, plane: str) -> Optional[Dict]:
        with self._lock:
            return self._published.get(plane)

    # -- the refresh cycle (monitor thread; also callable inline) ------------

    # ktpu: hot-path
    def refresh(self) -> Dict:
        """One monitor cycle: census -> gauges -> audit-due bookkeeping.
        Counters and metadata only (hot-path-marked: a forcing call in
        here is a KTPU004 violation)."""
        doc = census(self.sched, monitor=self)
        export_gauges(doc)
        with self._lock:
            self._refreshes += 1
            self._since_audit += 1
            if self.audit_every > 0 and self._since_audit >= self.audit_every:
                self._since_audit = 0
                self._audit_due = True
        M.health_refresh.inc()
        return doc

    def request_audit(self) -> None:
        """Mark a shadow audit due out-of-cycle (tests; an operator
        poking /debug/ktpu after an alert)."""
        with self._lock:
            self._audit_due = True

    # -- driver-side hooks (driver thread ONLY) ------------------------------

    def driver_sync_hook(self) -> None:
        """Called by the driver at its post-sync safe point (commit
        pipeline drained, mirror freshly synced): publish the
        driver-confined mirror census and execute any due shadow
        audit."""
        self.publish("mirror", mirror_census(self.sched.mirror))
        with self._lock:
            due, self._audit_due = self._audit_due, False
        if due:
            self.run_shadow_audit()

    def run_shadow_audit(self) -> List[str]:
        """Execute one shadow audit ON THE DRIVER THREAD at a safe sync
        point: the existing device_bank_divergence probe (which includes
        the vectorized columns-vs-banks cross-check) — the drift that
        used to surface only at bench-audit time, sampled into the
        steady state. Ships any still-pending dirty rows first
        (device_arrays — the exact patch the next dispatch would pay,
        just earlier in the same cycle) so the probe compares a SETTLED
        host/device pair: right after sync() the host is legitimately
        ahead of the device, and auditing that window would report the
        pipeline's own in-flight delta as drift. Returns the divergence
        list (empty = clean). With no resident device banks there is
        nothing to compare — counted as result="skipped", never as a
        phantom "clean" (the probe's early-return would otherwise let
        the clean counter climb having verified nothing)."""
        mirror = self.sched.mirror
        if mirror._dev_nodes is None:
            M.shadow_audit.inc("skipped")
            with self._lock:
                self._audit_counts["skipped"] = (
                    self._audit_counts.get("skipped", 0) + 1
                )
                self._last_audit_unix = time.time()
            return []
        mirror.device_arrays()
        div = list(mirror.device_bank_divergence())
        result = "divergent" if div else "clean"
        M.shadow_audit.inc(result)
        if div:
            # escalation (kubernetes_tpu/faults): a divergent audit is
            # KNOWN-wrong device state, not a suspicion — force-trip the
            # mirror breaker, queue the resync from host truth, dump the
            # black box. We are on the driver thread at its safe sync
            # point by this method's own contract, holding no locks.
            from ..faults.recover import escalate_divergence

            escalate_divergence(self.sched, div)
        now = time.time()
        with self._lock:
            self._audit_counts[result] = self._audit_counts.get(result, 0) + 1
            self._last_audit_unix = now
            if div:
                self._last_divergence = div
        return div

    # -- readers -------------------------------------------------------------

    def audit_counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._audit_counts)

    def census_block(self) -> Dict:
        """The monitor's own block of the census document."""
        with self._lock:
            return {
                "running": self.running,
                "interval_s": self.interval,
                "audit_every": self.audit_every,
                "refreshes": self._refreshes,
                "shadow_audits": dict(self._audit_counts),
                "last_audit_unix": self._last_audit_unix,
                "last_divergence": list(self._last_divergence),
            }
