"""Flight recorder: pipeline-wide span tracing, per-pod latency
attribution hooks, non-forcing device timing, and a crash black box.

The observability contract of the reference scheduler
(pkg/scheduler/metrics + utiltrace's LogIfLong) extended to the batch
pipeline: every thread of the drain (informer admission, background
uploader, driver, commit-apply worker, bind pool, warmup worker) records
begin/end span records into its own lock-free ring buffer, merged on
export into Chrome-trace-event JSON a 100k-pod drain renders as an
inspectable Perfetto timeline.

Everything here is OFF by default — `KTPU_TRACE=1` (or
``Scheduler(trace=True)``) enables it; the disabled path is a single
attribute check and a shared no-op singleton (no allocation, no lock).
"""

from .recorder import (
    DEVICE_THREAD,
    FlightRecorder,
    NOOP_SPAN,
    RECORDER,
    TRACE_ENV,
)
from .export import export_trace, merge_events, validate_trace

__all__ = [
    "DEVICE_THREAD",
    "FlightRecorder",
    "NOOP_SPAN",
    "RECORDER",
    "TRACE_ENV",
    "export_trace",
    "merge_events",
    "validate_trace",
]
