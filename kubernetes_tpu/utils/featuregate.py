"""Feature gates (component-base/featuregate + pkg/features/kube_features.go).

The scheduler-relevant gates of the reference era with their 1.16 defaults:
EvenPodsSpread alpha/off (kube_features.go:480), ResourceLimits alpha/off,
TaintNodesByCondition GA/on (which is why the node-condition predicates are
NOT in the effective default provider — defaults.go:63-90 replaces them
with taint-based checks), VolumeScheduling GA/on.

Parses the kubelet-style --feature-gates=A=true,B=false syntax.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Mapping, Optional

ALPHA = "ALPHA"
BETA = "BETA"
GA = "GA"


@dataclass(frozen=True)
class FeatureSpec:
    default: bool
    stage: str = ALPHA
    locked_to_default: bool = False  # GA features can't be turned off


# scheduler-relevant subset of kube_features.go (159 gates upstream; the
# rest gate components out of scope here)
KNOWN_FEATURES: Dict[str, FeatureSpec] = {
    "EvenPodsSpread": FeatureSpec(default=False, stage=ALPHA),
    "ResourceLimits": FeatureSpec(default=False, stage=ALPHA),
    "TaintNodesByCondition": FeatureSpec(default=True, stage=GA, locked_to_default=True),
    "VolumeScheduling": FeatureSpec(default=True, stage=GA, locked_to_default=True),
    "ScheduleDaemonSetPods": FeatureSpec(default=True, stage=BETA),
    "NonPreemptingPriority": FeatureSpec(default=False, stage=ALPHA),
}


class FeatureGate:
    def __init__(
        self,
        known: Optional[Mapping[str, FeatureSpec]] = None,
        overrides: Optional[Mapping[str, bool]] = None,
    ):
        self._known = dict(known if known is not None else KNOWN_FEATURES)
        self._enabled: Dict[str, bool] = {}
        if overrides:
            self.set_from_map(overrides)

    def add(self, name: str, spec: FeatureSpec) -> None:
        if name in self._known:
            raise ValueError(f"feature {name} already known")
        self._known[name] = spec

    def enabled(self, name: str) -> bool:
        if name in self._enabled:
            return self._enabled[name]
        spec = self._known.get(name)
        if spec is None:
            raise KeyError(f"unknown feature gate {name}")
        return spec.default

    def set_from_map(self, m: Mapping[str, bool]) -> None:
        for name, value in m.items():
            spec = self._known.get(name)
            if spec is None:
                raise KeyError(f"unknown feature gate {name}")
            if spec.locked_to_default and value != spec.default:
                raise ValueError(f"cannot set {name}: locked to default since {spec.stage}")
            self._enabled[name] = bool(value)

    def parse(self, s: str) -> None:
        """--feature-gates=A=true,B=false"""
        if not s:
            return
        m: Dict[str, bool] = {}
        for part in s.split(","):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise ValueError(f"invalid feature gate {part!r} (want name=bool)")
            name, _, val = part.partition("=")
            if val.lower() not in ("true", "false"):
                raise ValueError(f"invalid boolean {val!r} for feature {name}")
            m[name.strip()] = val.lower() == "true"
        self.set_from_map(m)

    def known(self) -> Dict[str, FeatureSpec]:
        return dict(self._known)


DEFAULT_FEATURE_GATE = FeatureGate()
