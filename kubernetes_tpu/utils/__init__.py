"""Cross-cutting utilities: tracing (utiltrace), event recording
(client-go tools/events subset)."""

from .events import EVENT_TYPE_NORMAL, EVENT_TYPE_WARNING, Event, Recorder
from .trace import SLOW_CYCLE_THRESHOLD_S, Trace

__all__ = [
    "EVENT_TYPE_NORMAL",
    "EVENT_TYPE_WARNING",
    "Event",
    "Recorder",
    "SLOW_CYCLE_THRESHOLD_S",
    "Trace",
]
