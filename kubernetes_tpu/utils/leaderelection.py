"""Leader election (client-go tools/leaderelection/leaderelection.go:197).

The scheduler's HA story is active-passive (SURVEY §2.3): replicas race
for a lease; the holder runs, renewals extend it, and losing the lease is
fatal for the loop (the reference klog.Fatalf's — here on_stopped_leading
fires and run() returns). Locks are CAS-guarded records — the LeaseLock
below rides the fake apiserver's resourceVersion conflicts, exactly the
resourceVersion-precondition discipline of the real Lease objects.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, replace
from typing import Callable, Optional

from ..apiserver.store import ConflictError, FakeAPIServer, NotFoundError


@dataclass
class LeaderElectionRecord:
    """resourcelock.LeaderElectionRecord."""

    holder_identity: str = ""
    lease_duration_s: float = 15.0
    acquire_time: float = 0.0
    renew_time: float = 0.0
    leader_transitions: int = 0
    # lock bookkeeping (apiserver object contract)
    name: str = "kube-scheduler"
    resource_version: str = ""

    def key(self) -> str:
        return self.name


class LeaseLock:
    """resourcelock.Interface over the fake apiserver ("leases" kind):
    get/create/update with resourceVersion CAS — two racing candidates
    cannot both win (ConflictError loses)."""

    def __init__(self, api: FakeAPIServer, name: str = "kube-scheduler"):
        self.api = api
        self.name = name

    def get(self) -> Optional[LeaderElectionRecord]:
        try:
            return self.api.get("leases", self.name)
        except NotFoundError:
            return None

    def create(self, record: LeaderElectionRecord) -> bool:
        record = replace(record, name=self.name)
        try:
            self.api.create("leases", record)
            return True
        except ConflictError:
            return False

    def update(self, record: LeaderElectionRecord) -> bool:
        record = replace(record, name=self.name)
        try:
            self.api.update("leases", record, check_rv=True)
            return True
        except (ConflictError, NotFoundError):
            return False


class LeaderElector:
    def __init__(
        self,
        lock: LeaseLock,
        identity: str,
        lease_duration_s: float = 15.0,
        renew_deadline_s: float = 10.0,
        retry_period_s: float = 2.0,
        on_started_leading: Optional[Callable[[], None]] = None,
        on_stopped_leading: Optional[Callable[[], None]] = None,
        now: Callable[[], float] = time.monotonic,
    ):
        assert lease_duration_s > renew_deadline_s > retry_period_s > 0
        self.lock = lock
        self.identity = identity
        self.lease_duration_s = lease_duration_s
        self.renew_deadline_s = renew_deadline_s
        self.retry_period_s = retry_period_s
        self.on_started_leading = on_started_leading or (lambda: None)
        self.on_stopped_leading = on_stopped_leading or (lambda: None)
        self._now = now
        self._observed: Optional[LeaderElectionRecord] = None
        self._observed_at = 0.0
        self._stop = threading.Event()

    # -- acquire/renew (leaderelection.go:237-259) ---------------------------

    def is_leader(self) -> bool:
        return bool(self._observed and self._observed.holder_identity == self.identity)

    def try_acquire_or_renew(self) -> bool:
        now = self._now()
        current = self.lock.get()
        if current is None:
            rec = LeaderElectionRecord(
                holder_identity=self.identity,
                lease_duration_s=self.lease_duration_s,
                acquire_time=now,
                renew_time=now,
            )
            if not self.lock.create(rec):
                return False
            self._observed = self.lock.get()
            self._observed_at = now
            return True
        # observe changes for expiry tracking
        if self._observed is None or (
            current.holder_identity != self._observed.holder_identity
            or current.renew_time != self._observed.renew_time
        ):
            self._observed = current
            self._observed_at = now
        held_by_other = current.holder_identity and current.holder_identity != self.identity
        lease_valid = self._observed_at + current.lease_duration_s > now
        if held_by_other and lease_valid:
            return False  # someone else holds an unexpired lease
        rec = LeaderElectionRecord(
            holder_identity=self.identity,
            lease_duration_s=self.lease_duration_s,
            acquire_time=current.acquire_time if not held_by_other else now,
            renew_time=now,
            leader_transitions=current.leader_transitions + (1 if held_by_other else 0),
            resource_version=current.resource_version,
        )
        if not self.lock.update(rec):
            return False  # CAS lost: another candidate raced us
        self._observed = self.lock.get()
        self._observed_at = now
        return True

    # -- run loop ------------------------------------------------------------

    def run(self, stop: Optional[threading.Event] = None) -> None:
        """Block until leadership is acquired, call on_started_leading, keep
        renewing; on renewal failure past the deadline call
        on_stopped_leading and return (the caller decides to die or rejoin)."""
        stop = stop or self._stop
        while not stop.is_set():
            if self.try_acquire_or_renew():
                break
            stop.wait(self.retry_period_s)
        if stop.is_set():
            return
        # client-go runs OnStartedLeading in a goroutine: the holder's
        # (typically blocking) work must not starve lease renewal. The
        # callback is caller-supplied state the call graph cannot see —
        # the holder's work registers its own role (typically driver).
        # ktpu: thread-entry(leader)
        threading.Thread(
            target=self.on_started_leading, daemon=True, name="leading"
        ).start()
        deadline = self._now() + self.renew_deadline_s
        while not stop.is_set():
            if self.try_acquire_or_renew():
                deadline = self._now() + self.renew_deadline_s
            elif self._now() >= deadline:
                self.on_stopped_leading()
                return
            stop.wait(self.retry_period_s)
        # voluntary stop: release by letting the lease expire

    def stop(self) -> None:
        self._stop.set()
