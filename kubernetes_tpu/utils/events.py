"""Event recorder (client-go tools/events subset).

The scheduler emits Scheduled / FailedScheduling / Preempted / Nominated
events attached to pods (recordSchedulingFailure, scheduler.go:419-435).
This recorder keeps a bounded in-memory log, de-duplicates into per-key
counts like the events API's series aggregation, and fans out to sinks
(e.g. the fake apiserver's event store).
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Deque, Dict, List, Optional

from ..analysis.lockorder import audited_lock

EVENT_TYPE_NORMAL = "Normal"
EVENT_TYPE_WARNING = "Warning"


@dataclass
class Event:
    reason: str
    message: str
    type: str = EVENT_TYPE_NORMAL
    object_key: str = ""  # namespace/name of the involved object
    count: int = 1
    first_timestamp: float = field(default_factory=time.time)
    last_timestamp: float = field(default_factory=time.time)

    def key(self) -> str:
        """Store key: one object per (involved object, reason) series —
        stable across count bumps (the apiserver's _key_of hook). Shares
        the series-name scheme with the wire metadata (_event_name) so
        remote updates always match in-process objects."""
        return f"{_event_ns(self)}/{_event_name(self)}"


class Recorder:
    def __init__(self, capacity: int = 4096, sink: Optional[Callable[[Event], None]] = None):
        self._lock = audited_lock("event-recorder")
        self._capacity = capacity
        self._events: Deque[Event] = deque()
        self._series: Dict[tuple, Event] = {}
        self.sink = sink

    def event(self, object_key: str, reason: str, message: str, type_: str = EVENT_TYPE_NORMAL) -> None:
        with self._lock:
            key = (object_key, reason, type_)
            ev = self._series.get(key)
            if ev is not None and ev.message == message:
                ev.count += 1
                ev.last_timestamp = time.time()
            else:
                ev = Event(reason=reason, message=message, type=type_, object_key=object_key)
                self._series[key] = ev
                self._events.append(ev)
                # bound BOTH structures: evicting from the ring must drop the
                # series entry too, or memory grows with every unique pod
                while len(self._events) > self._capacity:
                    old = self._events.popleft()
                    okey = (old.object_key, old.reason, old.type)
                    if self._series.get(okey) is old:
                        del self._series[okey]
        if self.sink is not None:
            self.sink(ev)

    def pod_event_fn(self):
        """Adapter matching the Scheduler's event_fn(pod, reason, msg)."""
        warning_reasons = {"FailedScheduling", "Preempted"}

        def fn(pod, reason: str, message: str) -> None:
            self.event(
                pod.key(),
                reason,
                message,
                EVENT_TYPE_WARNING if reason in warning_reasons else EVENT_TYPE_NORMAL,
            )

        return fn

    def events(self, object_key: Optional[str] = None) -> List[Event]:
        with self._lock:
            return [e for e in self._events if object_key is None or e.object_key == object_key]


def _event_ns(ev: Event) -> str:
    """Involved object's namespace; cluster-scoped objects (no slash, e.g.
    a node name) land in "default" — consistently across key(), the wire
    codec, and round-trips."""
    return ev.object_key.split("/", 1)[0] if "/" in ev.object_key else "default"


def _event_name(ev: Event) -> str:
    """Stable per-series name (the events API names series objects)."""
    obj = ev.object_key.replace("/", ".")
    return f"{obj}.{ev.reason.lower()}"


def event_to_k8s(ev: Event) -> dict:
    ns = _event_ns(ev)
    name = ev.object_key.split("/", 1)[1] if "/" in ev.object_key else ev.object_key
    return {
        "apiVersion": "v1",
        "kind": "Event",
        "metadata": {
            "name": _event_name(ev),
            "namespace": ns,
            "resourceVersion": getattr(ev, "resource_version", ""),
        },
        "type": ev.type,
        "reason": ev.reason,
        "message": ev.message,
        "count": ev.count,
        "firstTimestamp": ev.first_timestamp,
        "lastTimestamp": ev.last_timestamp,
        "involvedObject": {"namespace": ns, "name": name},
    }


def event_from_k8s(d: dict) -> Event:
    meta = d.get("metadata") or {}
    inv = d.get("involvedObject") or {}
    ev = Event(
        reason=d.get("reason", ""),
        message=d.get("message", ""),
        type=d.get("type", EVENT_TYPE_NORMAL),
        object_key=f"{inv.get('namespace', 'default')}/{inv.get('name', '')}",
        count=int(d.get("count", 1)),
        first_timestamp=float(d.get("firstTimestamp", 0.0)),
        last_timestamp=float(d.get("lastTimestamp", 0.0)),
    )
    ev.resource_version = str(meta.get("resourceVersion", ""))
    return ev


def node_event_key(node_name: str) -> str:
    """Involved-object key for cluster-scoped nodes: namespaced into
    "default" so key()/codec/round-trips agree."""
    return f"default/{node_name}"


def api_sink(api) -> Callable[[Event], None]:
    """Sink writing event series to the apiserver's "events" kind (the
    recordToSink half of client-go's event broadcaster): one object per
    (involved object, reason) series, updated in place on count bumps."""

    def sink(ev: Event) -> None:
        # event recording must NEVER break scheduling: any transport or
        # store failure drops the event (the reference's broadcaster has
        # the same best-effort contract)
        try:
            obj = Event(
                reason=ev.reason, message=ev.message, type=ev.type,
                object_key=ev.object_key, count=ev.count,
                first_timestamp=ev.first_timestamp,
                last_timestamp=ev.last_timestamp,
            )
            try:
                api.update("events", obj)
            except KeyError:  # incl. NotFoundError: first write of a series
                api.create("events", obj)
        except Exception:
            pass

    return sink
