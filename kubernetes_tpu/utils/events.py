"""Event recorder (client-go tools/events subset).

The scheduler emits Scheduled / FailedScheduling / Preempted / Nominated
events attached to pods (recordSchedulingFailure, scheduler.go:419-435).
This recorder keeps a bounded in-memory log, de-duplicates into per-key
counts like the events API's series aggregation, and fans out to sinks
(e.g. the fake apiserver's event store).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Deque, Dict, List, Optional

EVENT_TYPE_NORMAL = "Normal"
EVENT_TYPE_WARNING = "Warning"


@dataclass
class Event:
    reason: str
    message: str
    type: str = EVENT_TYPE_NORMAL
    object_key: str = ""  # namespace/name of the involved object
    count: int = 1
    first_timestamp: float = field(default_factory=time.time)
    last_timestamp: float = field(default_factory=time.time)


class Recorder:
    def __init__(self, capacity: int = 4096, sink: Optional[Callable[[Event], None]] = None):
        self._lock = threading.Lock()
        self._capacity = capacity
        self._events: Deque[Event] = deque()
        self._series: Dict[tuple, Event] = {}
        self.sink = sink

    def event(self, object_key: str, reason: str, message: str, type_: str = EVENT_TYPE_NORMAL) -> None:
        with self._lock:
            key = (object_key, reason, type_)
            ev = self._series.get(key)
            if ev is not None and ev.message == message:
                ev.count += 1
                ev.last_timestamp = time.time()
            else:
                ev = Event(reason=reason, message=message, type=type_, object_key=object_key)
                self._series[key] = ev
                self._events.append(ev)
                # bound BOTH structures: evicting from the ring must drop the
                # series entry too, or memory grows with every unique pod
                while len(self._events) > self._capacity:
                    old = self._events.popleft()
                    okey = (old.object_key, old.reason, old.type)
                    if self._series.get(okey) is old:
                        del self._series[okey]
        if self.sink is not None:
            self.sink(ev)

    def pod_event_fn(self):
        """Adapter matching the Scheduler's event_fn(pod, reason, msg)."""
        warning_reasons = {"FailedScheduling", "Preempted"}

        def fn(pod, reason: str, message: str) -> None:
            self.event(
                pod.key(),
                reason,
                message,
                EVENT_TYPE_WARNING if reason in warning_reasons else EVENT_TYPE_NORMAL,
            )

        return fn

    def events(self, object_key: Optional[str] = None) -> List[Event]:
        with self._lock:
            return [e for e in self._events if object_key is None or e.object_key == object_key]
