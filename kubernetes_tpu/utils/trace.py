"""utiltrace equivalent (vendor/k8s.io/utils/trace/trace.go:55-120).

In-process step timers logged only when the total exceeds a threshold —
the reference wraps every scheduling cycle in one with a 100ms contract
(generic_scheduler.go:175-176 LogIfLong). Same here, around the batch
cycle.
"""

from __future__ import annotations

import logging
import time
from typing import List, Tuple

logger = logging.getLogger("kubernetes_tpu.trace")

SLOW_CYCLE_THRESHOLD_S = 0.100  # the reference's 100ms LogIfLong contract


def log_slow(name: str, seconds: float,
             threshold_s: float = SLOW_CYCLE_THRESHOLD_S, **fields) -> bool:
    """One-shot LogIfLong for an already-measured span (the compile plan
    reports inline XLA compiles through this — a mid-drain trace+compile
    is exactly the class of stall the 100ms contract exists to surface).
    Returns True when it logged."""
    if seconds < threshold_s:
        return False
    ftxt = " ".join(f"{k}={v}" for k, v in fields.items())
    logger.warning('Trace "%s" %s (total %.1fms)', name, ftxt, seconds * 1000)
    return True


class Trace:
    def __init__(self, name: str, **fields):
        self.name = name
        self.fields = fields
        self.start = time.perf_counter()
        self.steps: List[Tuple[float, str]] = []

    def step(self, msg: str) -> None:
        self.steps.append((time.perf_counter(), msg))

    def total_seconds(self) -> float:
        return time.perf_counter() - self.start

    def log_if_long(self, threshold_s: float = SLOW_CYCLE_THRESHOLD_S) -> bool:
        """Emit the step breakdown when the trace exceeded the threshold.
        Returns True when it logged (tests hook the logger)."""
        total = self.total_seconds()
        if total < threshold_s:
            return False
        fields = " ".join(f"{k}={v}" for k, v in self.fields.items())
        lines = [f'Trace "{self.name}" {fields} (total {total * 1000:.1f}ms):']
        prev = self.start
        for t, msg in self.steps:
            lines.append(f"  +{(t - prev) * 1000:.1f}ms {msg}")
            prev = t
        logger.warning("\n".join(lines))
        return True
