"""Minimal 5-field cron schedule evaluation for the CronJob controller.

The reference vendors robfig/cron (vendor/github.com/robfig/cron) for
`getRecentUnmetScheduleTimes` (pkg/controller/cronjob/utils.go). This is a
from-scratch evaluator for the standard subset CronJob specs actually use:
minute hour day-of-month month day-of-week, each field being `*`, `*/n`,
`a`, `a-b`, `a,b,c` or combinations joined by commas. Day-of-month and
day-of-week combine with OR when both are restricted (POSIX cron rule).
"""

from __future__ import annotations

import time
from typing import List, Optional, Set, Tuple

_RANGES = [(0, 59), (0, 23), (1, 31), (1, 12), (0, 6)]


class CronParseError(ValueError):
    pass


def _parse_field(expr: str, lo: int, hi: int) -> Tuple[Set[int], bool]:
    """→ (allowed values, is_wildcard)."""
    allowed: Set[int] = set()
    wildcard = False
    for part in expr.split(","):
        part = part.strip()
        step = 1
        if "/" in part:
            part, _, step_s = part.partition("/")
            try:
                step = int(step_s)
            except ValueError:
                raise CronParseError(f"bad step {step_s!r}")
            if step <= 0:
                raise CronParseError(f"bad step {step}")
        if part in ("*", ""):
            if step == 1:
                wildcard = True
            allowed.update(range(lo, hi + 1, step))
            continue
        if "-" in part:
            a_s, _, b_s = part.partition("-")
            try:
                a, b = int(a_s), int(b_s)
            except ValueError:
                raise CronParseError(f"bad range {part!r}")
        else:
            try:
                a = b = int(part)
            except ValueError:
                raise CronParseError(f"bad value {part!r}")
        if not (lo <= a <= hi and lo <= b <= hi and a <= b):
            raise CronParseError(f"value out of range [{lo},{hi}]: {part!r}")
        allowed.update(range(a, b + 1, step))
    return allowed, wildcard


class CronSchedule:
    def __init__(self, spec: str):
        fields = spec.split()
        if len(fields) != 5:
            raise CronParseError(f"want 5 fields, got {len(fields)}: {spec!r}")
        parsed = [_parse_field(f, lo, hi) for f, (lo, hi) in zip(fields, _RANGES)]
        (self.minutes, _), (self.hours, _) = parsed[0], parsed[1]
        (self.dom, self.dom_star), (self.months, _), (self.dow, self.dow_star) = (
            parsed[2], parsed[3], parsed[4])

    def _day_matches(self, t: time.struct_time) -> bool:
        dom_ok = t.tm_mday in self.dom
        # python weekday: Mon=0; cron: Sun=0
        dow_ok = ((t.tm_wday + 1) % 7) in self.dow
        if self.dom_star and self.dow_star:
            return True
        if self.dom_star:
            return dow_ok
        if self.dow_star:
            return dom_ok
        return dom_ok or dow_ok  # POSIX OR rule when both restricted

    def matches(self, epoch: float) -> bool:
        t = time.localtime(epoch)
        return (t.tm_min in self.minutes and t.tm_hour in self.hours
                and t.tm_mon in self.months and self._day_matches(t))

    def next_after(self, epoch: float, horizon_days: int = 366) -> Optional[float]:
        """First scheduled time strictly after `epoch` (minute granularity)."""
        # round up to the next whole minute
        t = int(epoch // 60 + 1) * 60
        end = t + horizon_days * 86400
        while t < end:
            st = time.localtime(t)
            if st.tm_mon not in self.months:
                # skip to the 1st of next month
                y, m = st.tm_year, st.tm_mon + 1
                if m > 12:
                    y, m = y + 1, 1
                t = int(time.mktime((y, m, 1, 0, 0, 0, 0, 0, -1)))
                continue
            if not self._day_matches(st):
                t = int(time.mktime((st.tm_year, st.tm_mon, st.tm_mday, 0, 0, 0, 0, 0, -1))) + 86400
                continue
            if st.tm_hour not in self.hours:
                t = int(time.mktime((st.tm_year, st.tm_mon, st.tm_mday, st.tm_hour, 0, 0, 0, 0, -1))) + 3600
                continue
            if st.tm_min not in self.minutes:
                t += 60
                continue
            return float(t)
        return None

    def unmet_since(self, last: float, now: float, limit: int = 100) -> List[float]:
        """Scheduled times in (last, now] — getRecentUnmetScheduleTimes.
        The walk is BOUNDED at limit+1 iterations: past 100 missed starts
        the reference gives up with a too-many-missed-times event
        (cronjob_controller.go — its answer to clock skew / long
        downtime); we signal the same state by returning an empty list,
        and the CronJob controller recovers by advancing
        lastScheduleTime instead of walking months of minutes."""
        out: List[float] = []
        t = self.next_after(last)
        while t is not None and t <= now:
            out.append(t)
            if len(out) > limit:
                return []  # too many missed starts — give up, bounded
            t = self.next_after(t)
        return out
