"""Device-resident staged pod bank: the PodStage slab's on-device twin.

The staging analogue of TensorMirror's dirty-row discipline (state/cache):
the slab uploads ONCE, then only the rows admissions touched since the
last flush cross the wire — batched, off the driver thread (a background
uploader drains the dirty set while the drain runs), chunked at
STAGE_RUNGS so the scatter program set stays small enough to pre-compile.
Every program (the row scatters AND the index-gather prologue) is routed
through the compile plan as a KIND_STAGE spec: staging never compiles
mid-drain, and a post-warmup compile is a counted miss.

Double-buffering falls out of JAX's functional updates: a scatter builds
NEW arrays and swaps the dict reference under the slab lock, so a solve
dispatched against the previous dict keeps its buffers immutable while
the uploader patches the next one (the scatters here are deliberately
NOT donated, unlike the mirror's — in-flight dispatches hold references).

On a mesh the bank places through the mirror's `_to_dev` recipe with
node_major=False — pod-major arrays are replicated, exactly like the
legacy per-batch upload — so warmed executables match dispatched ones.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from ..analysis.lockorder import register_thread_role
from ..compile.ladder import KIND_STAGE, SolveSpec
from ..obs import NOOP_SPAN as _NOOP, RECORDER as _REC
from .stage import PodStage

#: dirty-row scatter rungs (same quantizer idea as the mirror's
#: PATCH_RUNGS): each (structure, rung) pair is one XLA program, warmed
#: up-front; bigger flushes chunk at the top rung.
STAGE_RUNGS = (16, 64, 256)

_STAGE_SCATTER = None


def _stage_rung(n: int, rungs=STAGE_RUNGS) -> int:
    for r in rungs:
        if n <= r:
            return r
    return rungs[-1]


# ktpu: admitted(KIND_STAGE) every dispatch goes through _scatter_rows,
# which admits/declares the (rung, structure) pair as a KIND_STAGE spec
def _scatter_fn():
    """Row scatter over the whole staged-bank dict (compiled once per
    (row-rung, structure) pair). NOT donated: in-flight solve dispatches
    still reference the previous buffers (see module docstring)."""
    global _STAGE_SCATTER
    if _STAGE_SCATTER is None:
        import jax

        @jax.jit
        def scatter(dev, idx, updates):
            out = dict(dev)
            for k, u in updates.items():
                out[k] = dev[k].at[idx].set(u)
            return out

        _STAGE_SCATTER = scatter
    return _STAGE_SCATTER


class StageBank:
    """Keeps a device copy of a PodStage slab patched from its dirty rows.

    Shares the stage's RLock for all slab-coupled state (device dict swap,
    dirty drain) so the driver's covered-dispatch prologue — validate rows,
    flush, capture gather arguments — is atomic against admissions and
    slab rebuilds.

    The uploader machinery (full-upload-then-dirty-rows, chunked plan-
    admitted scatters, off-thread drain, synthetic re-warm after slab
    growth) is slab-agnostic: any stage exposing `batch` (an encoder with
    .arrays()), `empty_rows`, `_lock`, `dirty_rows`, `generation`,
    `capacity`, and an `on_dirty` hook can twin through a subclass — the
    term-bank plane (kubernetes_tpu/terms_plane/bank.py) does exactly
    that, overriding only the class attrs below and the two spec
    builders (`_patch_spec`, `gather_spec`).
    """

    #: worker-thread name, host→device ledger kind, and scatter rungs —
    #: the subclass knobs (terms_plane.bank overrides all three)
    THREAD_NAME = "ingest-upload"
    LEDGER_KIND = "stage"
    RUNGS = STAGE_RUNGS
    #: fault-plane identity (kubernetes_tpu/faults): the breaker this
    #: bank's runtime faults report to
    PLANE = "ingest"

    def __init__(
        self,
        stage: PodStage,
        place_fn: Optional[Callable] = None,
        ship_fn: Optional[Callable[[str, int], None]] = None,
    ):
        self.stage = stage
        self._lock = stage._lock
        self._place = place_fn
        self._ship = ship_fn or (lambda kind, n: None)
        self.compile_plan = None  # attached by the driver
        self._dev: Optional[Dict] = None  # ktpu: guarded-by(self._lock)
        self._empty_dev: Optional[Dict] = None  # ktpu: guarded-by(self._lock)
        self._dev_generation = -1  # ktpu: guarded-by(self._lock)
        # slab generation the scatter rungs were last warmed at: a slab
        # rebuild (capacity growth) changes every scatter program's row-
        # capacity axis, so the uploader re-warms before the first
        # post-growth flush needs them
        self._warmed_generation = -1  # ktpu: guarded-by(self._lock)
        # ktpu: guarded-by(self._lock)
        self.stats: Dict[str, int] = {
            "full_uploads": 0,
            "flush_rows": 0,  # rows shipped by the background worker
            "sync_rows": 0,  # rows the DRIVER had to flush at dispatch
        }
        # background uploader (started by the driver at warmup; without it
        # every flush is a synchronous dispatch-time one — correct, slower)
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._worker: Optional[threading.Thread] = None  # ktpu: guarded-by(self._lock)
        # fault plane (kubernetes_tpu/faults): the driver attaches a
        # fault sink (breaker board) and, under injection, a FaultPlan —
        # both default None so a standalone bank costs one attribute read
        self.fault_sink = None
        self.fault_plan = None
        # uploader liveness: the drain thread stamps a heartbeat each
        # loop so the health monitor can flag a stalled/dead uploader
        # even with the fault plane disabled (census schema v2)
        self._heartbeat_ts = 0.0  # ktpu: guarded-by(self._lock)
        self._last_uploader_error: Optional[str] = None  # ktpu: guarded-by(self._lock)
        self.uploader_restarts = 0  # ktpu: guarded-by(self._lock)
        # set by a dying drain thread BEFORE it reports; lets the
        # recovery distinguish "death in progress, thread still
        # unwinding" (join it) from "worker healthy" (leave it alone)
        self._death_pending = False  # ktpu: guarded-by(self._lock)
        stage.on_dirty = self._wake.set

    # -- placement -----------------------------------------------------------

    def _rung(self, n: int) -> int:
        return _stage_rung(n, self.RUNGS)

    def _to_dev(self, v):
        if self._place is not None:
            return self._place(v)
        import jax.numpy as jnp

        return jnp.asarray(v)

    # -- upload path ---------------------------------------------------------

    def _flush_locked(self, sync: bool = False) -> None:
        """Ship the slab's dirty rows into the device dict (stage lock
        held). Full upload on first use or after a slab rebuild."""
        stage = self.stage
        fp = self.fault_plan
        if fp is not None:
            # kill-point (crash-restart harness): die inside a bank
            # upload — full upload (warmup/resync: the process dies
            # DURING reconciliation) or dirty-row flush (rows half-
            # shipped, the twin torn). Nothing recovers here; the
            # restarted instance rebuilds the slab from the relisted
            # queue and re-uploads from host truth.
            fp.crash_if("mid-uploader-flush")
        if self._dev is None or self._dev_generation != stage.generation:
            with (_REC.span("upload", kind="full", sync=sync)
                  if _REC.enabled else _NOOP):
                host = stage.batch.arrays()
                self._dev = {k: self._to_dev(v) for k, v in host.items()}
                self._empty_dev = {
                    k: self._to_dev(v) for k, v in stage.empty_rows.items()
                }
                self._ship(
                    self.LEDGER_KIND,
                    sum(np.asarray(v).nbytes for v in host.values()),
                )
                self.stats["full_uploads"] += 1
                stage.dirty_rows.clear()
                self._dev_generation = stage.generation
            return
        if not stage.dirty_rows:
            return
        rows = sorted(stage.dirty_rows)
        stage.dirty_rows.clear()
        self.stats["sync_rows" if sync else "flush_rows"] += len(rows)
        host = stage.batch.arrays()
        # upload span: recorded on whichever thread ships the rows — the
        # background uploader in steady state, the driver on a sync flush
        with (_REC.span("upload", rows=len(rows), sync=sync)
              if _REC.enabled else _NOOP):
            self._dev = self._scatter_rows(self._dev, host, rows, warm=False)

    def _patch_spec(self, host: Dict, rb: int) -> SolveSpec:
        """Derived entirely from the HOST dict being scattered (not live
        stage state): synthetic warms run against capacity snapshots that
        may differ from the slab mid-rebuild."""
        structure = ",".join(
            f"{k}{list(v.shape[1:])}" for k, v in sorted(host.items())
        )
        return SolveSpec(
            kind=KIND_STAGE, b=rb, s=next(iter(host.values())).shape[0],
            k=host["label_vals"].shape[1], r=host["req"].shape[1],
            config_repr="patch|" + structure,
        )

    def _scatter_rows(self, dev, host, rows: List[int], warm: bool) -> Dict:
        """Chunked row scatter at STAGE_RUNGS, plan-admitted (the mirror's
        _scatter_rows discipline; `warm=True` declares instead of admitting
        so planned pre-compiles don't inflate the miss counters)."""
        import jax.numpy as jnp

        scatter = _scatter_fn()
        cap = next(iter(host.values())).shape[0]
        rb = min(self._rung(len(rows)), cap)
        plan = self.compile_plan
        known = True
        if plan is not None:
            spec = self._patch_spec(host, rb)
            if warm:
                known = plan.is_declared(spec)
                plan.declare(spec)
            else:
                known = plan.admit(spec)
        dt_compile = 0.0
        first = True
        for i in range(0, len(rows), rb):
            chunk = rows[i : i + rb]
            padded = chunk + [chunk[0]] * (rb - len(chunk))
            idx = np.asarray(padded, np.int32)
            updates = {k: np.ascontiguousarray(h[idx]) for k, h in host.items()}
            self._ship(
                "warm" if warm else self.LEDGER_KIND,
                idx.nbytes + sum(u.nbytes for u in updates.values()),
            )
            if first:
                t0 = time.perf_counter()
                dev = scatter(dev, jnp.asarray(idx), updates)
                dt_compile = time.perf_counter() - t0
                first = False
            else:
                dev = scatter(dev, jnp.asarray(idx), updates)
        if plan is not None and not known:
            from ..compile.plan import SOURCE_INLINE, SOURCE_WARMUP

            plan.note_compiled(
                spec, dt_compile,
                SOURCE_WARMUP if warm
                else (SOURCE_INLINE if plan.warmed else "warmup"),
            )
        return dev

    # -- background uploader -------------------------------------------------

    def start(self) -> None:
        """Arm the off-thread uploader (idempotent). Driver calls this at
        warmup so tests that never warm don't get surprise threads. The
        worker handle is written under the stage lock: recovery restarts
        it from the driver while the health census reads its liveness."""
        with self._lock:
            if self._worker is not None and self._worker.is_alive():
                return
            self._stop.clear()
            worker = threading.Thread(
                target=self._drain, name=self.THREAD_NAME, daemon=True
            )
            self._worker = worker
        worker.start()

    # ktpu: thread-entry(ingest-upload, terms-upload) the background
    # uploader loop — one def, two roles: TermBankDevice inherits it, so
    # the spawned thread runs as whichever bank's THREAD_NAME it carries
    def _drain(self) -> None:
        register_thread_role(self.THREAD_NAME)
        try:
            while not self._stop.is_set():
                self._wake.wait(timeout=0.05)
                self._wake.clear()
                if self._stop.is_set():
                    return
                need_warm = False
                with self._lock:
                    # liveness heartbeat: stamped every loop so census
                    # readers can distinguish a dead thread from an idle
                    # one (health monitor's stalled-uploader flag)
                    self._heartbeat_ts = time.monotonic()
                    if self._dev is None:
                        # the first-ever upload stays with the driver
                        # (warmup), where the compile plan can account it
                        continue
                    fp = self.fault_plan
                    if fp is not None:  # injection site: one attr read
                        fp.raise_if("uploader-death", self.PLANE)
                    if self._warmed_generation != self.stage.generation:
                        need_warm = True  # warmed OUTSIDE the lock, below
                    elif self.stage.dirty_rows or (
                        self._dev_generation != self.stage.generation
                    ):
                        self._flush_locked(sync=False)
                if need_warm:
                    # slab rebuilt (growth): the scatter programs' row-
                    # capacity axis changed — pre-compile the rungs against
                    # SYNTHETIC shape-twins, holding no lock (the compiles
                    # take seconds; admissions and dispatches must not block
                    # on them), before any flush admits the new programs
                    self._warm_synthetic()
        except BaseException as e:
            from ..faults.inject import SimulatedCrash

            if isinstance(e, SimulatedCrash):
                # kill -9 (crash-restart harness): the thread just stops
                # — no breaker report, no bookkeeping, nothing recovers;
                # the supervisor rebuilds the whole instance
                return
            if not isinstance(e, Exception):
                raise  # KeyboardInterrupt/SystemExit: not ours to handle
            # the drain thread is DYING — until now this was invisible
            # (a daemon thread's death just stops the off-thread flushes;
            # dispatch-time sync flushes keep the plane correct, slower).
            # Record why, and force-trip the plane breaker: the recovery
            # restarts the worker exactly once per trip with the dirty
            # backlog flushed synchronously (faults/recover.resync_bank).
            with self._lock:
                self._last_uploader_error = repr(e)
                self._death_pending = True
            sink = self.fault_sink
            if sink is not None:
                sink(self.PLANE, "uploader-death", True)
            logging.getLogger("kubernetes_tpu.ingest").exception(
                "%s worker DIED — plane breaker tripped; dispatch-time "
                "sync flushes cover until the recovery restarts it",
                self.THREAD_NAME,
            )
            # swallow rather than re-raise: the thread exits either way,
            # the death is recorded above, and an unhandled thread
            # exception would only add noise on top of the breaker trip

    def _warm_synthetic(self) -> None:
        """Pre-compile the scatter rungs at the slab's CURRENT shapes
        against throwaway zero banks — jit caches key on shapes/dtypes/
        placement, not buffers, so the later real flush hits the same
        executables. No lock held across the compiles; the generation is
        re-checked before recording the warm so a rebuild racing this
        pass simply warms again next tick."""
        with self._lock:
            gen = self.stage.generation
            host = {
                k: np.zeros_like(v)
                for k, v in self.stage.batch.arrays().items()
            }
        dev = {k: self._to_dev(v) for k, v in host.items()}
        cap = next(iter(host.values())).shape[0]
        seen = set()
        for rung in self.RUNGS:
            rb = min(rung, cap)
            if rb in seen:
                continue
            seen.add(rb)
            dev = self._scatter_rows(dev, host, [0] * rb, warm=True)
        with self._lock:
            if self.stage.generation == gen:
                self._warmed_generation = gen

    def restart_uploader(self) -> bool:
        """Fault-plane recovery (driver thread): restart a DEAD drain
        worker — exactly once per breaker trip by construction (the
        recovery queue drains once per trip; the next death is a fresh
        counted fault that must re-trip before anyone restarts again).
        The dirty backlog is flushed synchronously first so the new
        worker starts from a clean slate. Returns True if restarted."""
        with self._lock:
            w = self._worker
        if w is None or self._stop.is_set():
            return False
        if w.is_alive():
            # the trip is reported from the dying thread's except handler
            # BEFORE the thread has finished unwinding — a recovery that
            # runs promptly can observe it still alive. death_pending
            # disambiguates: join a dying thread briefly; never touch a
            # healthy one (it would block the driver for the timeout).
            with self._lock:
                dying = self._death_pending
            if not dying:
                return False
            w.join(timeout=2.0)
            if w.is_alive():
                return False  # pathological: try again on the next trip
        with self._lock:
            self._death_pending = False
            if self._dev is not None:
                self._flush_locked(sync=True)
            self.uploader_restarts += 1
        self.start()
        return True

    def resync(self) -> None:
        """Fault-plane recovery (driver thread): drop the device twin so
        the next flush takes the FULL-upload path — re-built from host
        truth via `_to_dev` placement (no new XLA programs; later dirty-
        row scatters land on the already-warmed rungs)."""
        with self._lock:
            self._dev = None

    # the staged banks' shadow-audit probe: like the mirror's
    # device_bank_divergence it is a debug/verification API that fetches
    # full arrays — a designated sync point, never a hot-path call
    # (checkers.repo_config sync_allowlist carries it)
    def device_divergence(self) -> List[str]:
        """Names of device-twin arrays NOT bit-identical to the host slab
        (dtype-canonicalized) — the ingest/terms half of the fault
        plane's probe gate. Flushes dirty rows first (driver thread): an
        un-flushed row is pipeline lag, not drift. Fetches go through a
        device-side copy (the mirror probe's discipline) so the probe
        never caches host views on live buffers."""
        import jax.numpy as jnp

        with self._lock:
            if self._dev is None:
                return []
            self._flush_locked(sync=True)
            host = self.stage.batch.arrays()
            dev = dict(self._dev)
            # only LIVE rows compare: release() frees host rows without
            # dirtying them — the device keeps stale content by design,
            # and no live (row, gen) pair can ever gather a freed row
            live = np.asarray(self.stage.live_rows_locked(), np.int64)
        out: List[str] = []
        for k, h in host.items():
            d = dev.get(k)
            if d is None:
                out.append(f"{self.LEDGER_KIND}.{k}:missing")
                continue
            dn = np.asarray(jnp.array(d, copy=True))
            hn = np.asarray(h)
            if dn.shape != hn.shape:
                out.append(f"{self.LEDGER_KIND}.{k}:shape")
                continue
            if live.size and not np.array_equal(
                dn[live], hn[live].astype(dn.dtype)
            ):
                out.append(f"{self.LEDGER_KIND}.{k}")
        return out

    def close(self) -> None:
        """Graceful shutdown: flush the dirty backlog synchronously (a
        clean close must not strand rows the uploader hadn't shipped —
        the device twin stays host-true to the last admission), then
        stop and join the worker with a bounded timeout. Idempotent."""
        try:
            with self._lock:
                if self._dev is not None and self.stage.dirty_rows:
                    self._flush_locked(sync=True)
        except Exception:
            pass  # a broken flush must not block shutdown
        self._stop.set()
        self._wake.set()
        with self._lock:
            w = self._worker
        if w is not None and w.is_alive():
            w.join(timeout=5)

    # -- dispatch-side API ---------------------------------------------------

    def current_arrays(self, sync: bool = True):
        """(bank_dev, empty_dev) with every dirty row flushed — the
        covered dispatch's gather inputs. Caller holds the stage lock (or
        relies on this RLock acquire) so the capture is atomic against
        admissions/rebuilds; the returned dicts are immutable snapshots."""
        with self._lock:
            self._flush_locked(sync=sync)
            return self._dev, self._empty_dev

    def gather_spec(self, u: int, capacity: Optional[int] = None) -> SolveSpec:
        """The index-gather prologue's XLA signature: u = index-vector
        rung, s = slab capacity, k/r = encoding widths."""
        return SolveSpec(
            kind=KIND_STAGE, u=u, s=capacity or self.stage.capacity,
            k=self.stage.key_capacity, r=self.stage.resource_capacity,
            config_repr="gather",
        )

    def census(self) -> Dict[str, object]:
        """Device-twin half of the slab census (obs/introspect): resident
        flag, the slab generation the device copy reflects, and the
        uploader's flush counters — shares the slab lock so the numbers
        are one consistent cut. Metadata only; never reads device
        buffers."""
        with self._lock:
            w = self._worker
            return {
                "resident": self._dev is not None,
                "device_generation": self._dev_generation,
                "warmed_generation": self._warmed_generation,
                "stats": dict(self.stats),
                # uploader liveness (census schema v2): a started-but-
                # dead worker is the stalled-uploader signal the health
                # monitor flags even with the fault plane disabled
                "uploader": {
                    "started": w is not None,
                    "alive": bool(w is not None and w.is_alive()),
                    "heartbeat_age_s": (
                        round(time.monotonic() - self._heartbeat_ts, 3)
                        if self._heartbeat_ts else None
                    ),
                    "restarts": self.uploader_restarts,
                    "last_error": self._last_uploader_error,
                },
            }

    def warm(self) -> int:
        """Pre-compile the staging scatter programs (each rung ≤ capacity)
        with idempotent no-op patches, after ensuring the bank is resident
        — the KIND_PATCH warm_patches discipline applied to staging. The
        gather prologue itself warms through WarmupService (KIND_STAGE
        gather specs at the live + headroom shapes)."""
        n = 0
        with self._lock:
            self._flush_locked(sync=True)
            host = self.stage.batch.arrays()
            seen = set()
            for rung in self.RUNGS:
                rb = min(rung, self.stage.capacity)
                if rb in seen:
                    continue
                seen.add(rb)
                self._dev = self._scatter_rows(
                    self._dev, host, [0] * rb, warm=True
                )
                n += 1
            self._warmed_generation = self.stage.generation
        return n
