"""Pod-ingest plane: enqueue-time pod encoding + device-resident pod banks.

The input-stream counterpart of the resident-state plane (PR 3): PRs 2-4
made the node/commit side of the cycle device-resident, but every batch
the driver thread still re-encoded each pod into its tensor row and
uploaded the padded pod-side arrays per dispatch — `encode_s` + the pod
half of the upload were front-half walls the commit pipeline's worker
could never hide. This package moves batch construction off the per-batch
critical path and off the wire:

* `stage`  — `PodStage`: a host-side slab of encoded pod-spec rows (the
  exact `state/tensors.PodBatch` layout), content-interned by `spec_key`
  and refcounted by queue entries. Rows are encoded ONCE, when the
  informer/queue admits the pod (on the informer thread), not per batch
  on the driver thread; the queue entry carries a ready (row, generation)
  pair instead of re-deriving the row at pop time.
* `bank`   — `StageBank`: the slab's device-resident twin, patched by
  dirty staged rows (batched, off-thread, double-buffered against the
  drain — the same discipline as the speculative fetch chain) through
  `compile/` as KIND_STAGE specs so staging never compiles mid-drain.
* `gather` — the index-only dispatch prologue: a jitted gather that
  reconstructs the batch's pod arrays FROM the resident bank on device;
  dispatch ships an int32 index vector + the small per-batch control
  scalars instead of the full pod-array set (`patch_bytes.pods` drops
  from the whole padded PodBatch to KB-scale on a covered drain).

Coverage is per batch: every popped pod must hold a valid staged row
whose generation matches (updates/deletes between enqueue and pop, slab
rebuilds, and width growth all invalidate). Anything else takes the
legacy host-built dispatch unchanged, observable via
`scheduler_ingest_batches_total{path}` — the plane is transport, never
policy, and placements are bit-identical either way (pinned by
tests/test_ingest_plane.py).
"""

from .bank import STAGE_RUNGS, StageBank
from .stage import PodStage

__all__ = ["PodStage", "StageBank", "STAGE_RUNGS"]
