"""Host-side pod staging slab: enqueue-time encoding into ready rows.

A `PodStage` is a `state/tensors.PodBatch` used as a SLAB with a row
allocator instead of a per-batch scratch buffer: rows are content-interned
by `spec_key` (replicas of one controller share ONE row, exactly like the
dispatch-time dedup and SigBank's `_encode_key` memo) and refcounted by
the queue entries that hold them. The expensive `set_pod` encode runs once
per distinct spec at ADMISSION time — on the informer thread — so the
driver's dispatch reduces to validating (row, generation) pairs and
shipping an index vector.

Generation discipline
---------------------
Every allocation and free stamps the row with a fresh value from one
monotone counter, and a slab rebuild (width growth, capacity growth)
restarts nothing — the counter keeps climbing, so ANY (row, gen) pair
issued before the event mismatches afterwards. A queue entry whose pair
went stale (its pod was updated/deleted between enqueue and pop, or the
slab rebuilt under it) is re-staged at dispatch time (counted) or falls
back to the legacy in-batch encode; correctness never depends on a row
being live.

Thread safety: one RLock around all bookkeeping. The driver's covered
dispatch holds it across validate → flush → gather-argument capture
(StageBank.prologue): device arrays are functional, so once the argument
dict is captured the lock can drop — a concurrent admission can neither
rewrite a captured device buffer nor swap the slab under the window.
Lock order where both are held: queue lock → stage lock (the queue
acquires rows under its own lock; the stage never calls into the queue).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..analysis.lockorder import audited_rlock
from ..state.tensors import KeySlotOverflow, PodBatch, spec_key

#: slab capacity floor and hard ceiling (pow-2 rungs in between). The slab
#: holds one row per DISTINCT pending spec — workload-bounded like
#: SigBank's signatures, not pod-count-bounded — so the ceiling is a
#: safety valve, not a sizing concern.
MIN_CAPACITY = 256
MAX_CAPACITY = 16384


class PodStage:
    """Content-interned, refcounted slab of encoded pod rows."""

    def __init__(self, vocab, capacity: int = MIN_CAPACITY):
        self.vocab = vocab
        self._lock = audited_rlock("stage")
        self._next_gen = 1  # ktpu: guarded-by(self._lock)
        # bank wake-up hook (StageBank sets it): called after a fresh row
        # is staged so the background uploader can batch it out
        self.on_dirty: Optional[callable] = None
        # bumped on every rebuild; the device twin (bank.StageBank) keys
        # its full-upload decision on it
        self.generation = 0  # ktpu: guarded-by(self._lock)
        # staleness counters (stale rows seen, dispatch-time restages)
        # live on the DRIVER's stats (ingest_stale_rows/ingest_restaged)
        # — the slab only counts what it owns
        # ktpu: guarded-by(self._lock)
        self.stats: Dict[str, int] = {
            "staged": 0,  # fresh rows encoded (once per distinct spec)
            "hits": 0,  # acquire served by an existing row
            "overflows": 0,  # slab-full growth events
            "rebuilds": 0,  # width-growth / capacity-growth rebuilds
        }
        self._build(max(capacity, MIN_CAPACITY))

    # -- slab lifecycle ------------------------------------------------------

    # ktpu: holds(self._lock) callers: __init__ (pre-concurrency) and the
    # locked acquire/ensure_current/_rebuild paths
    def _build(self, capacity: int) -> None:
        self.capacity = capacity  # ktpu: guarded-by(self._lock)
        self.batch = PodBatch(self.vocab, capacity)  # ktpu: guarded-by(self._lock)
        self.key_capacity = self.batch.key_capacity
        self.resource_capacity = self.batch.req.shape[1]
        self.row_of: Dict[tuple, int] = {}  # ktpu: guarded-by(self._lock)
        self._key_of_row: Dict[int, tuple] = {}  # ktpu: guarded-by(self._lock)
        self.refs = np.zeros(capacity, np.int64)  # ktpu: guarded-by(self._lock)
        self.row_gen = np.zeros(capacity, np.int64)  # ktpu: guarded-by(self._lock) gen 0 = never issued
        self._free: List[int] = list(range(capacity - 1, -1, -1))  # ktpu: guarded-by(self._lock)
        self.dirty_rows: set = set()  # ktpu: guarded-by(self._lock)
        self.generation += 1
        # the legacy PodBatch's zero-state per array, for gather padding:
        # padding rows of the index dispatch must reproduce EXACTLY what
        # an untouched PodBatch row holds (-1 pads on selector/term slots,
        # zeros elsewhere) or the device programs stop being bit-identical
        self.empty_rows = PodBatch(self.vocab, 1).arrays()

    # ktpu: holds(self._lock) called from acquire/ensure_current only
    def _rebuild(self, capacity: Optional[int] = None) -> None:
        self.stats["rebuilds"] += 1
        self._build(capacity or self.capacity)

    def current_for(self, vocab) -> bool:
        """Do the slab's array widths still match the vocab's config? A
        key-slot or resource-slot growth (mirror rebuild territory) makes
        every staged row the wrong SHAPE — the slab must rebuild."""
        return (
            vocab is self.vocab
            and self.key_capacity == vocab.config.key_slots
            and self.resource_capacity == vocab.config.resource_slots
        )

    def ensure_current(self) -> bool:
        """Rebuild if the vocab widths grew. Returns True when a rebuild
        happened (every outstanding (row, gen) pair is now stale)."""
        with self._lock:
            if self.current_for(self.vocab):
                return False
            self._rebuild()
            return True

    # -- row acquisition -----------------------------------------------------

    def acquire(self, pod) -> Optional[Tuple[int, int]]:
        """Intern `pod`'s spec row (+1 ref). Returns (row, gen), or None
        when the pod cannot be staged right now (encode overflow mid-vocab-
        growth) — the caller schedules it via the legacy path and retries
        staging on the next admission. Slab-capacity overflow GROWS the
        slab (pow-2 rung, through compile/'s KIND_STAGE headroom warming)
        rather than failing: the rebuild invalidates outstanding rows
        (one legacy batch at worst, counted) and staging resumes covered."""
        with self._lock:
            if not self.current_for(self.vocab):
                self._rebuild()
            key = spec_key(pod)
            row = self.row_of.get(key)
            if row is not None:
                self.refs[row] += 1
                self.stats["hits"] += 1
                return row, int(self.row_gen[row])
            if not self._free:
                self.stats["overflows"] += 1
                if self.capacity >= MAX_CAPACITY:
                    return None  # safety valve: legacy path absorbs it
                self._rebuild(self.capacity * 2)
            row = self._free.pop()
            try:
                self.batch.set_pod(row, pod)
            except KeySlotOverflow:
                # vocab grew mid-encode: widths changed under us — rebuild
                # (fresh widths) and let the caller's next admission stage
                self._free.append(row)
                self._rebuild()
                return None
            self.row_of[key] = row
            self._key_of_row[row] = key
            self.refs[row] = 1
            gen = self._next_gen
            self._next_gen += 1
            self.row_gen[row] = gen
            self.dirty_rows.add(row)
            self.stats["staged"] += 1
            cb = self.on_dirty
            if cb is not None:
                cb()  # Event.set — safe under the lock
            return row, gen

    def ensure_row(self, pod) -> Optional[Tuple[int, int]]:
        """Intern `pod`'s spec row WITHOUT taking a reference — the
        dispatch-time restage path (a popped entry whose staged pair went
        stale, or a pod admitted before the plane attached). Same contract
        as SigBank.prepare_row: a fresh zero-ref row is never freed by
        release() (no holder can release it), so it stays valid through
        the dispatch and lingers until a slab rebuild reclaims it —
        bounded by slab capacity. Returns (row, gen) or None exactly like
        acquire()."""
        with self._lock:
            pair = self.acquire(pod)
            if pair is None:
                return None
            row, gen = pair
            # undo acquire's ref without triggering the free path: a
            # fresh row drops to 0 (lingers, by contract); an existing
            # row returns to its holders' count
            self.refs[row] -= 1
            if self.refs[row] < 0:
                self.refs[row] = 0
            return pair

    def release(self, row: int, gen: int) -> None:
        """Drop one reference. Frees the row (generation bump) at zero —
        a later acquire of the same spec re-encodes. Stale pairs are
        ignored (the row they named is already gone)."""
        with self._lock:
            if not (0 <= row < self.capacity) or self.row_gen[row] != gen:
                return
            self.refs[row] -= 1
            if self.refs[row] <= 0:
                self.refs[row] = 0
                key = self._key_of_row.pop(row, None)
                if key is not None:
                    self.row_of.pop(key, None)
                self.batch.valid[row] = False
                self.row_gen[row] = self._next_gen
                self._next_gen += 1
                self._free.append(row)
                # freed host rows are never gathered (no live (row, gen)
                # names them), so the device twin needs no update

    # ktpu: holds(self._lock) callers hold the slab lock (StageBank's
    # device_divergence probe)
    def live_rows_locked(self) -> List[int]:
        """Row indices currently ALLOCATED (not on the free list) — the
        only rows the gather can ever read, and therefore the only rows
        the device-twin parity probe may compare: release() frees host
        rows without dirtying them (the device keeps stale content by
        design, doc above)."""
        free = set(self._free)
        return [r for r in range(self.capacity) if r not in free]

    def valid_pair(self, row: int, gen: int) -> bool:
        with self._lock:
            return 0 <= row < self.capacity and self.row_gen[row] == gen

    def census(self) -> Dict[str, object]:
        """One lock-disciplined snapshot of the slab's steady-state
        health (obs/introspect): occupancy, free-list depth, outstanding
        refcounts, dirty (not-yet-shipped) rows, and the lifetime stats.
        Counters and metadata only — never touches the row arrays."""
        with self._lock:
            return {
                "enabled": True,
                "capacity": int(self.capacity),
                "rows": int(self.capacity - len(self._free)),
                "free_rows": len(self._free),
                "refs_total": int(self.refs.sum()),
                "dirty_rows": len(self.dirty_rows),
                "generation": int(self.generation),
                "next_gen": int(self._next_gen),
                "stats": dict(self.stats),
            }
