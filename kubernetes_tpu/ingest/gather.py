"""Index-only dispatch prologue: rebuild a batch's pod arrays ON DEVICE.

One jitted gather reconstructs the exact per-batch PodBatch array dict the
solve/gang/arbiter programs consume, from the resident staged bank and an
int32 index vector — the only pod-side payload a covered dispatch ships.
Padding rows reproduce an untouched PodBatch row bit-for-bit (`empty` is
the slab's 1-row zero-state: -1 pads on selector/term slots, zeros
elsewhere), so the downstream programs see EXACTLY what the legacy
host-built upload would have produced — placements are bit-identical by
construction, which the parity suite pins.

`fallback` is uploaded host-side (a [U] bool, bytes not KB): the
effective per-spec fallback is staged-row overflow OR batch term-table
overflow, and the term half only exists at dispatch time.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


# ktpu: admitted(KIND_STAGE) every dispatch site (driver._stage_prologue,
# WarmupService._warm_stage) admits the (u, slab-structure) pair through
# compile_plan.admit as a KIND_STAGE spec before calling — the program is
# planned even though the jit wrapper lives here
@jax.jit
def gather_stage(bank, idx, keep, empty, fallback):
    """bank: staged slab dict ([S, ...]); idx: [U] int32 slab rows;
    keep: [U] bool (True for real batch specs, False for padding);
    empty: 1-row PodBatch dict (the padding template); fallback: [U] bool
    (host-computed effective fallback). Returns the batch's pod-array
    dict, [U, ...]."""
    out = {}
    for k, v in bank.items():
        g = v[idx]
        cond = keep.reshape((-1,) + (1,) * (g.ndim - 1))
        out[k] = jnp.where(cond, g, empty[k])
    out["fallback"] = fallback
    return out
