"""Multi-host (DCN) scale-out for the sharded solve.

SURVEY §2.4: the reference's control plane scales across machines through
the apiserver's watch fan-out; the TPU build's analogue is sharding the
NODE axis of the solve across every chip of every host. Within a host the
solver's election collectives ride ICI; across hosts they ride DCN. The
layout is deliberately node-major:

  * per-node state (bank rows, residual carry columns, signature/pattern
    count rows) lives on exactly ONE chip of ONE host — residual updates
    and acceptance prefix sums never cross a link;
  * the only cross-host traffic per chunk-repair iteration is the [K]-wide
    pmax/pmin election reductions (ops are identical over ICI and DCN —
    XLA routes them), tens of rounds per 1024-pod batch;
  * the host-side driver runs on process 0 (the elected leader,
    utils.leaderelection); follower processes run the same program under
    jax.distributed and participate only in collectives, mirroring the
    reference's active-passive scheduler replicas (leaderelection.go:197)
    with the ACTIVE computation data-parallel over every host's chips.

This module only wires jax.distributed + the mesh; the pipeline itself is
parallel.sharded.make_sharded_pipeline, which is mesh-shape agnostic.
"""

from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import Mesh

from .mesh import node_mesh


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    autodetect: bool = False,
) -> int:
    """Initialize the JAX distributed runtime (DCN) and return this
    process's id. Explicit coordinator/process arguments initialize a
    fixed-size cluster; `autodetect=True` defers to JAX's standard
    cluster-environment detection (TPU pod metadata, SLURM, ...). The
    default — no arguments — is a deliberate single-process no-op so local
    runs and tests need no cluster environment."""
    if autodetect:
        jax.distributed.initialize()
    elif num_processes is not None and num_processes > 1:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    return jax.process_index()


def multihost_node_mesh(pods_axis: int = 1) -> Mesh:
    """Mesh over EVERY device of every connected host — a thin alias of
    mesh.node_mesh, which gives the pods axis the consecutive (same-host)
    devices so its [B, N] gathers stay intra-host/ICI, while the node axis
    strides across hosts and only its tiny election reductions cross DCN.
    Node capacity
    (state/tensors._node_bucket: power of two up to 2048, multiples of
    2048 above) divides any power-of-two total shard count."""
    return node_mesh(pods_parallel=pods_axis)
