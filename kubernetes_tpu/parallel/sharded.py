"""Multi-chip solve: the scheduling cycle sharded over the node axis.

Two cooperating pieces (SURVEY §2.4 "TPU-native equivalent"):

1. **Mask/score stage — GSPMD.** The Filter/Score/topology kernels
   (ops/filters.py, ops/scores.py, ops/topology.py) are column-parallel
   over nodes: every [B, N] matrix is computed under a
   `with_sharding_constraint` that pins the node axis to the mesh's
   "nodes" axis (and optionally the batch axis to "pods"), and XLA's SPMD
   partitioner inserts the few collectives the topology kernels need
   (per-topology-value segment sums, min/max normalizations). This is the
   idiomatic pjit recipe: annotate, let the compiler place psum/all-gather.

2. **Greedy commit stage — explicit shard_map.** The sequential
   pod-by-pod commit (reference scheduleOne order, one pod's residual
   update visible to the next) keeps per-node residuals SHARD-LOCAL and
   pays exactly two tiny collectives per pod: a pmax to find the global
   best score and a pmin to elect the winning (shard, node) — an argmax
   over ICI. The winning shard alone updates its residual rows. Bit-for-bit
   identical to ops/solver.solve_greedy on one device (parity-tested in
   tests/test_parallel.py), including the selectHost random tie-break
   (core/generic_scheduler.go:278): the tie-break noise is generated from
   the same per-step PRNG keys and sliced per shard.

Node capacity is a power of two up to 2048 and a multiple of 2048 above
(state/tensors._node_bucket), so any power-of-two shard count up to 2048
divides it; no repadding is needed.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.pipeline import SolveConfig, _pod_axis, mask_and_score
from ..ops.solver import pop_order, tie_noise
from .mesh import AXIS_NODES, AXIS_PODS

Arrays = Dict[str, jnp.ndarray]

_BIG = 2**30


def _solver_body(
    mask: jnp.ndarray,  # [U, Nl] local node columns (spec rows)
    score: jnp.ndarray,  # [U, Nl]
    req: jnp.ndarray,  # [U, R] replicated
    free: jnp.ndarray,  # [Nl, R] shard-local residuals
    count: jnp.ndarray,  # [Nl]
    allowed: jnp.ndarray,  # [Nl]
    order: jnp.ndarray,  # [B] replicated scan order
    noise: jnp.ndarray,  # [B, Nl] tie-break noise (or [B, 1] dummy)
    req_any: jnp.ndarray,  # [U] replicated
    sig: jnp.ndarray,  # [B] pod → spec row, replicated
    pod_valid: jnp.ndarray,  # [B] replicated
    *,
    deterministic: bool,
    n_local: int,
) -> jnp.ndarray:
    """shard_map body: the greedy scan with cross-shard argmax election."""
    shard = jax.lax.axis_index(AXIS_NODES)
    base = (shard * n_local).astype(jnp.int32)

    def step(carry, inp):
        free, count = carry
        i, nz = inp
        s = sig[i]
        m = mask[s] & pod_valid[i]
        # PodFitsResources against the residual carry (predicates.go:854
        # semantics: count always, resource rows only when requested)
        res_ok = ~req_any[s] | jnp.all(req[s][None, :] <= free, axis=-1)
        feasible = m & res_ok & (count + 1 <= allowed)
        neg = jnp.iinfo(score.dtype).min
        masked = jnp.where(feasible, score[s], neg)
        local_best = jnp.max(masked)
        global_best = jax.lax.pmax(local_best, AXIS_NODES)
        any_feasible = jax.lax.pmax(jnp.any(feasible), AXIS_NODES)
        if deterministic:
            # first global max == smallest global index among shard maxima
            gidx = jnp.where(
                local_best == global_best, base + jnp.argmax(masked).astype(jnp.int32), _BIG
            )
        else:
            # selectHost: uniform among max-score nodes — max noise wins
            ties = feasible & (masked == global_best)
            nzm = jnp.where(ties, nz, -1.0)
            local_nbest = jnp.max(nzm)
            global_nbest = jax.lax.pmax(local_nbest, AXIS_NODES)
            gidx = jnp.where(
                (local_nbest == global_nbest) & jnp.any(ties),
                base + jnp.argmax(nzm).astype(jnp.int32),
                _BIG,
            )
        choice = jax.lax.pmin(gidx, AXIS_NODES)
        choice = jnp.where(any_feasible, choice, -1)
        committed = choice >= 0
        mine = committed & (choice >= base) & (choice < base + n_local)
        sel = jnp.where(mine, choice - base, 0)
        free = jnp.where(mine, free.at[sel].add(-req[s]), free)
        count = jnp.where(mine, count.at[sel].add(1), count)
        return (free, count), choice

    (_, _), choices = jax.lax.scan(step, (free, count), (order, noise))
    return choices.astype(jnp.int32)


def make_sharded_pipeline(mesh: Mesh):
    """Build the jitted multi-chip pipeline bound to `mesh`.

    Same signature/result contract as ops.pipeline.solve_pipeline:
    (na, pa, ea, ta, xa, au, ids, key, deterministic) → (assign [B],
    score [B, N]).
    """
    n_shards = mesh.shape[AXIS_NODES]

    def _c(x: jnp.ndarray, *spec) -> jnp.ndarray:
        return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P(*spec)))

    @partial(jax.jit, static_argnames=("deterministic", "config", "term_kinds"))
    def pipeline(
        na: Arrays, pa: Arrays, ea: Arrays, ta: Arrays, xa: Arrays,
        au: Arrays, ids: Arrays, key, pb: Arrays = None,
        deterministic: bool = False,
        config: "SolveConfig" = None, term_kinds=None,
    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        N = na["valid"].shape[0]
        assert N % n_shards == 0, f"node capacity {N} not divisible by {n_shards} shards"
        n_local = N // n_shards
        # pin every per-node bank array's leading axis to the mesh
        na = {k: _c(v, AXIS_NODES) for k, v in na.items()}
        # the signature-count matrix is node-major [N, S]: shard its node
        # axis too (signature metadata stays replicated — it is tiny); the
        # [T,S]x[S,N] count matmuls then produce node-sharded outputs
        if "counts" in ea:
            ea = {**ea, "counts": _c(ea["counts"], AXIS_NODES)}
        # mask/score compute (shared stage — identical math to the
        # single-device pipelines): nodes sharded, batch data-parallel
        mask, score = mask_and_score(na, pa, ea, ta, xa, au, ids, config, term_kinds)
        mask = _c(mask, AXIS_PODS, AXIS_NODES)
        score = _c(score, AXIS_PODS, AXIS_NODES)
        # the greedy commit is a strict sequential order over the whole
        # batch: gather the batch axis, keep nodes sharded
        mask = _c(mask, None, AXIS_NODES)
        score = _c(score, None, AXIS_NODES)

        free0 = na["alloc"] - na["requested"]
        count0 = na["pod_count"].astype(free0.dtype)
        allowed = na["allowed_pods"].astype(free0.dtype)
        sig, pvalid, prio, b = _pod_axis(pa, pb)
        if sig is None:
            sig = jnp.arange(b, dtype=jnp.int32)
        order = pop_order(prio, jnp.arange(b, dtype=jnp.int32), pvalid)
        if deterministic:
            noise = jnp.zeros((b, n_shards))
        else:
            # bit-identical to the single-device solve_greedy stream:
            # per-step keys, full-width uniform rows, sliced per shard
            noise = tie_noise(key, b, N)
        solver = jax.shard_map(
            partial(_solver_body, deterministic=deterministic, n_local=n_local),
            mesh=mesh,
            in_specs=(
                P(None, AXIS_NODES),  # mask
                P(None, AXIS_NODES),  # score
                P(),                  # req
                P(AXIS_NODES),        # free0
                P(AXIS_NODES),        # count0
                P(AXIS_NODES),        # allowed
                P(),                  # order
                P(None, AXIS_NODES),  # noise
                P(),                  # req_any
                P(),                  # sig
                P(),                  # pod_valid
            ),
            out_specs=P(),
        )
        choices = solver(
            mask, score, pa["req"], free0, count0, allowed, order, noise,
            pa["req_any"], sig, pvalid,
        )
        assign = jnp.full((b,), -1, jnp.int32).at[order].set(choices)
        return assign, score

    return pipeline
