"""Multi-chip solve: the scheduling cycle sharded over the node axis.

Two cooperating pieces (SURVEY §2.4 "TPU-native equivalent"):

1. **Mask/score stage — GSPMD.** The Filter/Score/topology kernels
   (ops/filters.py, ops/scores.py, ops/topology.py) are column-parallel
   over nodes: every [B, N] matrix is computed under a
   `with_sharding_constraint` that pins the node axis to the mesh's
   "nodes" axis (and optionally the batch axis to "pods"), and XLA's SPMD
   partitioner inserts the few collectives the topology kernels need
   (per-topology-value segment sums, min/max normalizations). This is the
   idiomatic pjit recipe: annotate, let the compiler place psum/all-gather.

2. **Greedy commit stage — explicit shard_map.** The chunked
   prefix-acceptance commit (ops/solver.solve_greedy's algorithm,
   bit-identical to sequential pod-by-pod order) keeps per-node residuals
   SHARD-LOCAL; each repair iteration elects every chunk pod's winning
   (shard, node) with a handful of [K]-wide pmax/pmin collectives over
   ICI and reduces the first locally-rejected order index, so a 1024-pod
   batch pays ~tens of collective rounds instead of three per pod.
   Acceptance prefix sums are shard-local because a node lives on exactly
   one shard. Bit-for-bit identical to ops/solver.solve_greedy on one
   device (parity-tested in tests/test_parallel.py), including the
   selectHost random tie-break (core/generic_scheduler.go:278): the
   tie-break noise comes from the shared tie_noise stream, sliced per
   shard.

Node capacity is a power of two up to 2048 and a multiple of 2048 above
(state/tensors._node_bucket), so any power-of-two shard count up to 2048
divides it; no repadding is needed.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.pipeline import SolveConfig, _pod_axis, apply_carry, mask_and_score
from ..ops.solver import DEFAULT_CHUNK, pop_order, tie_noise
from .mesh import AXIS_NODES, AXIS_PODS, shard_map

Arrays = Dict[str, jnp.ndarray]

_BIG = 2**30


def _solver_body(
    mask: jnp.ndarray,  # [U, Nl] local node columns (spec rows)
    score: jnp.ndarray,  # [U, Nl]
    req: jnp.ndarray,  # [U, R] replicated
    free: jnp.ndarray,  # [Nl, R] shard-local residuals
    count: jnp.ndarray,  # [Nl]
    allowed: jnp.ndarray,  # [Nl]
    order: jnp.ndarray,  # [B] replicated scan order
    noise: jnp.ndarray,  # [B, Nl] tie-break noise (or [B, 1] dummy)
    req_any: jnp.ndarray,  # [U] replicated
    sig: jnp.ndarray,  # [B] pod → spec row, replicated
    pod_valid: jnp.ndarray,  # [B] replicated
    nzacc: jnp.ndarray,  # [Nl, 2] shard-local non-zero scoring accumulators
    scoring_req: jnp.ndarray,  # [U, 2] replicated
    inb: "Optional[dict]" = None,  # in-batch anti/port tracking (see below)
    *,
    deterministic: bool,
    n_local: int,
    n_shards: int = 1,
):
    """shard_map body: chunked prefix-acceptance greedy (the multi-chip
    twin of ops.solver.solve_greedy, bit-identical results). Pods are
    processed in chunks; each repair iteration pays a handful of [K]-wide
    collectives — global (score, noise) argmax election plus a pmin over
    the first locally-rejected order index — instead of the former three
    collectives per POD."""
    shard = jax.lax.axis_index(AXIS_NODES)
    base = (shard * n_local).astype(jnp.int32)
    B = order.shape[0]
    K = min(DEFAULT_CHUNK, B)
    if B % K:
        K = B
    n_chunks = B // K
    neg = jnp.iinfo(score.dtype).min
    jrange = jnp.arange(K)
    order_c = jnp.reshape(order, (n_chunks, K))
    noise_c = jnp.reshape(noise, (n_chunks, K, noise.shape[-1]))
    track = inb is not None
    if track:
        # in-batch anti/port sequentialization on the mesh (the multi-chip
        # twin of ops.solver.solve_greedy's inb contract). The commit-count
        # tables ca/cb [TT, V] are REPLICATED (updates are pure functions
        # of replicated commit data once the winning node's topology bucket
        # is broadcast from its owner shard); cs [U, Nl] and the per-node
        # bucket/haskey columns are shard-local.
        t_anti = inb["anti"]
        t_owner = inb["owner"]
        m_bb = inb["m_bb"] & t_anti[:, None]  # [TT, U] replicated
        bucket_nl = inb["bucket_n"]  # [TT, Nl] local columns
        haskey_nl = inb["haskey_n"]  # [TT, Nl]
        pconf = inb["port_conflict"]  # [U, U] replicated
        ca0, cb0, cs0 = inb["ca0"], inb["cb0"], inb["cs0"]
        TT = t_anti.shape[0]
        t_rows = jnp.arange(TT, dtype=jnp.int32)[:, None]
        Vb = ca0.shape[1]
    else:
        _z = jnp.zeros((1, 1), jnp.float32)
        ca0 = cb0 = _z
        cs0 = jnp.zeros((1, n_local), jnp.float32)

    def chunk_step(carry, inp):
        free, count, nza, ca, cb, cs = carry
        idx, nz = inp  # [K] pod positions; [K, Nl] local noise columns
        sg = sig[idx]
        pv = pod_valid[idx]
        m_r = mask[sg] & pv[:, None]  # [K, Nl]
        s_r = score[sg]
        r_q = req[sg]  # [K, R]
        r_any = req_any[sg]
        s_q = scoring_req[sg]  # [K, 2]
        if track:
            ownK = (t_owner[None, :] == sg[:, None]) & t_anti[None, :]  # [K, TT]
            mbbK = m_bb[:, sg].T  # [K, TT]
            pconfK = pconf[sg].astype(jnp.float32)  # [K, U]

        def not_done(st):
            return ~jnp.all(st[6])

        def body(st):
            free, count, nza, ca, cb, cs, decided, choice = st
            res_ok = (~r_any[:, None]) | jnp.all(
                r_q[:, None, :] <= free[None, :, :], axis=-1
            )
            feas = m_r & res_ok & (count[None, :] + 1 <= allowed[None, :])
            if track:
                hp = jax.lax.Precision.HIGHEST
                ca_pos = ((jnp.take_along_axis(ca, bucket_nl, axis=1) > 0) & haskey_nl)
                cb_pos = ((jnp.take_along_axis(cb, bucket_nl, axis=1) > 0) & haskey_nl)
                blockA = jnp.matmul(
                    ownK.astype(jnp.float32), ca_pos.astype(jnp.float32), precision=hp
                ) > 0.5
                blockB = jnp.matmul(
                    mbbK.astype(jnp.float32), cb_pos.astype(jnp.float32), precision=hp
                ) > 0.5
                blockP = jnp.matmul(
                    pconfK, (cs > 0).astype(jnp.float32), precision=hp
                ) > 0.5
                feas = feas & ~(blockA | blockB | blockP)
            feas = feas & ~decided[:, None]
            anyf = jax.lax.pmax(jnp.any(feas, axis=1), AXIS_NODES)  # [K]
            masked = jnp.where(feas, s_r, neg)
            local_best = jnp.max(masked, axis=1)  # [K]
            global_best = jax.lax.pmax(local_best, AXIS_NODES)
            if deterministic:
                # first global max == smallest global index among shard maxima
                gidx = jnp.where(
                    local_best == global_best,
                    base + jnp.argmax(masked, axis=1).astype(jnp.int32),
                    _BIG,
                )
            else:
                # selectHost: uniform among max-score nodes — max noise wins
                ties = feas & (masked == global_best[:, None])
                nzm = jnp.where(ties, nz, -1.0)
                local_nbest = jnp.max(nzm, axis=1)
                global_nbest = jax.lax.pmax(local_nbest, AXIS_NODES)
                gidx = jnp.where(
                    (local_nbest == global_nbest) & jnp.any(ties, axis=1),
                    base + jnp.argmax(nzm, axis=1).astype(jnp.int32),
                    _BIG,
                )
            cand = jnp.where(anyf, jax.lax.pmin(gidx, AXIS_NODES), -1)  # [K] global
            newly_none = ~decided & ~anyf
            active = ~decided & (cand >= 0)
            local = active & (cand >= base) & (cand < base + n_local)
            lidx = jnp.where(local, cand - base, 0)
            # per-node in-order prefix among pods choosing THIS shard's nodes
            # (a node lives on exactly one shard, so acceptance is local)
            same = (
                local[:, None]
                & local[None, :]
                & (cand[:, None] == cand[None, :])
                & (jrange[None, :] < jrange[:, None])
            )
            # broadcast-sum, not matmul: an s64 dot has no TPU x64 rewrite
            prefix_req = jnp.sum(same[:, :, None] * r_q[None, :, :], axis=1)
            prefix_cnt = jnp.sum(same, axis=1)
            fits = (
                (~r_any) | jnp.all(r_q <= free[lidx] - prefix_req, axis=-1)
            ) & (count[lidx] + prefix_cnt + 1 <= allowed[lidx])
            rejected = local & ~fits
            first_rej = jax.lax.pmin(
                jnp.min(jnp.where(rejected, jrange, K)), AXIS_NODES
            )
            commit = active & (jrange < first_rej)
            if track:
                # scatter-min commit barrier (ops/solver.py contract, multi-
                # chip twin): every candidate's topology bucket + haskey bit
                # is pmax-broadcast from its node's owner shard, then the
                # replicated min-candidate-index tables truncate at the
                # first pod an earlier in-round commit could affect
                cand_ok = active & (jrange < first_rej)
                lidx3 = jnp.where(local & cand_ok, lidx, 0)
                bK = jnp.where(
                    (local & cand_ok)[None, :], bucket_nl[:, lidx3], -1
                )  # [TT, K] local half
                bK = jax.lax.pmax(bK, AXIS_NODES)  # owner shard wins
                hkK = jax.lax.pmax(
                    haskey_nl[:, lidx3] & (local & cand_ok)[None, :], AXIS_NODES
                )
                contrib = m_bb[:, sg] & hkK
                ownk_t = ownK.T & hkK
                idxK = jnp.broadcast_to(jrange[None, :], bK.shape).astype(jnp.int32)
                TT = bK.shape[0]
                mi_contrib = jnp.full((TT, Vb), K, jnp.int32).at[
                    t_rows, jnp.where(contrib, bK, Vb)
                ].min(idxK, mode="drop")
                mi_own = jnp.full((TT, Vb), K, jnp.int32).at[
                    t_rows, jnp.where(ownk_t, bK, Vb)
                ].min(idxK, mode="drop")
                g_contrib = jnp.take_along_axis(
                    mi_contrib, jnp.where(hkK, bK, 0), axis=1
                )
                g_own = jnp.take_along_axis(mi_own, jnp.where(hkK, bK, 0), axis=1)
                blockA_j = jnp.any(ownk_t & (g_contrib < jrange[None, :]), axis=0)
                blockB_j = jnp.any(contrib & (g_own < jrange[None, :]), axis=0)
                U_ = mask.shape[0]
                n_total = n_local * n_shards
                cg = jnp.where(cand_ok, cand, 0)
                mi_sn = jnp.full((U_, n_total), K, jnp.int32).at[
                    jnp.where(cand_ok, sg, U_), cg
                ].min(jnp.where(cand_ok, jrange, K).astype(jnp.int32), mode="drop")
                g_sn = mi_sn[:, cg]  # [U, K]
                blockP_j = jnp.any(
                    (pconfK.T > 0.5) & (g_sn < jrange[None, :]), axis=0
                )
                blocked = cand_ok & (blockA_j | blockB_j | blockP_j)
                first_block = jnp.min(jnp.where(blocked, jrange, K))
                commit = commit & (jrange < first_block)
            mine = commit & local
            target = jnp.where(mine, lidx, n_local)
            free = free.at[target].add(-(mine[:, None] * r_q), mode="drop")
            count = count.at[target].add(mine.astype(count.dtype), mode="drop")
            nza = nza.at[target].add(mine[:, None] * s_q, mode="drop")
            if track:
                # broadcast each committed pod's topology bucket (and the
                # haskey bit) from the shard that owns its node, then apply
                # the replicated ca/cb updates identically on every shard
                bc_local = jnp.where(
                    mine[None, :], bucket_nl[:, jnp.where(mine, lidx, 0)], -1
                )  # [TT, K]
                bc_g = jax.lax.pmax(bc_local, AXIS_NODES)
                hk_local = haskey_nl[:, jnp.where(mine, lidx, 0)] & mine[None, :]
                hk_g = jax.lax.pmax(hk_local, AXIS_NODES)
                one = jnp.float32(1.0)
                ca = ca.at[
                    t_rows, jnp.where(m_bb[:, sg] & hk_g & commit[None, :], bc_g, Vb)
                ].add(one, mode="drop")
                cb = cb.at[
                    t_rows, jnp.where(ownK.T & hk_g & commit[None, :], bc_g, Vb)
                ].add(one, mode="drop")
                cs = cs.at[
                    jnp.where(mine, sg, mask.shape[0]), jnp.where(mine, lidx, 0)
                ].add(one, mode="drop")
            choice = jnp.where(commit, cand, choice)
            decided = decided | commit | newly_none
            return free, count, nza, ca, cb, cs, decided, choice

        decided0 = ~pv
        choice0 = jnp.full((K,), -1, jnp.int32)
        free, count, nza, ca, cb, cs, _, choice = jax.lax.while_loop(
            not_done, body, (free, count, nza, ca, cb, cs, decided0, choice0)
        )
        return (free, count, nza, ca, cb, cs), choice

    (free_f, count_f, nz_f, _, _, _), choices = jax.lax.scan(
        chunk_step, (free, count, nzacc, ca0, cb0, cs0), (order_c, noise_c)
    )
    return jnp.reshape(choices, (B,)).astype(jnp.int32), free_f, count_f, nz_f


_PIPELINE_CACHE: Dict[Mesh, object] = {}


# ktpu: admitted(KIND_SOLVE) every program built here is dispatched via
# SolveSpec(shards=...) rungs the warmup realizes through this same
# memoized factory — see driver._solve_spec and WarmupService._banks_for
def make_sharded_pipeline(mesh: Mesh):
    """Build the jitted multi-chip pipeline bound to `mesh`.

    Memoized per mesh (Mesh hashes by device grid + axis names): the
    jitted closures ARE the XLA program cache, so two schedulers — or a
    warmup service and the driver it warms — must share one instance or
    every warm compiles a program the dispatch never finds.

    Full signature/result parity with ops.pipeline.solve_pipeline —
    (na, pa, ea, ta, xa, au, ids, key, pb=None, carry=None,
    deterministic=False, config=None, term_kinds=None, n_buckets=None,
    return_carry=False) → (assign [B], score [U, N]) or
    (assign, score, carry_out) — so the production driver can route
    _dispatch_solve through it unchanged, speculative carry included.
    The carry's free/count/nz residuals stay node-SHARDED on device
    between batches (they never cross to the host)."""
    cached = _PIPELINE_CACHE.get(mesh)
    if cached is not None:
        return cached
    n_shards = mesh.shape[AXIS_NODES]

    def _c(x: jnp.ndarray, *spec) -> jnp.ndarray:
        return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P(*spec)))

    def _prep(na, pa, ea, ta, xa, au, ids, key, pb, carry,
              deterministic, config, term_kinds, n_buckets,
              track_inbatch=False):
        N = na["valid"].shape[0]
        assert N % n_shards == 0, f"node capacity {N} not divisible by {n_shards} shards"
        n_local = N // n_shards
        # pin every per-node bank array's leading axis to the mesh
        na = {k: _c(v, AXIS_NODES) for k, v in na.items()}
        if carry is not None:
            # speculative pipelining (ops/pipeline.apply_carry contract,
            # with the residuals pinned to their node shards)
            carry = tuple(_c(x, AXIS_NODES) for x in carry)
            na = apply_carry(na, carry)
        # the signature-count matrix is node-major [N, S]: shard its node
        # axis too (signature metadata stays replicated — it is tiny); the
        # [T,S]x[S,N] count matmuls then produce node-sharded outputs
        if "counts" in ea:
            ea = {**ea, "counts": _c(ea["counts"], AXIS_NODES)}
        # mask/score compute (shared stage — identical math to the
        # single-device pipelines): nodes sharded, batch data-parallel
        mask, score = mask_and_score(na, pa, ea, ta, xa, au, ids, config,
                                     term_kinds, n_buckets)
        mask = _c(mask, AXIS_PODS, AXIS_NODES)
        score = _c(score, AXIS_PODS, AXIS_NODES)
        # the greedy commit is a strict sequential order over the whole
        # batch: gather the batch axis, keep nodes sharded
        mask = _c(mask, None, AXIS_NODES)
        score = _c(score, None, AXIS_NODES)

        free0 = na["alloc"] - na["requested"]
        count0 = na["pod_count"].astype(free0.dtype)
        allowed = na["allowed_pods"].astype(free0.dtype)
        nz0 = na["nonzero_req"].astype(free0.dtype)
        sig, pvalid, prio, b = _pod_axis(pa, pb)
        if sig is None:
            sig = jnp.arange(b, dtype=jnp.int32)
        order = pop_order(prio, jnp.arange(b, dtype=jnp.int32), pvalid)
        if deterministic:
            noise = jnp.zeros((b, n_shards))
        else:
            # bit-identical to the single-device solve_greedy stream: the
            # counter-based tie_noise is a pure function of (key, row,
            # global column), so each shard holds exactly its columns
            noise = tie_noise(key, b, N)
        base_specs = (
            P(None, AXIS_NODES),  # mask
            P(None, AXIS_NODES),  # score
            P(),                  # req
            P(AXIS_NODES),        # free0
            P(AXIS_NODES),        # count0
            P(AXIS_NODES),        # allowed
            P(),                  # order
            P(None, AXIS_NODES),  # noise
            P(),                  # req_any
            P(),                  # sig
            P(),                  # pod_valid
            P(AXIS_NODES),        # nz0
            P(),                  # scoring_req
        )
        if track_inbatch:
            from ..ops.pipeline import _inbatch_tensors

            inb = _inbatch_tensors(na, pa, ta, ids, n_buckets)
            inb_specs = {
                "anti": P(), "owner": P(), "m_bb": P(),
                "bucket_n": P(None, AXIS_NODES),
                "haskey_n": P(None, AXIS_NODES),
                "port_conflict": P(), "ca0": P(), "cb0": P(),
                "cs0": P(None, AXIS_NODES),
            }
            in_specs = base_specs + (inb_specs,)
        else:
            inb = None
            in_specs = base_specs
        solver = shard_map(
            partial(
                _solver_body,
                deterministic=deterministic,
                n_local=n_local,
                n_shards=n_shards,
            ),
            mesh=mesh,
            in_specs=in_specs,
            out_specs=(
                P(),                  # choices (replicated)
                P(AXIS_NODES),        # free residuals (stay sharded)
                P(AXIS_NODES),        # count residuals
                P(AXIS_NODES),        # nz residuals
            ),
        )
        scoring_req = pa.get("scoring_req")
        if scoring_req is None:
            scoring_req = jnp.zeros((pa["req"].shape[0], 2), free0.dtype)
        args = (mask, score, pa["req"], free0, count0, allowed, order, noise,
                pa["req_any"], sig, pvalid, nz0, scoring_req)
        if track_inbatch:
            args = args + (inb,)
        return solver, args, score, order, b, pvalid

    @partial(jax.jit, static_argnames=(
        "deterministic", "config", "term_kinds", "n_buckets", "return_carry",
        "track_inbatch",
    ))
    def pipeline(
        na: Arrays, pa: Arrays, ea: Arrays, ta: Arrays, xa: Arrays,
        au: Arrays, ids: Arrays, key, pb: Arrays = None, carry=None,
        deterministic: bool = False,
        config: "SolveConfig" = None, term_kinds=None, n_buckets=None,
        return_carry: bool = False, track_inbatch: bool = False,
    ):
        solver, args, score, order, b, _ = _prep(
            na, pa, ea, ta, xa, au, ids, key, pb, carry,
            deterministic, config, term_kinds, n_buckets,
            track_inbatch=track_inbatch)
        choices, free_f, count_f, nz_f = solver(*args)
        assign = jnp.full((b,), -1, jnp.int32).at[order].set(choices)
        if return_carry:
            return assign, score, (free_f, count_f, nz_f)
        return assign, score

    @partial(jax.jit, static_argnames=(
        "deterministic", "config", "term_kinds", "n_buckets", "return_carry"
    ))
    def pipeline_gang(
        na: Arrays, pa: Arrays, ea: Arrays, ta: Arrays, xa: Arrays,
        au: Arrays, ids: Arrays, key, group: jnp.ndarray, pb: Arrays = None,
        carry=None, deterministic: bool = False,
        config: "SolveConfig" = None, term_kinds=None, n_buckets=None,
        return_carry: bool = False,
    ):
        """All-or-nothing two-pass gang solve on the mesh (the multi-chip
        twin of ops.pipeline.solve_pipeline_gang): pass 1 places everything;
        groups with an unplaced member are dropped (replicated [B]
        elementwise math) and pass 2 re-solves without them. Pass 2's
        node-sharded residuals come back with return_carry so the chain
        can speculate past gang batches."""
        k1, k2 = jax.random.split(key)
        solver, args, score, order, b, pvalid = _prep(
            na, pa, ea, ta, xa, au, ids, k1, pb, carry,
            deterministic, config, term_kinds, n_buckets)
        choices, _, _, _ = solver(*args)
        first = jnp.full((b,), -1, jnp.int32).at[order].set(choices)
        grouped = group >= 0
        failed_member = grouped & (first < 0)
        fail_by_group = jnp.zeros(b, bool).at[
            jnp.where(grouped, group, 0)
        ].max(failed_member)
        dropped = grouped & fail_by_group[jnp.where(grouped, group, 0)]
        alive = pvalid & ~dropped
        # pass 2 reuses pass 1's mask/score/solver (same bit-parity recipe
        # as ops.solver.solve_gang) — only the tie-noise stream and the
        # alive set change
        args2 = list(args)
        N = na["valid"].shape[0]
        args2[7] = (
            jnp.zeros((b, n_shards)) if deterministic
            else _c(tie_noise(k2, b, N), None, AXIS_NODES)
        )
        args2[10] = alive
        choices2, free_f, count_f, nz_f = solver(*args2)
        second = jnp.full((b,), -1, jnp.int32).at[order].set(choices2)
        gang_ok = ~dropped
        assign = jnp.where(dropped, -1, second)
        if return_carry:
            return assign, score, gang_ok, (free_f, count_f, nz_f)
        return assign, score, gang_ok

    pipeline.gang = pipeline_gang
    # the commit plane's mesh twin rides along: full signature parity with
    # commit.arbiter.arbitrate, so the driver routes covered sharded
    # batches through `pipeline.arbitrate` exactly as it does replicated
    from ..commit.arbiter import make_sharded_arbiter

    pipeline.arbitrate = make_sharded_arbiter(mesh)
    _PIPELINE_CACHE[mesh] = pipeline
    return pipeline
