"""Multi-chip parallelism: node-axis sharding over a jax.sharding.Mesh.

See mesh.py for the mesh layout rationale and sharded.py for the two-stage
(GSPMD mask/score + shard_map greedy commit) design.
"""

from .mesh import AXIS_NODES, AXIS_PODS, node_mesh, node_shards
from .multihost import init_distributed, multihost_node_mesh
from .sharded import make_sharded_pipeline

__all__ = [
    "AXIS_NODES",
    "AXIS_PODS",
    "node_mesh",
    "node_shards",
    "make_sharded_pipeline",
    "init_distributed",
    "multihost_node_mesh",
]
