"""Device-mesh construction for multi-chip scheduling.

The scaling axis of the reference is CLUSTER SIZE (SURVEY §5 "long-context"
note): the kube-scheduler copes with 10k-node clusters by adaptively
SAMPLING nodes (numFeasibleNodesToFind, core/generic_scheduler.go:434-453);
this framework instead evaluates the FULL pods×nodes matrices and shards
the node axis across TPU chips over ICI. The mesh layout:

  * axis "nodes"  — the node columns of every mask/score matrix and the
    per-node residual state of the greedy solver live shard-local; the only
    cross-chip traffic is one tiny (best-score, best-node) argmax collective
    per committed pod plus XLA-inserted collectives for the handful of
    global reductions in the topology kernels (min/max normalization,
    per-topology-value counts).
  * axis "pods"   — optional data-parallel axis: the [B, N] mask/score
    COMPUTE is embarrassingly parallel over the pod batch, so B can be
    split across a second mesh dimension; the sequential greedy commit
    gathers the matrices to node-sharded form first (the scan is a strict
    order over pods by construction — reference scheduleOne semantics).

A v5e-8 is mesh (1, 8) or (2, 4); multi-host slices extend the "nodes"
axis over DCN (node columns never talk to each other except through the
argmax collective, which is latency- not bandwidth-bound).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh

try:  # jax >= 0.5 exports shard_map at the top level
    from jax import shard_map
except ImportError:  # 0.4.x keeps it under experimental
    from jax.experimental.shard_map import shard_map as _shard_map_04

    def shard_map(f, **kwargs):
        # 0.4.x's replication checker has no rule for lax.while_loop (the
        # greedy solver's repair loop) — disable it; every out_spec we
        # claim replicated is replicated by construction (broadcast
        # collectives), which newer jax verifies natively.
        kwargs.setdefault("check_rep", False)
        return _shard_map_04(f, **kwargs)

AXIS_NODES = "nodes"
AXIS_PODS = "pods"


def node_mesh(
    n_devices: Optional[int] = None,
    devices: Optional[Sequence] = None,
    pods_parallel: int = 1,
) -> Mesh:
    """Build a ("pods", "nodes") mesh over the first n_devices (default all).

    pods_parallel splits the device set into a data-parallel pod axis; the
    remainder shard the node axis. pods_parallel must divide the device
    count.
    """
    devs = list(devices) if devices is not None else list(jax.devices())
    if n_devices is not None:
        if len(devs) < n_devices:
            raise ValueError(f"need {n_devices} devices, have {len(devs)}")
        devs = devs[:n_devices]
    if len(devs) % pods_parallel != 0:
        raise ValueError(f"pods_parallel={pods_parallel} does not divide {len(devs)} devices")
    # jax.devices() is process-major: consecutive devices share a host. The
    # PODS axis gets the stride-1 (same-host) devices so its [B, N]
    # mask/score gathers (sharded.py) ride ICI on multi-host slices; the
    # node axis strides across hosts, and the only DCN traffic is its tiny
    # election reductions. grid[p, n] = devs[n * pods_parallel + p].
    grid = np.asarray(devs, dtype=object).reshape(-1, pods_parallel).T
    return Mesh(np.ascontiguousarray(grid), (AXIS_PODS, AXIS_NODES))


def node_shards(mesh: Mesh) -> int:
    return mesh.shape[AXIS_NODES]
