"""Randomized cluster/workload generators.

The TPU-native replacement for test/utils/runners.go's prepare strategies
(TrivialNodePrepareStrategy, NewCustomCreatePodStrategy, ...) and the
scheduler_perf config matrix (test/integration/scheduler_perf/
scheduler_bench_test.go:52-283): seeded, property-based generators producing
clusters that exercise every predicate/priority, used both for oracle-vs-
device parity tests and for benchmark population.
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional

from ..api.quantity import Quantity
from ..api.types import (
    Affinity,
    Container,
    ContainerImage,
    ContainerPort,
    LabelSelector,
    LabelSelectorRequirement,
    Node,
    NodeAffinity,
    NodeSelector,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    Pod,
    PodAffinity,
    PodAffinityTerm,
    PodAntiAffinity,
    PreferredSchedulingTerm,
    RESOURCE_CPU,
    RESOURCE_MEMORY,
    RESOURCE_PODS,
    Taint,
    Toleration,
    TopologySpreadConstraint,
    WeightedPodAffinityTerm,
)

ZONES = ["zone-a", "zone-b", "zone-c"]
REGIONS = ["region-1", "region-2"]
APP_NAMES = ["web", "db", "cache", "queue", "batch"]
ENV_VALUES = ["prod", "staging", "dev"]
NAMESPACES = ["default", "kube-system", "team-a", "team-b"]
TAINT_KEYS = ["dedicated", "gpu", "spot"]
IMAGES = [f"registry.local/app-{i}:v1" for i in range(8)]


def q(v) -> Quantity:
    return Quantity.parse(v)


def make_node(
    name: str,
    cpu_milli: int = 4000,
    mem: int = 16 * 2**30,
    pods: int = 110,
    labels: Optional[Dict[str, str]] = None,
    taints: Optional[List[Taint]] = None,
    unschedulable: bool = False,
    images: Optional[List[ContainerImage]] = None,
) -> Node:
    alloc = {
        RESOURCE_CPU: Quantity.parse(f"{cpu_milli}m"),
        RESOURCE_MEMORY: Quantity.parse(mem),
        RESOURCE_PODS: Quantity.parse(pods),
    }
    return Node(
        name=name,
        labels=dict(labels or {}),
        taints=list(taints or []),
        unschedulable=unschedulable,
        capacity=dict(alloc),
        allocatable=alloc,
        images=list(images or []),
    )


def make_pod(
    name: str,
    namespace: str = "default",
    cpu_milli: int = 100,
    mem: int = 128 * 2**20,
    labels: Optional[Dict[str, str]] = None,
    node_name: str = "",
    **kwargs,
) -> Pod:
    requests = {}
    if cpu_milli:
        requests[RESOURCE_CPU] = Quantity.parse(f"{cpu_milli}m")
    if mem:
        requests[RESOURCE_MEMORY] = Quantity.parse(mem)
    return Pod(
        name=name,
        namespace=namespace,
        labels=dict(labels or {}),
        node_name=node_name,
        containers=[Container(name="main", image=IMAGES[0], requests=requests)],
        **kwargs,
    )


class ClusterGen:
    """Seeded random cluster generator exercising all scheduling features."""

    def __init__(self, seed: int = 0):
        self.rng = random.Random(seed)

    def node(self, i: int, feature_rate: float = 0.3) -> Node:
        rng = self.rng
        labels = {
            "kubernetes.io/hostname": f"node-{i}",
            "failure-domain.beta.kubernetes.io/zone": rng.choice(ZONES),
            "failure-domain.beta.kubernetes.io/region": rng.choice(REGIONS),
            "instance-type": rng.choice(["small", "medium", "large"]),
        }
        if rng.random() < feature_rate:
            labels["disk"] = rng.choice(["ssd", "hdd"])
        if rng.random() < feature_rate / 2:
            labels["cores"] = str(rng.randint(1, 64))
        taints = []
        if rng.random() < feature_rate / 2:
            taints.append(
                Taint(
                    key=rng.choice(TAINT_KEYS),
                    value=rng.choice(["true", "team-a", ""]),
                    effect=rng.choice(["NoSchedule", "PreferNoSchedule", "NoExecute"]),
                )
            )
        images = []
        for img in IMAGES:
            if rng.random() < 0.3:
                images.append(ContainerImage(names=[img], size_bytes=rng.randint(10, 900) * 2**20))
        return make_node(
            f"node-{i}",
            cpu_milli=rng.choice([2000, 4000, 8000, 16000]),
            mem=rng.choice([4, 8, 16, 32]) * 2**30,
            pods=rng.choice([32, 64, 110]),
            labels=labels,
            taints=taints,
            unschedulable=rng.random() < 0.03,
            images=images,
        )

    def _label_selector(self) -> LabelSelector:
        rng = self.rng
        if rng.random() < 0.6:
            return LabelSelector(match_labels={"app": rng.choice(APP_NAMES)})
        return LabelSelector(
            match_expressions=[
                LabelSelectorRequirement(
                    key=rng.choice(["app", "env"]),
                    operator=rng.choice(["In", "NotIn", "Exists", "DoesNotExist"]),
                    values=[rng.choice(APP_NAMES + ENV_VALUES)],
                )
            ]
        )

    def _affinity_term(self) -> PodAffinityTerm:
        rng = self.rng
        return PodAffinityTerm(
            label_selector=self._label_selector(),
            namespaces=[rng.choice(NAMESPACES)] if rng.random() < 0.3 else [],
            topology_key=rng.choice(
                [
                    "kubernetes.io/hostname",
                    "failure-domain.beta.kubernetes.io/zone",
                    "failure-domain.beta.kubernetes.io/region",
                ]
            ),
        )

    def pod(
        self,
        i: int,
        feature_rate: float = 0.3,
        namespace: Optional[str] = None,
        node_name: str = "",
    ) -> Pod:
        rng = self.rng
        labels = {"app": rng.choice(APP_NAMES), "env": rng.choice(ENV_VALUES)}
        pod = make_pod(
            f"pod-{i}",
            namespace=namespace if namespace is not None else rng.choice(NAMESPACES),
            cpu_milli=rng.choice([0, 50, 100, 250, 500, 1000]),
            mem=rng.choice([0, 64, 128, 256, 512]) * 2**20,
            labels=labels,
            node_name=node_name,
            priority=rng.choice([None, 0, 100, 1000]),
        )
        pod.containers[0].image = rng.choice(IMAGES)
        if rng.random() < feature_rate:
            pod.node_selector = {"instance-type": rng.choice(["small", "medium", "large"])}
        if rng.random() < feature_rate / 2:
            pod.containers[0].ports = [
                ContainerPort(
                    host_port=rng.choice([8080, 9090, 9091]),
                    container_port=8080,
                    protocol=rng.choice(["TCP", "UDP"]),
                    host_ip=rng.choice(["", "0.0.0.0", "127.0.0.1"]),
                )
            ]
        if rng.random() < feature_rate:
            pod.tolerations = [
                Toleration(
                    key=rng.choice(TAINT_KEYS + [""]),
                    operator=rng.choice(["Equal", "Exists"]),
                    value=rng.choice(["true", "team-a", ""]),
                    effect=rng.choice(["NoSchedule", "NoExecute", "PreferNoSchedule", ""]),
                )
            ]
        affinity = Affinity()
        has_affinity = False
        if rng.random() < feature_rate:
            has_affinity = True
            req = None
            if rng.random() < 0.7:
                req = NodeSelector(
                    node_selector_terms=[
                        NodeSelectorTerm(
                            match_expressions=[
                                NodeSelectorRequirement(
                                    key=rng.choice(["disk", "instance-type", "cores"]),
                                    operator=rng.choice(
                                        ["In", "NotIn", "Exists", "DoesNotExist", "Gt", "Lt"]
                                    ),
                                    values=[rng.choice(["ssd", "hdd", "small", "large", "8", "32"])],
                                )
                            ]
                        )
                    ]
                )
            affinity.node_affinity = NodeAffinity(
                required=req,
                preferred=[
                    PreferredSchedulingTerm(
                        weight=rng.randint(1, 100),
                        preference=NodeSelectorTerm(
                            match_expressions=[
                                NodeSelectorRequirement(
                                    key="instance-type", operator="In", values=[rng.choice(["small", "large"])]
                                )
                            ]
                        ),
                    )
                ]
                if rng.random() < 0.5
                else [],
            )
        if rng.random() < feature_rate / 2:
            has_affinity = True
            term = self._affinity_term()
            wterm = WeightedPodAffinityTerm(weight=rng.randint(1, 100), pod_affinity_term=self._affinity_term())
            if rng.random() < 0.5:
                affinity.pod_affinity = PodAffinity(
                    required=[term] if rng.random() < 0.6 else [],
                    preferred=[wterm] if rng.random() < 0.6 else [],
                )
            else:
                affinity.pod_anti_affinity = PodAntiAffinity(
                    required=[term] if rng.random() < 0.6 else [],
                    preferred=[wterm] if rng.random() < 0.6 else [],
                )
        if has_affinity:
            pod.affinity = affinity
        if rng.random() < feature_rate / 2:
            pod.topology_spread_constraints = [
                TopologySpreadConstraint(
                    max_skew=rng.randint(1, 3),
                    topology_key=rng.choice(
                        ["failure-domain.beta.kubernetes.io/zone", "kubernetes.io/hostname"]
                    ),
                    when_unsatisfiable=rng.choice(["DoNotSchedule", "ScheduleAnyway"]),
                    label_selector=self._label_selector(),
                )
            ]
        return pod

    def cluster(
        self, n_nodes: int, n_existing: int, feature_rate: float = 0.3
    ) -> tuple[List[Node], List[Pod]]:
        nodes = [self.node(i, feature_rate) for i in range(n_nodes)]
        existing = []
        for i in range(n_existing):
            node = self.rng.choice(nodes)
            existing.append(
                self.pod(i, feature_rate, node_name=node.name)
            )
        return nodes, existing
