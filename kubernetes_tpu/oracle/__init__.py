"""Scalar Python reference semantics ("the oracle") for parity testing.

This package re-states the reference scheduler's exact Filter/Score
semantics in plain Python (see predicates.py, priorities.py). Device kernels
in kubernetes_tpu/ops are validated bit-for-bit against these functions on
randomized clusters (SURVEY.md section 4 "Implication for the build").
"""

from .nodeinfo import NodeInfo, Snapshot, get_zone_key
from .predicates import (
    PredicateMetadata,
    compute_predicate_metadata,
    find_nodes_that_fit,
    pod_fits_on_node,
)
from .priorities import MAX_NODE_SCORE, prioritize_nodes

__all__ = [
    "NodeInfo",
    "Snapshot",
    "get_zone_key",
    "PredicateMetadata",
    "compute_predicate_metadata",
    "find_nodes_that_fit",
    "pod_fits_on_node",
    "MAX_NODE_SCORE",
    "prioritize_nodes",
]
