"""Host-side cluster snapshot views used by the oracle (scalar reference
semantics) and by the tensorization layer.

Mirrors the role of pkg/scheduler/nodeinfo/node_info.go: a per-node aggregate
of the scheduling-relevant state (requested resources, pod list, used host
ports, pods with affinity), plus a Snapshot keyed by node name like
nodeinfo.Snapshot (pkg/scheduler/nodeinfo/snapshot.go:22).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from ..api.types import Node, Pod, RESOURCE_CPU, RESOURCE_MEMORY, RESOURCE_PODS

DEFAULT_BIND_ALL_HOST_IP = "0.0.0.0"

# Kubernetes zone/region label keys (v1.LabelZoneFailureDomain / LabelZoneRegion).
LABEL_ZONE_FAILURE_DOMAIN = "failure-domain.beta.kubernetes.io/zone"
LABEL_ZONE_REGION = "failure-domain.beta.kubernetes.io/region"


def get_zone_key(node: Node) -> str:
    """utilnode.GetZoneKey (pkg/util/node/node.go): region + zone combined;
    empty string when neither label is present."""
    region = node.labels.get(LABEL_ZONE_REGION, "")
    zone = node.labels.get(LABEL_ZONE_FAILURE_DOMAIN, "")
    if not region and not zone:
        return ""
    return region + ":\x00:" + zone


def pod_has_affinity_constraints(pod: Pod) -> bool:
    """nodeinfo tracks podsWithAffinity = pods with affinity OR anti-affinity
    (node_info.go AddPod -> hasPodAffinityConstraints)."""
    a = pod.affinity
    return a is not None and (a.pod_affinity is not None or a.pod_anti_affinity is not None)


@dataclass
class NodeInfo:
    """Per-node scheduling aggregate (reference: nodeinfo/node_info.go:48).

    Resource/port aggregates are maintained INCREMENTALLY like the
    reference's calculateResource add/remove path — O(1) per pod change
    instead of O(pods-on-node) per query (the query sits on the mirror
    sync and oracle hot paths). Mutate `pods` ONLY through
    add_pod/remove_pod/remove_pod_key/set_pods; writing the list directly
    desyncs the running sums."""

    node: Node
    pods: List[Pod] = field(default_factory=list)

    def __post_init__(self):
        self._recount()

    # NodeInfo is an EXTERNALLY-synchronized value object: live instances
    # are mutated only under SchedulerCache._lock (the cache is the sole
    # mutator — holder-side discipline checked in state/cache.py), and
    # snapshot/lazy-view clones are thread-local. The running-sum attrs
    # therefore carry allow(KTPU006) rather than a guarded-by they could
    # not name (the lock lives on the owning cache, not on the object).
    def _recount(self) -> None:
        self._aff_pods: List[Pod] = []  # ktpu: allow(KTPU006) cache-lock-held
        self._req: Dict[str, int] = {}  # ktpu: allow(KTPU006) cache-lock-held
        self._nz_cpu = 0  # ktpu: allow(KTPU006) cache-lock-held
        self._nz_mem = 0  # ktpu: allow(KTPU006) cache-lock-held
        self._ports: Dict[Tuple[str, str, int], int] = {}  # ktpu: allow(KTPU006) cache-lock-held
        # lazy-view generation tag (state/columns.py): when this NodeInfo
        # is a columnar cache's view, materialization stamps it with the
        # row's column generation — a reader comparing against
        # CacheColumns.row_gen can tell exactly how stale a view is
        self.generation = 0
        for p in self.pods:
            self._account(p, 1)

    def _account(self, pod: Pod, sign: int) -> None:
        # podsWithAffinity maintained INCREMENTALLY (node_info.go AddPod/
        # RemovePod do the same): preemption's reprieve loop re-reads it
        # once per candidate node per victim — recomputing over every pod
        # made preempt() O(cluster x pods) in pure list filtering
        if pod_has_affinity_constraints(pod):
            if sign > 0:
                self._aff_pods.append(pod)
            else:
                # every removal path (remove_pod / remove_pod_key) passes
                # the stored object, matching the pods-list semantics
                self._aff_pods.remove(pod)
        req = self._req
        for name, v in accumulated_request(pod).items():
            nv = req.get(name, 0) + sign * v
            if nv:
                req[name] = nv
            else:
                req.pop(name, None)
        c, m = pod_non_zero_request(pod)
        self._nz_cpu += sign * c
        self._nz_mem += sign * m
        ports = self._ports
        for t in pod.host_ports():
            nv = ports.get(t, 0) + sign
            if nv:
                ports[t] = nv
            else:
                ports.pop(t, None)

    # -- mutations (keep the running aggregates in sync) ---------------------

    def add_pod(self, pod: Pod) -> None:
        self.pods.append(pod)
        self._account(pod, 1)

    def remove_pod(self, pod: Pod) -> None:
        """Remove by object identity (simulation paths)."""
        self.pods.remove(pod)
        self._account(pod, -1)

    def remove_pod_key(self, key: str) -> Optional[Pod]:
        for p in self.pods:
            if p.key() == key:
                self.pods.remove(p)
                self._account(p, -1)
                return p
        return None

    def set_pods(self, pods: List[Pod]) -> None:
        self.pods = list(pods)  # ktpu: allow(KTPU006) cache-lock-held
        self._recount()

    # -- aggregates ----------------------------------------------------------

    def pods_with_affinity(self) -> List[Pod]:
        """READ-ONLY view (the incrementally-maintained list itself —
        mutating it desyncs the affinity bookkeeping that feeds the
        mirror's pattern encoding and preemption's fast-path guard)."""
        return self._aff_pods

    def requested(self) -> Dict[str, int]:
        """RequestedResource per calculateResource (node_info.go): sum of
        container requests + overhead — NOTE: unlike the incoming pod's
        GetResourceRequest, init-container maxima are NOT included."""
        return dict(self._req)

    def non_zero_requested(self) -> Tuple[int, int]:
        """nonzeroRequest (milliCPU, memoryBytes): per container,
        max(request, default 100m / 200Mi) — priorityutil.GetNonzeroRequests;
        plus overhead when present (calculateResource, node_info.go)."""
        return self._nz_cpu, self._nz_mem

    def allowed_pod_number(self) -> int:
        q = self.node.allocatable.get(RESOURCE_PODS)
        return q.value() if q is not None else 0

    def used_host_ports(self) -> Set[Tuple[str, str, int]]:
        """(protocol, hostIP, hostPort) triples across pods (HostPortInfo)."""
        return set(self._ports)

    def host_port_conflict(self, pod: Pod) -> bool:
        """HostPortInfo.CheckConflict semantics (nodeinfo/host_ports.go):
        0.0.0.0 conflicts with every IP for the same (protocol, port)."""
        used = self.used_host_ports()
        for proto, ip, port in pod.host_ports():
            if port <= 0:
                continue
            if ip == DEFAULT_BIND_ALL_HOST_IP:
                if any(u_port == port and u_proto == proto for u_proto, _, u_port in used):
                    return True
            else:
                for u_proto, u_ip, u_port in used:
                    if u_port == port and u_proto == proto and u_ip in (DEFAULT_BIND_ALL_HOST_IP, ip):
                        return True
        return False

    def image_sizes(self) -> Dict[str, int]:
        """image name -> size (nodeinfo imageStates, keyed by normalized name)."""
        out: Dict[str, int] = {}
        for img in self.node.images:
            for name in img.names:
                out[normalized_image_name(name)] = img.size_bytes
        return out


# Defaults for pods with no explicit cpu/memory request, used only for
# scoring (priorityutil non_zero.go:26-29).
DEFAULT_MILLI_CPU_REQUEST = 100
DEFAULT_MEMORY_REQUEST = 200 * 1024 * 1024


def accumulated_request(pod: Pod) -> Dict[str, int]:
    """calculateResource's `res` (node_info.go): container request sums +
    overhead; init containers excluded (unlike GetResourceRequest).

    Memoized on the pod object (computed once per pod; assume + forget +
    every oracle pass re-read it). `with_node` clones carry the memo.
    Callers must treat the returned dict as read-only."""
    memo = pod.__dict__.get("_acc_req_memo")
    if memo is not None:
        return memo
    total: Dict[str, int] = {}
    for c in pod.containers:
        for name, q in c.requests.items():
            v = q.milli_value() if name == RESOURCE_CPU else q.value()
            total[name] = total.get(name, 0) + v
    for name, q in pod.overhead.items():
        v = q.milli_value() if name == RESOURCE_CPU else q.value()
        total[name] = total.get(name, 0) + v
    pod.__dict__["_acc_req_memo"] = total
    return total


def pod_non_zero_request(pod: Pod) -> Tuple[int, int]:
    """(milliCPU, memBytes) with per-container defaulting of unset requests.
    Memoized like accumulated_request."""
    memo = pod.__dict__.get("_nz_req_memo")
    if memo is not None:
        return memo
    cpu = 0
    mem = 0
    for c in pod.containers:
        q = c.requests.get(RESOURCE_CPU)
        cpu += q.milli_value() if q is not None else DEFAULT_MILLI_CPU_REQUEST
        q = c.requests.get(RESOURCE_MEMORY)
        mem += q.value() if q is not None else DEFAULT_MEMORY_REQUEST
    q = pod.overhead.get(RESOURCE_CPU)
    if q is not None:
        cpu += q.milli_value()
    q = pod.overhead.get(RESOURCE_MEMORY)
    if q is not None:
        mem += q.value()
    pod.__dict__["_nz_req_memo"] = (cpu, mem)
    return cpu, mem


def normalized_image_name(name: str) -> str:
    """parsers.ParseImageName default-tag normalization: bare names get :latest
    (pkg/util/parsers; used by image_locality.go normalizedImageName)."""
    if ":" not in name.split("/")[-1] and "@" not in name:
        return name + ":latest"
    return name


class Snapshot:
    """Cluster snapshot: node name -> NodeInfo; the oracle's equivalent of
    nodeNameToInfo maps passed through the reference algorithm."""

    def __init__(self, nodes: Optional[List[Node]] = None, pods: Optional[List[Pod]] = None):
        # ktpu: allow(KTPU006) externally synchronized like NodeInfo: the
        # live snapshot mutates only under SchedulerCache._lock; oracle/
        # plugin copies are built and read on one thread
        self.node_infos: Dict[str, NodeInfo] = {}
        for n in nodes or []:
            self.add_node(n)
        for p in pods or []:
            if p.node_name:
                self.assign(p)

    def add_node(self, node: Node) -> NodeInfo:
        ni = NodeInfo(node=node)
        self.node_infos[node.name] = ni
        return ni

    def assign(self, pod: Pod) -> None:
        ni = self.node_infos.get(pod.node_name)
        if ni is None:
            # pods on unknown nodes are tracked nowhere in the snapshot
            # (reference keeps a headless NodeInfo; scheduling never sees it)
            return
        ni.add_pod(pod)

    def get(self, name: str) -> Optional[NodeInfo]:
        return self.node_infos.get(name)

    def nodes(self) -> List[Node]:
        return [ni.node for ni in self.node_infos.values()]

    def all_pods(self) -> List[Pod]:
        out: List[Pod] = []
        for ni in self.node_infos.values():
            out.extend(ni.pods)
        return out

    def total_image_nodes(self) -> Dict[str, int]:
        """image name -> number of nodes that have it (ImageStateSummary.NumNodes)."""
        counts: Dict[str, int] = {}
        for ni in self.node_infos.values():
            for name in ni.image_sizes():
                counts[name] = counts.get(name, 0) + 1
        return counts
