"""Oracle predicates: scalar reference semantics for every Filter.

Re-implements, in plain Python over the typed API objects, the exact
feasibility semantics of pkg/scheduler/algorithm/predicates/predicates.go.
This module is the single source of truth the vectorized device kernels
(kubernetes_tpu/ops/filters.py, topology.py) are parity-tested against.

Where the reference has two code paths (precomputed predicateMetadata vs the
slow path), this oracle implements the METADATA path — that is what runs in
the production scheduler (GetPredicateMetadata is always installed by the
default algorithm provider) and what the vectorized kernels model.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from ..api.selectors import (
    match_label_selector,
    node_matches_node_selector,
)
from ..api.types import (
    Affinity,
    Pod,
    RESOURCE_CPU,
    RESOURCE_EPHEMERAL_STORAGE,
    RESOURCE_MEMORY,
    PodAffinityTerm,
    TAINT_NO_EXECUTE,
    TAINT_NO_SCHEDULE,
    TAINT_NODE_UNSCHEDULABLE,
    Taint,
    TopologySpreadConstraint,
    DO_NOT_SCHEDULE,
    tolerations_tolerate_taint,
)
from .nodeinfo import NodeInfo, Snapshot

# Failure reason strings (mirror predicates.Err* for debuggability).
ERR_NODE_UNSCHEDULABLE = "NodeUnschedulable"
ERR_POD_NOT_FIT_HOST = "PodFitsHost"
ERR_POD_NOT_FIT_PORTS = "PodFitsHostPorts"
ERR_NODE_SELECTOR_NOT_MATCH = "MatchNodeSelector"
ERR_INSUFFICIENT = "Insufficient {}"
ERR_TAINTS = "PodToleratesNodeTaints"
ERR_TOPOLOGY_SPREAD = "EvenPodsSpreadNotMatch"
ERR_POD_AFFINITY = "MatchInterPodAffinity"


# ---------------------------------------------------------------------------
# Simple per-node predicates
# ---------------------------------------------------------------------------

def check_node_unschedulable(pod: Pod, node_info: NodeInfo) -> bool:
    """CheckNodeUnschedulablePredicate (predicates.go:1584): unschedulable
    nodes pass only if the pod tolerates the unschedulable taint."""
    if not node_info.node.unschedulable:
        return True
    return tolerations_tolerate_taint(
        pod.tolerations,
        Taint(key=TAINT_NODE_UNSCHEDULABLE, effect=TAINT_NO_SCHEDULE),
    )


def pod_fits_host(pod: Pod, node_info: NodeInfo) -> bool:
    """PodFitsHost (predicates.go:991): spec.nodeName pinning."""
    if not pod.node_name:
        return True
    return pod.node_name == node_info.node.name


def pod_fits_host_ports(pod: Pod, node_info: NodeInfo) -> bool:
    """PodFitsHostPorts (predicates.go:1161) via HostPortInfo conflicts."""
    if not pod.host_ports():
        return True
    return not node_info.host_port_conflict(pod)


def pod_match_node_selector(pod: Pod, node_info: NodeInfo) -> bool:
    """PodMatchNodeSelector (predicates.go:979) =
    PodMatchesNodeSelectorAndAffinityTerms: spec.nodeSelector (all labels
    must match) AND nodeAffinity.required terms (ORed)."""
    node = node_info.node
    for k, v in pod.node_selector.items():
        if node.labels.get(k) != v:
            return False
    aff = pod.affinity
    if aff is not None and aff.node_affinity is not None and aff.node_affinity.required is not None:
        return node_matches_node_selector(aff.node_affinity.required, node.labels, node.name)
    return True


def pod_fits_resources(pod: Pod, node_info: NodeInfo) -> bool:
    """PodFitsResources (predicates.go:854): pod count always checked. When
    the pod requests anything at all, cpu/memory/ephemeral-storage are ALWAYS
    checked (so a zero-cpu pod still fails on a cpu-overcommitted node, per
    the reference's unconditional compares at predicates.go:886-895) while
    scalar resources are checked only when requested non-zero (explicit-zero
    scalar requests are treated as unset — indistinguishable in the tensor
    encoding; deviation only matters on overcommitted nodes)."""
    if len(node_info.pods) + 1 > node_info.allowed_pod_number():
        return False
    req = pod.resource_request()
    if all(v == 0 for k, v in req.items() if k != "pods"):
        return True
    alloc = node_info.node.allocatable_int()
    used = node_info.requested()
    for name in (RESOURCE_CPU, RESOURCE_MEMORY, RESOURCE_EPHEMERAL_STORAGE):
        if alloc.get(name, 0) < req.get(name, 0) + used.get(name, 0):
            return False
    for name, r in req.items():
        if name in (RESOURCE_CPU, RESOURCE_MEMORY, RESOURCE_EPHEMERAL_STORAGE, "pods"):
            continue
        if r != 0 and alloc.get(name, 0) < r + used.get(name, 0):
            return False
    return True


def pod_tolerates_node_taints(pod: Pod, node_info: NodeInfo) -> bool:
    """PodToleratesNodeTaints (predicates.go:1604): only NoSchedule/NoExecute
    taints matter; every such taint must be tolerated."""
    for taint in node_info.node.taints:
        if taint.effect not in (TAINT_NO_SCHEDULE, TAINT_NO_EXECUTE):
            continue
        if not tolerations_tolerate_taint(pod.tolerations, taint):
            return False
    return True


# ---------------------------------------------------------------------------
# EvenPodsSpread (hard topology spread constraints)
# ---------------------------------------------------------------------------

def get_hard_spread_constraints(pod: Pod) -> List[TopologySpreadConstraint]:
    return [c for c in pod.topology_spread_constraints if c.when_unsatisfiable == DO_NOT_SCHEDULE]


def get_soft_spread_constraints(pod: Pod) -> List[TopologySpreadConstraint]:
    return [c for c in pod.topology_spread_constraints if c.when_unsatisfiable != DO_NOT_SCHEDULE]


def pod_matches_spread_constraint(pod_labels: Dict[str, str], c: TopologySpreadConstraint) -> bool:
    """PodMatchesSpreadConstraint (metadata.go:499): nil selector matches
    nothing (LabelSelectorAsSelector of nil -> Nothing)."""
    return match_label_selector(c.label_selector, pod_labels)


def node_labels_match_spread_constraints(
    node_labels: Dict[str, str], constraints: List[TopologySpreadConstraint]
) -> bool:
    """metadata.go:511: node must carry ALL topology keys."""
    return all(c.topology_key in node_labels for c in constraints)


@dataclass
class EvenPodsSpreadMetadata:
    """getEvenPodsSpreadMetadata (metadata.go:399): per-(key,value) counts of
    same-namespace pods matching each constraint's selector, over candidate
    nodes (nodes passing the incoming pod's node selector/affinity and
    carrying all topology keys), plus the per-key global minimum."""

    tp_pair_to_match_num: Dict[Tuple[str, str], int] = field(default_factory=dict)
    tp_key_min_match: Dict[str, int] = field(default_factory=dict)


def compute_even_pods_spread_metadata(pod: Pod, snapshot: Snapshot) -> Optional[EvenPodsSpreadMetadata]:
    constraints = get_hard_spread_constraints(pod)
    if not constraints:
        return None
    m = EvenPodsSpreadMetadata()
    for ni in snapshot.node_infos.values():
        node = ni.node
        if not pod_match_node_selector(pod, ni):
            continue
        if not node_labels_match_spread_constraints(node.labels, constraints):
            continue
        for c in constraints:
            match_total = sum(
                1
                for ep in ni.pods
                if ep.namespace == pod.namespace and pod_matches_spread_constraint(ep.labels, c)
            )
            pair = (c.topology_key, node.labels[c.topology_key])
            m.tp_pair_to_match_num[pair] = m.tp_pair_to_match_num.get(pair, 0) + match_total
    for (key, _), num in m.tp_pair_to_match_num.items():
        cur = m.tp_key_min_match.get(key)
        if cur is None or num < cur:
            m.tp_key_min_match[key] = num
    return m


def even_pods_spread_predicate(
    pod: Pod, node_info: NodeInfo, meta: Optional[EvenPodsSpreadMetadata]
) -> bool:
    """EvenPodsSpreadPredicate (predicates.go:1778): per hard constraint,
    matchNum(node's pair) + selfMatch - minMatchNum(key) <= maxSkew; node must
    carry the topology key."""
    constraints = get_hard_spread_constraints(pod)
    if not constraints:
        return True
    if meta is None or not meta.tp_pair_to_match_num:
        return True
    node = node_info.node
    for c in constraints:
        tp_val = node.labels.get(c.topology_key)
        if tp_val is None:
            return False
        self_match = 1 if pod_matches_spread_constraint(pod.labels, c) else 0
        if c.topology_key not in meta.tp_key_min_match:
            continue  # "error which should not happen" branch: skip constraint
        min_match = meta.tp_key_min_match[c.topology_key]
        match_num = meta.tp_pair_to_match_num.get((c.topology_key, tp_val), 0)
        if match_num + self_match - min_match > c.max_skew:
            return False
    return True


# ---------------------------------------------------------------------------
# InterPodAffinity
# ---------------------------------------------------------------------------

def get_pod_affinity_terms(affinity: Optional[Affinity]) -> List[PodAffinityTerm]:
    """GetPodAffinityTerms: required terms only."""
    if affinity is None or affinity.pod_affinity is None:
        return []
    return list(affinity.pod_affinity.required)


def get_pod_anti_affinity_terms(affinity: Optional[Affinity]) -> List[PodAffinityTerm]:
    if affinity is None or affinity.pod_anti_affinity is None:
        return []
    return list(affinity.pod_anti_affinity.required)


def term_namespaces(owner: Pod, term: PodAffinityTerm) -> Set[str]:
    """priorityutil.GetNamespacesFromPodAffinityTerm: empty -> owner's ns."""
    return set(term.namespaces) if term.namespaces else {owner.namespace}


def pod_matches_term(target: Pod, owner: Pod, term: PodAffinityTerm) -> bool:
    """PodMatchesTermsNamespaceAndSelector for one term."""
    if target.namespace not in term_namespaces(owner, term):
        return False
    return match_label_selector(term.label_selector, target.labels)


def pod_matches_all_term_properties(target: Pod, owner: Pod, terms: List[PodAffinityTerm]) -> bool:
    """podMatchesAllAffinityTermProperties: target must match (ns, selector)
    of every term. Empty terms -> False (getAffinityTermProperties of [])."""
    if not terms:
        return False
    return all(pod_matches_term(target, owner, t) for t in terms)


@dataclass
class PodAffinityMetadata:
    """podAffinityMetadata (metadata.go:~360): three topology-pair sets."""

    # (key, value) pairs where scheduling the incoming pod violates an
    # EXISTING pod's required anti-affinity. Node fails if any of its own
    # labels is in this set.
    existing_anti_pairs: Set[Tuple[str, str]] = field(default_factory=set)
    # (key, value) pairs from existing pods matching ALL of the incoming
    # pod's required affinity terms' properties.
    incoming_affinity_pairs: Set[Tuple[str, str]] = field(default_factory=set)
    # (key, value) pairs from existing pods matching each of the incoming
    # pod's required anti-affinity terms.
    incoming_anti_pairs: Set[Tuple[str, str]] = field(default_factory=set)


class SnapshotAffinityIndex:
    """The pod-independent structure of the affinity metadata, built ONCE
    per snapshot epoch instead of re-walking the cluster for every pod:

    * existing pods' required anti-affinity terms grouped by CONTENT
      (namespace set, selector, topology key) with the set of topology
      values their hosting nodes carry — one selector match per distinct
      term content instead of one per (pod, term) instance;
    * existing pods grouped by (namespace, labels) signature with their
      hosting nodes' label dicts — the incoming pod's own terms match one
      group representative instead of every pod.

    Exactness: both halves of compute_pod_affinity_metadata depend on an
    existing pod only through its namespace + labels (and its node's
    labels), so grouping by those is a pure dedup. Callers that mutate the
    snapshot after building (the driver's in-batch commits) pass the new
    pods through `extra`, which replays the original per-pod logic."""

    def __init__(self, snapshot: Snapshot):
        self.anti_groups: Dict[tuple, dict] = {}
        self.pod_groups: Dict[tuple, dict] = {}
        for ni in snapshot.node_infos.values():
            labels = ni.node.labels
            for ep in ni.pods_with_affinity():
                for term in get_pod_anti_affinity_terms(ep.affinity):
                    v = labels.get(term.topology_key)
                    if v is None:
                        continue
                    nss = (
                        tuple(sorted(term.namespaces))
                        if term.namespaces
                        else ep.namespace
                    )
                    key = (nss, repr(term.label_selector), term.topology_key)
                    g = self.anti_groups.get(key)
                    if g is None:
                        self.anti_groups[key] = g = {
                            "term": term,
                            "ep": ep,
                            "values": set(),
                        }
                    g["values"].add(v)
            for ep in ni.pods:
                key = (ep.namespace, tuple(sorted(ep.labels.items())))
                g = self.pod_groups.get(key)
                if g is None:
                    self.pod_groups[key] = g = {"ep": ep, "nodes": []}
                g["nodes"].append(labels)


def _affinity_pairs_for_pod(
    m: PodAffinityMetadata,
    pod: Pod,
    ep: Pod,
    node_labels: Dict[str, str],
    affinity_terms,
    anti_terms,
) -> None:
    """The original per-(existing pod, node) metadata contribution — used
    for index `extra` entries (in-batch commits)."""
    for term in get_pod_anti_affinity_terms(ep.affinity):
        if pod_matches_term(pod, ep, term) and term.topology_key in node_labels:
            m.existing_anti_pairs.add((term.topology_key, node_labels[term.topology_key]))
    if affinity_terms and pod_matches_all_term_properties(ep, pod, affinity_terms):
        for term in affinity_terms:
            if term.topology_key in node_labels:
                m.incoming_affinity_pairs.add(
                    (term.topology_key, node_labels[term.topology_key])
                )
    for term in anti_terms:
        if pod_matches_term(ep, pod, term) and term.topology_key in node_labels:
            m.incoming_anti_pairs.add((term.topology_key, node_labels[term.topology_key]))


def compute_pod_affinity_metadata(
    pod: Pod,
    snapshot: Snapshot,
    index: Optional[SnapshotAffinityIndex] = None,
    extra=(),
) -> PodAffinityMetadata:
    m = PodAffinityMetadata()
    affinity_terms = get_pod_affinity_terms(pod.affinity)
    anti_terms = get_pod_anti_affinity_terms(pod.affinity)

    if index is not None:
        # grouped fast path: one match per distinct term content / pod
        # signature (see SnapshotAffinityIndex)
        for g in index.anti_groups.values():
            if pod_matches_term(pod, g["ep"], g["term"]):
                tk = g["term"].topology_key
                for v in g["values"]:
                    m.existing_anti_pairs.add((tk, v))
        if affinity_terms or anti_terms:
            for g in index.pod_groups.values():
                rep = g["ep"]
                if affinity_terms and pod_matches_all_term_properties(rep, pod, affinity_terms):
                    for term in affinity_terms:
                        for labels in g["nodes"]:
                            v = labels.get(term.topology_key)
                            if v is not None:
                                m.incoming_affinity_pairs.add((term.topology_key, v))
                for term in anti_terms:
                    if pod_matches_term(rep, pod, term):
                        for labels in g["nodes"]:
                            v = labels.get(term.topology_key)
                            if v is not None:
                                m.incoming_anti_pairs.add((term.topology_key, v))
        for ep, node_labels in extra:
            _affinity_pairs_for_pod(m, pod, ep, node_labels, affinity_terms, anti_terms)
        return m

    for ni in snapshot.node_infos.values():
        node = ni.node
        # Existing pods' required anti-affinity vs the incoming pod
        # (getTPMapMatchingExistingAntiAffinity).
        for ep in ni.pods_with_affinity():
            for term in get_pod_anti_affinity_terms(ep.affinity):
                if pod_matches_term(pod, ep, term):
                    if term.topology_key in node.labels:
                        m.existing_anti_pairs.add((term.topology_key, node.labels[term.topology_key]))
        # Incoming pod's terms vs existing pods
        # (getTPMapMatchingIncomingAffinityAntiAffinity).
        if affinity_terms or anti_terms:
            for ep in ni.pods:
                if affinity_terms and pod_matches_all_term_properties(ep, pod, affinity_terms):
                    for term in affinity_terms:
                        if term.topology_key in node.labels:
                            m.incoming_affinity_pairs.add(
                                (term.topology_key, node.labels[term.topology_key])
                            )
                for term in anti_terms:
                    if pod_matches_term(ep, pod, term):
                        if term.topology_key in node.labels:
                            m.incoming_anti_pairs.add(
                                (term.topology_key, node.labels[term.topology_key])
                            )
    return m


def inter_pod_affinity_matches(
    pod: Pod, node_info: NodeInfo, meta: PodAffinityMetadata
) -> bool:
    """InterPodAffinityMatches (predicates.go:1269), metadata path."""
    node = node_info.node
    # 1. satisfiesExistingPodsAntiAffinity: any of the node's own label pairs
    # present in the existing-anti set -> fail.
    for k, v in node.labels.items():
        if (k, v) in meta.existing_anti_pairs:
            return False

    affinity = pod.affinity
    if affinity is None or (affinity.pod_affinity is None and affinity.pod_anti_affinity is None):
        return True

    # 2. Pod's own required affinity: node must match topology of ALL terms.
    affinity_terms = get_pod_affinity_terms(affinity)
    if affinity_terms:
        match_exists = all(
            term.topology_key in node.labels
            and (term.topology_key, node.labels[term.topology_key]) in meta.incoming_affinity_pairs
            for term in affinity_terms
        )
        if not match_exists:
            # First-pod-in-series escape (generic_scheduler commentary at
            # satisfiesPodsAffinityAntiAffinity): allowed only when no pod in
            # the cluster matches and the pod matches its own terms.
            if not (
                not meta.incoming_affinity_pairs
                and pod_matches_all_term_properties(pod, pod, affinity_terms)
            ):
                return False

    # 3. Pod's own required anti-affinity: node matching ANY term -> fail.
    for term in get_pod_anti_affinity_terms(affinity):
        if (
            term.topology_key in node.labels
            and (term.topology_key, node.labels[term.topology_key]) in meta.incoming_anti_pairs
        ):
            return False
    return True


# ---------------------------------------------------------------------------
# Combined runner (findNodesThatFit semantics for one pod)
# ---------------------------------------------------------------------------

# Registration names (predicates.go:56-110) — what Policy files and the
# algorithm provider registry refer to.
CHECK_NODE_UNSCHEDULABLE_PRED = "CheckNodeUnschedulable"
GENERAL_PRED = "GeneralPredicates"
HOST_NAME_PRED = "HostName"
POD_FITS_HOST_PORTS_PRED = "PodFitsHostPorts"
MATCH_NODE_SELECTOR_PRED = "MatchNodeSelector"
POD_FITS_RESOURCES_PRED = "PodFitsResources"
POD_TOLERATES_NODE_TAINTS_PRED = "PodToleratesNodeTaints"
EVEN_PODS_SPREAD_PRED = "EvenPodsSpread"
MATCH_INTER_POD_AFFINITY_PRED = "MatchInterPodAffinity"

# GeneralPredicates expands to these (predicates.go:1204 noncriticalPredicates
# + EssentialPredicates). THE one definition — the device mask
# (ops/filters.py) and the provider registry import it.
GENERAL_PREDICATES_EXPANSION = frozenset(
    {HOST_NAME_PRED, POD_FITS_HOST_PORTS_PRED, MATCH_NODE_SELECTOR_PRED, POD_FITS_RESOURCES_PRED}
)
_GENERAL_SET = GENERAL_PREDICATES_EXPANSION


def predicate_enabled(name: str, enabled) -> bool:
    """Is `name` on, given an enabled-set from Policy/provider config?
    None = default provider (everything the oracle implements)."""
    if enabled is None:
        return True
    if name in enabled:
        return True
    return name in _GENERAL_SET and GENERAL_PRED in enabled


@dataclass
class PredicateMetadata:
    """GetPredicateMetadata (metadata.go:333) equivalent: the per-cycle
    precomputation for one incoming pod against a snapshot. Carries the
    config's enabled-predicate set so every consumer (driver, preemption,
    nominated-pods two-pass) applies the same policy."""

    even_pods_spread: Optional[EvenPodsSpreadMetadata]
    pod_affinity: Optional[PodAffinityMetadata]
    enabled: Optional[frozenset] = None


def compute_predicate_metadata(
    pod: Pod,
    snapshot: Snapshot,
    enabled: Optional[frozenset] = None,
    affinity_index: Optional["SnapshotAffinityIndex"] = None,
    affinity_extra=(),
) -> PredicateMetadata:
    return PredicateMetadata(
        even_pods_spread=(
            compute_even_pods_spread_metadata(pod, snapshot)
            if predicate_enabled(EVEN_PODS_SPREAD_PRED, enabled)
            else None
        ),
        pod_affinity=(
            compute_pod_affinity_metadata(
                pod, snapshot, index=affinity_index, extra=affinity_extra
            )
            if predicate_enabled(MATCH_INTER_POD_AFFINITY_PRED, enabled)
            else None
        ),
        enabled=enabled,
    )


def pod_fits_on_node(
    pod: Pod,
    node_info: NodeInfo,
    meta: Optional[PredicateMetadata] = None,
    snapshot: Optional[Snapshot] = None,
) -> Tuple[bool, List[str]]:
    """All default-provider predicates in predicates.Ordering()
    (predicates.go:147-153), short-circuiting like podFitsOnNode
    (core/generic_scheduler.go:612 with alwaysCheckAllPredicates=false),
    honoring meta.enabled (Policy/provider predicate selection). Volume
    predicates run separately (volume.make_volume_checker — the driver's
    volume_checker seam)."""
    if meta is None:
        assert snapshot is not None, "need snapshot to compute metadata"
        meta = compute_predicate_metadata(pod, snapshot)
    enabled = meta.enabled
    checks = [
        (
            CHECK_NODE_UNSCHEDULABLE_PRED,
            ERR_NODE_UNSCHEDULABLE,
            lambda: check_node_unschedulable(pod, node_info),
        ),
        (HOST_NAME_PRED, ERR_POD_NOT_FIT_HOST, lambda: pod_fits_host(pod, node_info)),
        (
            POD_FITS_HOST_PORTS_PRED,
            ERR_POD_NOT_FIT_PORTS,
            lambda: pod_fits_host_ports(pod, node_info),
        ),
        (
            MATCH_NODE_SELECTOR_PRED,
            ERR_NODE_SELECTOR_NOT_MATCH,
            lambda: pod_match_node_selector(pod, node_info),
        ),
        (
            POD_FITS_RESOURCES_PRED,
            ERR_INSUFFICIENT.format("resources"),
            lambda: pod_fits_resources(pod, node_info),
        ),
        (
            POD_TOLERATES_NODE_TAINTS_PRED,
            ERR_TAINTS,
            lambda: pod_tolerates_node_taints(pod, node_info),
        ),
        (
            EVEN_PODS_SPREAD_PRED,
            ERR_TOPOLOGY_SPREAD,
            lambda: even_pods_spread_predicate(pod, node_info, meta.even_pods_spread),
        ),
        (
            MATCH_INTER_POD_AFFINITY_PRED,
            ERR_POD_AFFINITY,
            lambda: inter_pod_affinity_matches(pod, node_info, meta.pod_affinity),
        ),
    ]
    for name, reason, fn in checks:
        if not predicate_enabled(name, enabled):
            continue
        if not fn():
            return False, [reason]
    return True, []


def find_nodes_that_fit(pod: Pod, snapshot: Snapshot) -> List[str]:
    """findNodesThatFit (core/generic_scheduler.go:457) without adaptive
    sampling: full feasibility set, deterministic node order."""
    meta = compute_predicate_metadata(pod, snapshot)
    return [
        name
        for name, ni in snapshot.node_infos.items()
        if pod_fits_on_node(pod, ni, meta=meta)[0]
    ]


# ---------------------------------------------------------------------------
# Policy custom-argument predicates (api/types.go:83-121): labelsPresence →
# CheckNodeLabelPresence (predicates.go:1033), serviceAffinity →
# checkServiceAffinity (predicates.go:1123). Registered as framework Filter
# plugins by the factory — they gate the host commit path, not the device
# mask (arbitrary user-named predicates can't be jit statics).
# ---------------------------------------------------------------------------

def check_node_label_presence(pod, node_info, labels, presence: bool) -> bool:
    """CheckNodeLabelPresence (predicates.go:1033-1048): every listed label
    key must be present (presence=True) or absent (presence=False) on the
    node, values ignored."""
    node_labels = node_info.node.labels
    for label in labels:
        exists = label in node_labels
        if (exists and not presence) or (not exists and presence):
            return False
    return True


def get_pod_services(pod, services):
    """GetPodServices (client-go listers/core/v1/service_expansion.go):
    same-namespace services with a NON-EMPTY selector matching the pod's
    labels."""
    out = []
    for svc in services or []:
        if svc.namespace != pod.namespace or not svc.selector:
            continue
        if all(pod.labels.get(k) == v for k, v in svc.selector.items()):
            out.append(svc)
    return out


def service_affinity_precompute(pod, snapshot, labels, services):
    """The once-per-pod half of checkServiceAffinity
    (serviceAffinityMetadataProducer, predicates.go:1060-1082):
    (base_labels, anchor_candidates) where base_labels come from the pod's
    own nodeSelector and anchor_candidates is the ordered list of
    already-placed same-namespace pods with labels matching OURS —
    non-empty only when the pod belongs to some service. The per-node half
    (service_affinity_fits) applies the FilterOutPods exclusion against
    this list, so Filter stays O(1) amortized per node instead of
    O(cluster pods)."""
    base_labels = {k: pod.node_selector[k] for k in labels if k in pod.node_selector}
    candidates = []
    if len(labels) > len(base_labels) and get_pod_services(pod, services):
        for other in snapshot.all_pods():
            if other.namespace != pod.namespace or not other.node_name:
                continue
            if all(other.labels.get(k) == v for k, v in pod.labels.items()):
                candidates.append(other)
    return base_labels, candidates


def service_affinity_fits(pod, node_info, snapshot, labels, base_labels, candidates) -> bool:
    """Per-node half of checkServiceAffinity (predicates.go:1123-1160):
    backfill missing constraint keys from the FIRST anchor candidate not on
    the node under evaluation (FilterOutPods), then require the node to
    carry every constrained label with the constrained value."""
    affinity_labels = dict(base_labels)
    if len(labels) > len(affinity_labels):
        for other in candidates:
            if other.node_name == node_info.node.name:
                continue
            anchor_ni = snapshot.get(other.node_name)
            if anchor_ni is None:
                continue
            for k in labels:
                if k not in affinity_labels and k in anchor_ni.node.labels:
                    affinity_labels[k] = anchor_ni.node.labels[k]
            break
    node_labels = node_info.node.labels
    return all(node_labels.get(k) == v for k, v in affinity_labels.items())


def check_service_affinity(pod, node_info, snapshot, labels, services) -> bool:
    """checkServiceAffinity (predicates.go:1123-1160): force the listed
    node-label keys to stay homogeneous across a service's pods. One-shot
    convenience wrapper; the framework plugin path precomputes per pod."""
    base, cands = service_affinity_precompute(pod, snapshot, labels, services)
    return service_affinity_fits(pod, node_info, snapshot, labels, base, cands)
