"""Oracle priorities: scalar reference semantics for every Score function.

Re-implements pkg/scheduler/algorithm/priorities/ (map/reduce model,
MaxNodeScore=10 in this version — framework/v1alpha1/interface.go:77) as
plain Python. Parity target for kubernetes_tpu/ops/scores.py.

The default provider registers (algorithmprovider/defaults/defaults.go:128):
SelectorSpreadPriority(1), InterPodAffinityPriority(1),
LeastRequestedPriority(1), BalancedResourceAllocation(1),
NodePreferAvoidPodsPriority(10000), NodeAffinityPriority(1),
TaintTolerationPriority(1), ImageLocalityPriority(1); EvenPodsSpreadPriority
(1, feature-gated).
"""

from __future__ import annotations

import json
from typing import Callable, Dict, List, Optional, Tuple

from ..api.selectors import match_label_selector, match_node_selector_requirement
from ..api.types import (
    LabelSelector,
    Pod,
    RESOURCE_CPU,
    RESOURCE_MEMORY,
    TAINT_PREFER_NO_SCHEDULE,
)
from .nodeinfo import (
    NodeInfo,
    Snapshot,
    get_zone_key,
    normalized_image_name,
)
from .predicates import (
    get_soft_spread_constraints,
    node_labels_match_spread_constraints,
    pod_match_node_selector,
    pod_matches_spread_constraint,
    pod_matches_term,
)

MAX_NODE_SCORE = 10  # framework.MaxNodeScore in v1alpha1 (interface.go:77)

# image_locality.go:36-40
_MB = 1024 * 1024
IMAGE_MIN_THRESHOLD = 23 * _MB
IMAGE_MAX_THRESHOLD = 1000 * _MB

PREFER_AVOID_PODS_ANNOTATION = "scheduler.alpha.kubernetes.io/preferAvoidPods"

# Scores is node name -> int64 score.
Scores = Dict[str, int]


def _score_list(snapshot: Snapshot, fn: Callable[[NodeInfo], int]) -> Scores:
    return {name: fn(ni) for name, ni in snapshot.node_infos.items()}


def normalize_reduce(scores: Scores, max_priority: int = MAX_NODE_SCORE, reverse: bool = False) -> Scores:
    """priorities/reduce.go NormalizeReduce: scale to [0, max], optionally
    invert; all-zero input stays zero (or all-max when reversed)."""
    max_count = max(scores.values(), default=0)
    if max_count == 0:
        if reverse:
            return {k: max_priority for k in scores}
        return dict(scores)
    out = {}
    for k, v in scores.items():
        s = max_priority * v // max_count
        if reverse:
            s = max_priority - s
        out[k] = s
    return out


# ---------------------------------------------------------------------------
# Resource-based priorities (resource_allocation.go)
# ---------------------------------------------------------------------------

def _pod_scoring_request(pod: Pod) -> Tuple[int, int]:
    """calculatePodResourceRequest (resource_allocation.go:138): per-container
    non-zero-defaulted requests; overhead added via Quantity.Value() — whole
    cores for CPU, a reference quirk preserved deliberately (the node-side
    accumulation in calculateResource uses MilliValue instead)."""
    cpu = 0
    mem = 0
    for c in pod.containers:
        q = c.requests.get(RESOURCE_CPU)
        cpu += q.milli_value() if q is not None else 100
        q = c.requests.get(RESOURCE_MEMORY)
        mem += q.value() if q is not None else 200 * 1024 * 1024
    q = pod.overhead.get(RESOURCE_CPU)
    if q is not None:
        cpu += q.value()
    q = pod.overhead.get(RESOURCE_MEMORY)
    if q is not None:
        mem += q.value()
    return cpu, mem


def _allocatable_and_requested(pod: Pod, ni: NodeInfo) -> Tuple[int, int, int, int]:
    """calculateResourceAllocatableRequest for cpu and memory: requested uses
    the node's accumulated NON-ZERO requests plus the incoming pod's
    defaulted (non-zero) scoring request."""
    alloc = ni.node.allocatable_int()
    node_cpu, node_mem = ni.non_zero_requested()
    pod_cpu, pod_mem = _pod_scoring_request(pod)
    return (
        alloc.get(RESOURCE_CPU, 0),
        node_cpu + pod_cpu,
        alloc.get(RESOURCE_MEMORY, 0),
        node_mem + pod_mem,
    )


def _least_requested_score(requested: int, capacity: int) -> int:
    if capacity == 0 or requested > capacity:
        return 0
    return (capacity - requested) * MAX_NODE_SCORE // capacity


def _most_requested_score(requested: int, capacity: int) -> int:
    if capacity == 0 or requested > capacity:
        return 0
    return requested * MAX_NODE_SCORE // capacity


def least_requested_priority(pod: Pod, snapshot: Snapshot) -> Scores:
    """LeastRequestedPriority: mean of cpu/mem scores (weights 1,1)."""

    def fn(ni: NodeInfo) -> int:
        ac, rc, am, rm = _allocatable_and_requested(pod, ni)
        return (_least_requested_score(rc, ac) + _least_requested_score(rm, am)) // 2

    return _score_list(snapshot, fn)


def most_requested_priority(pod: Pod, snapshot: Snapshot) -> Scores:
    def fn(ni: NodeInfo) -> int:
        ac, rc, am, rm = _allocatable_and_requested(pod, ni)
        return (_most_requested_score(rc, ac) + _most_requested_score(rm, am)) // 2

    return _score_list(snapshot, fn)


def balanced_resource_allocation(pod: Pod, snapshot: Snapshot) -> Scores:
    """BalancedResourceAllocation (balanced_resource_allocation.go): score =
    (1 - |cpuFraction - memFraction|) * 10; 0 if either fraction >= 1."""

    def fn(ni: NodeInfo) -> int:
        ac, rc, am, rm = _allocatable_and_requested(pod, ni)
        cpu_frac = rc / ac if ac else 1.0
        mem_frac = rm / am if am else 1.0
        if cpu_frac >= 1 or mem_frac >= 1:
            return 0
        return int((1 - abs(cpu_frac - mem_frac)) * MAX_NODE_SCORE)

    return _score_list(snapshot, fn)


# ---------------------------------------------------------------------------
# RequestedToCapacityRatio (requested_to_capacity_ratio.go) + ResourceLimits
# (resource_limits.go) — Policy-configurable / feature-gated resource scores
# ---------------------------------------------------------------------------

# default shape prefers least-utilized nodes: f(0%)=10, f(100%)=0
# (requested_to_capacity_ratio.go:40)
DEFAULT_RTCR_SHAPE: Tuple[Tuple[int, int], ...] = ((0, 10), (100, 0))
DEFAULT_RTCR_RESOURCES: Tuple[Tuple[str, int], ...] = ((RESOURCE_CPU, 1), (RESOURCE_MEMORY, 1))


def _go_div(a: int, b: int) -> int:
    """Go integer division truncates toward zero; Python // floors — the
    difference shows on down-sloping shape segments (negative numerators)."""
    q = abs(a) // abs(b)
    return q if (a < 0) == (b < 0) else -q


def validate_function_shape(shape) -> None:
    """NewFunctionShape preconditions (requested_to_capacity_ratio.go:53-86):
    nonempty, strictly increasing utilization in [0, 100], score in [0, 10]."""
    if not shape:
        raise ValueError("at least one point must be specified")
    for i, (u, s) in enumerate(shape):
        if i and shape[i - 1][0] >= u:
            raise ValueError("utilization values must be sorted")
        if not (0 <= u <= 100):
            raise ValueError("utilization values must be in [0, 100]")
        if not (0 <= s <= MAX_NODE_SCORE):
            raise ValueError("score values must be in [0, 10]")


def _broken_linear(shape: Tuple[Tuple[int, int], ...], p: int) -> int:
    """buildBrokenLinearFunction (requested_to_capacity_ratio.go:144-167):
    piecewise-linear through (utilization, score) points, integer math,
    constant extrapolation outside the shape's utilization range."""
    for i, (u, s) in enumerate(shape):
        if p <= u:
            if i == 0:
                return shape[0][1]
            u0, s0 = shape[i - 1]
            return s0 + _go_div((s - s0) * (p - u0), u - u0)
    return shape[-1][1]


def _rtcr_resource_values(pod: Pod, ni: NodeInfo, resource: str) -> Tuple[int, int]:
    """calculateResourceAllocatableRequest (resource_allocation.go:101-123):
    cpu/memory use the non-zero-defaulted accumulation + the incoming pod's
    scoring request; other resources use the plain requested accumulation.
    Unknown resources score (0, 0)."""
    if resource in (RESOURCE_CPU, RESOURCE_MEMORY):
        ac, rc, am, rm = _allocatable_and_requested(pod, ni)
        return (ac, rc) if resource == RESOURCE_CPU else (am, rm)
    a = ni.node.allocatable_int().get(resource)
    if a is None:
        return 0, 0
    node_req = ni.requested().get(resource, 0)
    pod_req = 0
    for c in pod.containers:
        q = c.requests.get(resource)
        if q is not None:
            pod_req += q.value()
    return a, node_req + pod_req


def requested_to_capacity_ratio_priority(
    pod: Pod,
    snapshot: Snapshot,
    shape: Tuple[Tuple[int, int], ...] = DEFAULT_RTCR_SHAPE,
    resources: Tuple[Tuple[str, int], ...] = DEFAULT_RTCR_RESOURCES,
) -> Scores:
    """RequestedToCapacityRatioResourceAllocationPriority
    (requested_to_capacity_ratio.go:115-142): per resource, utilization% is
    mapped through the broken-linear shape; full/overflowing nodes evaluate
    at 100% utilization. Resources scoring 0 are EXCLUDED from the weighted
    mean (both numerator and denominator — a reference quirk), and the mean
    is rounded half away from zero (math.Round)."""

    def fn(ni: NodeInfo) -> int:
        node_score = 0
        weight_sum = 0
        for resource, weight in resources:
            cap, req = _rtcr_resource_values(pod, ni, resource)
            if cap == 0 or req > cap:
                p = 100
            else:
                p = 100 - (cap - req) * 100 // cap
            s = _broken_linear(shape, p)
            if s > 0:
                node_score += s * weight
                weight_sum += weight
        if weight_sum == 0:
            return 0
        # math.Round for a non-negative ratio == floor(x + 1/2)
        return (2 * node_score + weight_sum) // (2 * weight_sum)

    return _score_list(snapshot, fn)


def _pod_resource_limits(pod: Pod) -> Tuple[int, int]:
    """getResourceLimits (resource_limits.go:92-107): sum of container
    limits, then elementwise max against each init container's limits.
    CPU in millicores, memory in bytes (Resource.Add semantics)."""
    cpu = 0
    mem = 0
    for c in pod.containers:
        q = c.limits.get(RESOURCE_CPU)
        if q is not None:
            cpu += q.milli_value()
        q = c.limits.get(RESOURCE_MEMORY)
        if q is not None:
            mem += q.value()
    for ic in pod.init_containers:
        q = ic.limits.get(RESOURCE_CPU)
        if q is not None:
            cpu = max(cpu, q.milli_value())
        q = ic.limits.get(RESOURCE_MEMORY)
        if q is not None:
            mem = max(mem, q.value())
    return cpu, mem


def resource_limits_priority(pod: Pod, snapshot: Snapshot) -> Scores:
    """ResourceLimitsPriorityMap (resource_limits.go:36-80): score 1 when the
    node can satisfy the pod's cpu OR memory limit (both quantities nonzero),
    else 0 — a deliberate coarse tie-breaker, no normalization (Reduce nil)."""
    limit_cpu, limit_mem = _pod_resource_limits(pod)

    def fn(ni: NodeInfo) -> int:
        alloc = ni.node.allocatable_int()
        ac = alloc.get(RESOURCE_CPU, 0)
        am = alloc.get(RESOURCE_MEMORY, 0)
        cpu_ok = limit_cpu != 0 and ac != 0 and limit_cpu <= ac
        mem_ok = limit_mem != 0 and am != 0 and limit_mem <= am
        return 1 if (cpu_ok or mem_ok) else 0

    return _score_list(snapshot, fn)


# ---------------------------------------------------------------------------
# NodeAffinity / TaintToleration / NodePreferAvoidPods / ImageLocality
# ---------------------------------------------------------------------------

def node_affinity_priority(pod: Pod, snapshot: Snapshot) -> Scores:
    """CalculateNodeAffinityPriorityMap + NormalizeReduce(10, false):
    sum of weights of matching preferred terms."""

    def fn(ni: NodeInfo) -> int:
        count = 0
        aff = pod.affinity
        if aff is not None and aff.node_affinity is not None:
            for pref in aff.node_affinity.preferred:
                if pref.weight == 0:
                    continue
                # Preference uses matchExpressions only, as a plain selector
                # (NodeSelectorRequirementsAsSelector) — an empty preference
                # (no expressions) matches everything, unlike required terms.
                if all(
                    match_node_selector_requirement(r, ni.node.labels)
                    for r in pref.preference.match_expressions
                ):
                    count += pref.weight
        return count

    return normalize_reduce(_score_list(snapshot, fn))


def taint_toleration_priority(pod: Pod, snapshot: Snapshot) -> Scores:
    """ComputeTaintTolerationPriorityMap + NormalizeReduce(10, true): count
    intolerable PreferNoSchedule taints; fewer is better. Only tolerations
    with empty or PreferNoSchedule effect participate
    (getAllTolerationPreferNoSchedule)."""
    tols = [t for t in pod.tolerations if t.effect in ("", TAINT_PREFER_NO_SCHEDULE)]

    def fn(ni: NodeInfo) -> int:
        return sum(
            1
            for taint in ni.node.taints
            if taint.effect == TAINT_PREFER_NO_SCHEDULE
            and not any(t.tolerates(taint) for t in tols)
        )

    return normalize_reduce(_score_list(snapshot, fn), reverse=True)


def node_prefer_avoid_pods_priority(pod: Pod, snapshot: Snapshot) -> Scores:
    """CalculateNodePreferAvoidPodsPriorityMap: 0 when the node's
    preferAvoidPods annotation lists the pod's RC/RS controller, else 10.
    Weight 10000 in the default registry makes this nearly a hard filter."""
    controller = None
    for ref in pod.owner_references:
        if ref.get("controller"):
            controller = ref
            break
    if controller is not None and controller.get("kind") not in ("ReplicationController", "ReplicaSet"):
        controller = None

    def fn(ni: NodeInfo) -> int:
        if controller is None:
            return MAX_NODE_SCORE
        ann = ni.node.annotations.get(PREFER_AVOID_PODS_ANNOTATION, "")
        if not ann:
            return MAX_NODE_SCORE
        try:
            avoids = json.loads(ann)
        except ValueError:
            return MAX_NODE_SCORE
        if not isinstance(avoids, dict):
            return MAX_NODE_SCORE
        entries = avoids.get("preferAvoidPods")
        if not isinstance(entries, list):
            return MAX_NODE_SCORE
        for avoid in entries:
            if not isinstance(avoid, dict):
                continue
            sig = avoid.get("podSignature")
            ref = (sig.get("podController") if isinstance(sig, dict) else None) or {}
            if ref.get("kind") == controller.get("kind") and ref.get("uid") == controller.get("uid"):
                return 0
        return MAX_NODE_SCORE

    return _score_list(snapshot, fn)


def image_locality_priority(pod: Pod, snapshot: Snapshot) -> Scores:
    """ImageLocalityPriorityMap (image_locality.go): sum of image sizes
    already on the node, scaled by image spread (numNodes/totalNodes),
    clamped to [23MB, 1000MB] and mapped to [0, 10]."""
    total_nodes = len(snapshot.node_infos)
    image_node_counts = snapshot.total_image_nodes()

    def fn(ni: NodeInfo) -> int:
        sizes = ni.image_sizes()
        total = 0
        for c in pod.containers:
            name = normalized_image_name(c.image)
            if name in sizes:
                spread = image_node_counts.get(name, 0) / total_nodes if total_nodes else 0
                total += int(sizes[name] * spread)
        s = min(max(total, IMAGE_MIN_THRESHOLD), IMAGE_MAX_THRESHOLD)
        return MAX_NODE_SCORE * (s - IMAGE_MIN_THRESHOLD) // (IMAGE_MAX_THRESHOLD - IMAGE_MIN_THRESHOLD)

    return _score_list(snapshot, fn)


# ---------------------------------------------------------------------------
# SelectorSpread (selector_spreading.go)
# ---------------------------------------------------------------------------

ZONE_WEIGHTING = 2.0 / 3.0


def selector_spread_priority(
    pod: Pod, snapshot: Snapshot, selectors: Optional[List[LabelSelector]] = None
) -> Scores:
    """CalculateSpreadPriorityMap/Reduce: count same-namespace, non-deleting
    pods matching ALL controller selectors (services/RC/RS/SS of the pod);
    fewer is better, blended 1/3 node-level + 2/3 zone-level."""
    selectors = selectors or []
    counts: Scores = {}
    for name, ni in snapshot.node_infos.items():
        if not selectors:
            counts[name] = 0
            continue
        c = 0
        for ep in ni.pods:
            if ep.namespace != pod.namespace or ep.deletion_timestamp is not None:
                continue
            if all(match_label_selector(sel, ep.labels) for sel in selectors):
                c += 1
        counts[name] = c

    max_by_node = max(counts.values(), default=0)
    counts_by_zone: Dict[str, int] = {}
    for name, ni in snapshot.node_infos.items():
        zone = get_zone_key(ni.node)
        if zone:
            counts_by_zone[zone] = counts_by_zone.get(zone, 0) + counts[name]
    max_by_zone = max(counts_by_zone.values(), default=0)

    out: Scores = {}
    for name, ni in snapshot.node_infos.items():
        f = float(MAX_NODE_SCORE)
        if max_by_node > 0:
            f = MAX_NODE_SCORE * ((max_by_node - counts[name]) / max_by_node)
        if counts_by_zone:
            zone = get_zone_key(ni.node)
            if zone:
                zf = float(MAX_NODE_SCORE)
                if max_by_zone > 0:
                    zf = MAX_NODE_SCORE * ((max_by_zone - counts_by_zone[zone]) / max_by_zone)
                f = f * (1.0 - ZONE_WEIGHTING) + ZONE_WEIGHTING * zf
        out[name] = int(f)
    return out


# ---------------------------------------------------------------------------
# EvenPodsSpread soft constraints (even_pods_spread.go)
# ---------------------------------------------------------------------------

def even_pods_spread_priority(pod: Pod, snapshot: Snapshot) -> Scores:
    """CalculateEvenPodsSpreadPriority: total matching count minus the node's
    own count, normalized by (total - minCount) * 10. Candidate nodes are
    those passing the pod's node selector/affinity AND carrying all soft
    constraint topology keys; others score 0.

    NOTE (reference quirk, even_pods_spread.go:112): the per-node sum counts
    matching pods over ALL namespaces — unlike the hard-constraint predicate
    metadata which restricts to the incoming pod's namespace."""
    constraints = get_soft_spread_constraints(pod)
    result: Scores = {name: 0 for name in snapshot.node_infos}
    if not constraints:
        return result

    # initialize: candidate nodes must match spread constraints' keys
    candidate: Dict[str, bool] = {}
    pair_counts: Dict[Tuple[str, str], int] = {}
    for name, ni in snapshot.node_infos.items():
        if not node_labels_match_spread_constraints(ni.node.labels, constraints):
            continue
        candidate[name] = True
        for c in constraints:
            pair_counts.setdefault((c.topology_key, ni.node.labels[c.topology_key]), 0)

    # count matches per topology pair over nodes that ALSO pass the pod's
    # node selector/affinity
    for name, ni in snapshot.node_infos.items():
        if not pod_match_node_selector(pod, ni):
            continue
        if not node_labels_match_spread_constraints(ni.node.labels, constraints):
            continue
        for c in constraints:
            pair = (c.topology_key, ni.node.labels[c.topology_key])
            if pair not in pair_counts:
                continue
            pair_counts[pair] += sum(
                1 for ep in ni.pods if pod_matches_spread_constraint(ep.labels, c)
            )

    node_counts: Scores = {}
    total = 0
    min_count = None
    for name, ni in snapshot.node_infos.items():
        if name not in candidate:
            continue
        cnt = 0
        for c in constraints:
            tp_val = ni.node.labels.get(c.topology_key)
            if tp_val is not None:
                cnt += pair_counts.get((c.topology_key, tp_val), 0)
                total += pair_counts.get((c.topology_key, tp_val), 0)
        node_counts[name] = cnt
        if min_count is None or cnt < min_count:
            min_count = cnt

    if min_count is None:
        return result
    max_min_diff = total - min_count
    for name in snapshot.node_infos:
        if name not in node_counts:
            result[name] = 0
        elif max_min_diff == 0:
            result[name] = MAX_NODE_SCORE
        else:
            result[name] = int(MAX_NODE_SCORE * ((total - node_counts[name]) / max_min_diff))
    return result


# ---------------------------------------------------------------------------
# InterPodAffinity priority (interpod_affinity.go)
# ---------------------------------------------------------------------------

DEFAULT_HARD_POD_AFFINITY_WEIGHT = 1  # api/types.go DefaultHardPodAffinitySymmetricWeight


def inter_pod_affinity_priority(
    pod: Pod, snapshot: Snapshot, hard_pod_affinity_weight: int = DEFAULT_HARD_POD_AFFINITY_WEIGHT
) -> Scores:
    """CalculateInterPodAffinityPriority: for every existing pod, accumulate
    term weights onto all nodes sharing the term's topology key with the
    existing pod's node; includes the symmetric contributions of existing
    pods' own (anti-)affinity toward the incoming pod. Final min-max
    normalization to [0, 10]."""
    aff = pod.affinity
    has_aff = aff is not None and aff.pod_affinity is not None
    has_anti = aff is not None and aff.pod_anti_affinity is not None

    node_list = list(snapshot.node_infos.values())
    counts = {ni.node.name: 0 for ni in node_list}

    def process_term(term, owner: Pod, to_check: Pod, fixed_node, weight: int) -> None:
        if weight == 0:
            return
        if not pod_matches_term(to_check, owner, term):
            return
        if not term.topology_key:
            return
        fixed_val = fixed_node.labels.get(term.topology_key)
        if fixed_val is None:
            return
        for ni in node_list:
            if ni.node.labels.get(term.topology_key) == fixed_val:
                counts[ni.node.name] += weight

    for ni in node_list:
        # When the incoming pod has constraints, iterate ALL existing pods on
        # the node; otherwise only pods that themselves have constraints.
        pods_iter = ni.pods if (has_aff or has_anti) else ni.pods_with_affinity()
        ep_node = ni.node
        for ep in pods_iter:
            ep_aff = ep.affinity
            if has_aff:
                for w in aff.pod_affinity.preferred:
                    process_term(w.pod_affinity_term, pod, ep, ep_node, w.weight)
            if has_anti:
                for w in aff.pod_anti_affinity.preferred:
                    process_term(w.pod_affinity_term, pod, ep, ep_node, -w.weight)
            if ep_aff is not None and ep_aff.pod_affinity is not None:
                if hard_pod_affinity_weight > 0:
                    for term in ep_aff.pod_affinity.required:
                        process_term(term, ep, pod, ep_node, hard_pod_affinity_weight)
                for w in ep_aff.pod_affinity.preferred:
                    process_term(w.pod_affinity_term, ep, pod, ep_node, w.weight)
            if ep_aff is not None and ep_aff.pod_anti_affinity is not None:
                for w in ep_aff.pod_anti_affinity.preferred:
                    process_term(w.pod_affinity_term, ep, pod, ep_node, -w.weight)

    max_count = max(counts.values(), default=0)
    min_count = min(counts.values(), default=0)
    max_count = max(max_count, 0)
    min_count = min(min_count, 0)
    diff = max_count - min_count
    out: Scores = {}
    for name, c in counts.items():
        out[name] = int(MAX_NODE_SCORE * ((c - min_count) / diff)) if diff > 0 else 0
    return out


# ---------------------------------------------------------------------------
# Default weighted sum (PrioritizeNodes, core/generic_scheduler.go:699)
# ---------------------------------------------------------------------------

DEFAULT_PRIORITY_WEIGHTS = {
    "SelectorSpreadPriority": 1,
    "InterPodAffinityPriority": 1,
    "LeastRequestedPriority": 1,
    "BalancedResourceAllocation": 1,
    "NodePreferAvoidPodsPriority": 10000,
    "NodeAffinityPriority": 1,
    "TaintTolerationPriority": 1,
    "ImageLocalityPriority": 1,
    "EvenPodsSpreadPriority": 1,
    # not in the default provider (ClusterAutoscalerProvider swaps it in for
    # LeastRequested); weight 0 unless a config raises it
    "MostRequestedPriority": 0,
    # Policy-argument custom priority (requested_to_capacity_ratio.go) and
    # the ResourceLimits feature-gated tie-breaker (resource_limits.go):
    # active only when a config names them
    "RequestedToCapacityRatioPriority": 0,
    "ResourceLimitsPriority": 0,
}


def prioritize_nodes(
    pod: Pod,
    snapshot: Snapshot,
    weights: Optional[Dict[str, int]] = None,
    spread_selectors: Optional[List[LabelSelector]] = None,
    enable_even_pods_spread: bool = True,
    rtcr: Optional[Tuple[Tuple[Tuple[int, int], ...], Tuple[Tuple[str, int], ...]]] = None,
) -> Scores:
    w = dict(DEFAULT_PRIORITY_WEIGHTS)
    if weights:
        w.update(weights)
    rtcr_shape, rtcr_resources = rtcr if rtcr is not None else (
        DEFAULT_RTCR_SHAPE,
        DEFAULT_RTCR_RESOURCES,
    )
    # each map is O(nodes×pods): only compute the ones with weight > 0
    makers: Dict[str, Callable[[], Scores]] = {
        "SelectorSpreadPriority": lambda: selector_spread_priority(pod, snapshot, spread_selectors),
        "InterPodAffinityPriority": lambda: inter_pod_affinity_priority(pod, snapshot),
        "MostRequestedPriority": lambda: most_requested_priority(pod, snapshot),
        "LeastRequestedPriority": lambda: least_requested_priority(pod, snapshot),
        "BalancedResourceAllocation": lambda: balanced_resource_allocation(pod, snapshot),
        "NodePreferAvoidPodsPriority": lambda: node_prefer_avoid_pods_priority(pod, snapshot),
        "NodeAffinityPriority": lambda: node_affinity_priority(pod, snapshot),
        "TaintTolerationPriority": lambda: taint_toleration_priority(pod, snapshot),
        "ImageLocalityPriority": lambda: image_locality_priority(pod, snapshot),
        "RequestedToCapacityRatioPriority": lambda: requested_to_capacity_ratio_priority(
            pod, snapshot, rtcr_shape, rtcr_resources
        ),
        "ResourceLimitsPriority": lambda: resource_limits_priority(pod, snapshot),
    }
    if enable_even_pods_spread:
        makers["EvenPodsSpreadPriority"] = lambda: even_pods_spread_priority(pod, snapshot)
    total: Scores = {name: 0 for name in snapshot.node_infos}
    for pname, make in makers.items():
        weight = w.get(pname, 0)
        if not weight:
            continue
        for node_name, s in make().items():
            total[node_name] += weight * s
    return total


# ---------------------------------------------------------------------------
# Policy custom-argument priorities (api/types.go:94-137): labelPreference →
# NodeLabelPrioritizer (node_label.go:46), serviceAntiAffinity →
# ServiceAntiAffinity map/reduce (selector_spreading.go:211-277).
# Registered as framework Score plugins by the factory.
# ---------------------------------------------------------------------------

def node_label_priority(pod: Pod, snapshot: Snapshot, label: str, presence: bool) -> Scores:
    """CalculateNodeLabelPriorityMap: MaxNodeScore when the node's
    has-the-label state matches `presence`, else 0. No normalization."""

    def fn(ni: NodeInfo) -> int:
        exists = label in ni.node.labels
        return MAX_NODE_SCORE if exists == presence else 0

    return _score_list(snapshot, fn)


def service_anti_affinity_priority(
    pod: Pod, snapshot: Snapshot, label: str, services
) -> Scores:
    """ServiceAntiAffinity map+reduce (selector_spreading.go:211-277):
    map counts same-namespace pods matching the pod's FIRST service
    selector per node; reduce groups nodes by the configured label's value
    and scores maxScore * (total - group) / total — label-less nodes score
    0, zero service pods scores maxScore everywhere labeled."""
    from .predicates import get_pod_services

    matching = get_pod_services(pod, services)
    first_selector = dict(matching[0].selector) if matching else None

    def count_on(ni: NodeInfo) -> int:
        if first_selector is None:
            return 0
        c = 0
        for p in ni.pods:
            if p.namespace != pod.namespace:
                continue
            if all(p.labels.get(k) == v for k, v in first_selector.items()):
                c += 1
        return c

    raw = {name: count_on(ni) for name, ni in snapshot.node_infos.items()}
    num_service_pods = sum(raw.values())
    pod_counts: Dict[str, int] = {}
    label_of: Dict[str, str] = {}
    for name, ni in snapshot.node_infos.items():
        if label in ni.node.labels:
            val = ni.node.labels[label]
            label_of[name] = val
            pod_counts[val] = pod_counts.get(val, 0) + raw[name]
    out: Scores = {}
    for name in snapshot.node_infos:
        val = label_of.get(name)
        if val is None:
            out[name] = 0
            continue
        if num_service_pods > 0:
            out[name] = int(
                MAX_NODE_SCORE * (num_service_pods - pod_counts[val]) / num_service_pods
            )
        else:
            out[name] = MAX_NODE_SCORE
    return out
