"""Per-plane circuit breakers: the runtime half of the kill switches.

Every device-residency plane (PRs 2-9) ships a bit-identical legacy host
path behind a STATIC env kill switch (``KTPU_INGEST_PLANE=0``, ...), but
nothing flips those paths at runtime: a dead uploader thread, an XLA
dispatch error, or a shadow-audit divergence either killed the drain or
silently stalled it. This module converts the six independent switches
into one degradation ladder:

* ``PlaneBreaker`` — the classic closed → open → half-open machine, with
  counted failure thresholds and a wall-clock cool-down on an INJECTABLE
  clock (tests never sleep). A closed breaker is ONE attribute read on
  the hot path (``breaker.closed``, a plain bool — the FlightRecorder
  disabled-path idiom); only a non-closed breaker ever takes the lock.

* ``BreakerBoard`` — one breaker per plane boundary that can fail at
  runtime (ingest/term slab uploads + gathers, the fold dispatch, the
  commit arbiter + pipeline worker, the columnar-cache scatters, the
  mirror's patch scatters), sharing ONE audited lock (role "faults",
  always a leaf: reporters may hold a plane lock when they report, the
  board never acquires anything while holding its own).

The soundness argument is the ON==OFF parity discipline of PRs 2-9: an
open breaker routes that plane's dispatches to its existing legacy host
path, which is bit-identical by construction, so tripping a breaker can
degrade throughput but never placements. A half-open breaker admits ONE
probe batch; the driver re-closes it only after the PR 10 shadow audit
(device_bank_divergence + columns cross-check) comes back clean at the
next safe sync point — resync-before-close, audit-gated.

Trip-side effects (gauges, the recovery queue) happen on the reporter's
thread under the board lock; the RECOVERY ACTIONS themselves (bank
resync, uploader restart, columns re-attach — faults/recover.py) only
ever run on the driver thread at its post-sync safe point, because they
touch driver-confined mirror state.
"""

from __future__ import annotations

import logging
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..analysis.lockorder import audited_lock
from ..metrics import metrics as M

logger = logging.getLogger("kubernetes_tpu.faults")

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

#: numeric projection for the ktpu_plane_breaker_state gauge
STATE_VALUE = {CLOSED: 0.0, HALF_OPEN: 1.0, OPEN: 2.0}

#: the plane boundaries that can fail at runtime — each maps to a legacy
#: host path (ingest/terms/fold/commit/columns) or a full-reupload resync
#: (mirror); see faults/recover.py for each plane's recovery action
PLANES = ("ingest", "terms", "fold", "commit", "columns", "mirror")

#: consecutive failures before a closed breaker trips
DEFAULT_THRESHOLD = 3
#: seconds an open breaker waits before offering a half-open probe
DEFAULT_COOLDOWN_S = 5.0
#: failed probes double the cool-down up to this multiple (escalation)
MAX_COOLDOWN_FACTOR = 8


class PlaneBreaker:
    """One plane's closed → open → half-open machine. All transitions run
    under the BOARD's shared lock (passed in); the hot-path gate is the
    plain ``closed`` bool, written only inside transitions."""

    def __init__(
        self,
        plane: str,
        lock,
        threshold: int = DEFAULT_THRESHOLD,
        cooldown_s: float = DEFAULT_COOLDOWN_S,
        clock: Callable[[], float] = time.monotonic,
        window_s: Optional[float] = None,
    ):
        self.plane = plane
        self._lock = lock
        self.threshold = int(threshold)
        self.cooldown_s = float(cooldown_s)
        # consecutive-failure window: a fault arriving more than this
        # after the previous one restarts the count (sporadic faults
        # spread over hours must not accumulate into a trip). Decoupled
        # from the cool-down: plane boundaries fire at batch cadence,
        # which can be much slower than the probe cadence.
        self.window_s = (
            float(window_s) if window_s is not None
            else max(30.0, self.cooldown_s * 10)
        )
        self._clock = clock
        #: hot-path gate — True iff state == CLOSED. Plain attribute so
        #: the covered dispatch pays one read, no lock (torn reads are
        #: benign: both paths are correct, only coverage shifts a batch).
        # ktpu: allow(KTPU006) mirror of `state == CLOSED` kept as a
        # plain bool ON PURPOSE: the covered dispatch reads it lock-free
        # (one attribute read per batch; a torn/stale read routes one
        # batch to the legacy path — benign). All WRITES happen under
        # the board lock in the transition methods.
        self.closed = True
        self.state = CLOSED  # ktpu: guarded-by(self._lock)
        # ktpu: guarded-by(self._lock) consecutive failures while closed
        self.failures = 0
        self.trips = 0  # ktpu: guarded-by(self._lock)
        self.probes_passed = 0  # ktpu: guarded-by(self._lock)
        self.probes_failed = 0  # ktpu: guarded-by(self._lock)
        # ktpu: guarded-by(self._lock) a probe batch is in flight
        self.probing = False
        self.last_reason: Optional[str] = None  # ktpu: guarded-by(self._lock)
        self._last_failure_ts = 0.0  # ktpu: guarded-by(self._lock)
        self._open_until = 0.0  # ktpu: guarded-by(self._lock)
        # ktpu: guarded-by(self._lock) escalates on failed probes
        self._cooldown = float(cooldown_s)
        # ktpu: guarded-by(self._lock) bounded (16 entries)
        self.trip_log: List[Tuple[float, str]] = []

    # -- transitions (board lock held by callers or taken here) --------------

    # ktpu: holds(self._lock)
    def _trip_locked(self, reason: str) -> None:
        self.state = OPEN
        self.closed = False
        self.probing = False
        self.trips += 1
        self.last_reason = reason
        self._open_until = self._clock() + self._cooldown
        self.trip_log.append((time.time(), reason))
        del self.trip_log[:-16]
        M.plane_breaker_state.set(STATE_VALUE[OPEN], self.plane)
        M.plane_trips.inc(self.plane, reason)
        logger.warning(
            "plane breaker TRIPPED: %s (%s) — routing to the legacy host "
            "path for %.1fs, then probing",
            self.plane, reason, self._cooldown,
        )

    def record_failure(self, reason: str, force: bool = False) -> bool:
        """One fault at this plane's boundary. Returns True when this
        report TRIPPED the breaker (closed → open, or a failed probe
        re-opening) — the board queues the recovery action then.
        ``force=True`` trips immediately regardless of the counted
        threshold (shadow-audit divergence: the banks are already known
        wrong, waiting for two more batches of wrong is not prudence)."""
        with self._lock:
            if self.state == OPEN:
                self.last_reason = reason
                return False
            if self.state == HALF_OPEN:
                self._probe_failed_locked(reason)
                return True
            # windowed counting without a hot-path success hook: a fault
            # arriving more than window_s after the previous one restarts
            # the consecutive count (sporadic faults spread over hours
            # must not accumulate into a trip)
            now = self._clock()
            if now - self._last_failure_ts > self.window_s:
                self.failures = 0
            self._last_failure_ts = now
            self.failures += 1
            self.last_reason = reason
            if force or self.failures >= self.threshold:
                self.failures = 0
                self._trip_locked(reason)
                return True
            return False

    def allow_probe(self) -> bool:
        """Non-closed gate: True exactly once per cool-down expiry — the
        caller's next covered dispatch is the probe batch. While a probe
        is in flight every other dispatch stays on the legacy path."""
        with self._lock:
            if self.state == OPEN and self._clock() >= self._open_until:
                self.state = HALF_OPEN
                self.probing = True
                M.plane_breaker_state.set(STATE_VALUE[HALF_OPEN], self.plane)
                logger.info(
                    "plane breaker %s: half-open — probing one covered batch",
                    self.plane,
                )
                return True
            if self.state == HALF_OPEN and not self.probing:
                self.probing = True
                return True
            return False

    def probe_passed(self) -> None:
        """The probe batch completed AND the shadow audit came back clean
        (the driver's _fault_service is the only caller): re-close and
        reset the cool-down escalation."""
        with self._lock:
            if self.state == CLOSED:
                return
            self.state = CLOSED
            self.closed = True
            self.probing = False
            self.failures = 0
            self.probes_passed += 1
            self._cooldown = self.cooldown_s
            M.plane_breaker_state.set(STATE_VALUE[CLOSED], self.plane)
            logger.info("plane breaker %s: probe clean — CLOSED", self.plane)

    # ktpu: holds(self._lock)
    def _probe_failed_locked(self, reason: str) -> None:
        self.probes_failed += 1
        self._cooldown = min(
            self._cooldown * 2, self.cooldown_s * MAX_COOLDOWN_FACTOR
        )
        self._trip_locked(f"probe:{reason}")

    def probe_failed(self, reason: str) -> None:
        """The probe batch faulted or its shadow audit found divergence:
        back to open with the cool-down doubled (bounded escalation)."""
        with self._lock:
            if self.state == CLOSED:
                return
            self._probe_failed_locked(reason)

    # -- readers -------------------------------------------------------------

    def census(self) -> Dict[str, object]:
        with self._lock:
            return {
                "state": self.state,
                "failures": self.failures,
                "trips": self.trips,
                "probes_passed": self.probes_passed,
                "probes_failed": self.probes_failed,
                "probing": self.probing,
                "last_reason": self.last_reason,
                "cooldown_s": self._cooldown,
                "open_remaining_s": (
                    max(self._open_until - self._clock(), 0.0)
                    if self.state == OPEN else 0.0
                ),
            }


class BreakerBoard:
    """All plane breakers plus the trip → recovery handshake.

    Faults are REPORTED from whatever thread hit them (driver, commit
    worker, uploader, informer); recovery ACTIONS are queued here and
    executed only by the driver at its post-sync safe point
    (``Scheduler._fault_service`` → ``faults.recover.run_recoveries``).
    ``quiet`` is the one-attribute-read hot-path gate: True while every
    breaker is closed and nothing is pending, so a healthy drain pays a
    single bool read per plane gate and one per batch."""

    def __init__(
        self,
        clock: Callable[[], float] = time.monotonic,
        threshold: int = DEFAULT_THRESHOLD,
        cooldown_s: float = DEFAULT_COOLDOWN_S,
        window_s: Optional[float] = None,
    ):
        # role "faults": always a leaf — reporters hold plane locks when
        # they report; nothing is ever acquired while this lock is held
        self._lock = audited_lock("faults")
        self.clock = clock
        self.breakers: Dict[str, PlaneBreaker] = {
            p: PlaneBreaker(
                p, self._lock, threshold=threshold, cooldown_s=cooldown_s,
                clock=clock, window_s=window_s,
            )
            for p in PLANES
        }
        #: hot-path gate: True while every breaker is closed AND no
        #: recovery is pending — the healthy steady state. Plain bool.
        # ktpu: allow(KTPU006) the board-wide fast-path bool (board.quiet
        # is THE one-attribute-read hot-path gate): read lock-free by
        # design, recomputed only under the lock (_recompute_quiet_locked);
        # a stale read costs one extra/missed service pass, never safety.
        self.quiet = True
        self._pending_recovery: List[str] = []  # ktpu: guarded-by(self._lock)
        for p in PLANES:
            M.plane_breaker_state.set(STATE_VALUE[CLOSED], p)

    def breaker(self, plane: str) -> PlaneBreaker:
        return self.breakers[plane]

    # ktpu: holds(self._lock)
    def _recompute_quiet_locked(self) -> None:
        self.quiet = not self._pending_recovery and all(
            b.state == CLOSED for b in self.breakers.values()
        )

    def record_failure(self, plane: str, reason: str, force: bool = False) -> bool:
        """Report one fault; on a trip, queue the plane's recovery for
        the driver's next safe point. A FORCED report queues the
        recovery even when the breaker is already open — forced means
        known-wrong state (a dead uploader, a divergent audit), and its
        repair action must run regardless of what tripped the breaker
        first (an uploader dying during another fault's cool-down would
        otherwise never be restarted: the clean probe would re-close the
        breaker right over the dead thread). Callable from any thread
        (may hold a plane lock — the board lock is a leaf)."""
        b = self.breakers.get(plane)
        if b is None:
            return False
        tripped = b.record_failure(reason, force=force)
        with self._lock:
            if (tripped or force) and plane not in self._pending_recovery:
                self._pending_recovery.append(plane)
            self._recompute_quiet_locked()
        return tripped

    def ok(self, plane: str) -> bool:
        """Dispatch gate for a plane: covered while closed, or exactly
        one probe batch when a cool-down expired. (The hot path short-
        circuits on ``quiet`` before ever calling this.)"""
        b = self.breakers[plane]
        return b.closed or b.allow_probe()

    def take_recoveries(self) -> List[str]:
        """Drain the pending recovery queue (driver thread only)."""
        with self._lock:
            out, self._pending_recovery = self._pending_recovery, []
            return out

    def probing_planes(self) -> List[str]:
        with self._lock:
            return [p for p, b in self.breakers.items() if b.probing]

    def settle(self) -> None:
        """Re-derive ``quiet`` after probe resolutions (driver thread)."""
        with self._lock:
            self._recompute_quiet_locked()

    def any_open(self) -> bool:
        with self._lock:
            return any(b.state != CLOSED for b in self.breakers.values())

    def trips_total(self) -> int:
        with self._lock:
            return sum(b.trips for b in self.breakers.values())

    # ktpu: hot-path census for /debug/ktpu + the health monitor: counters
    # and strings only, never a device value
    def census(self) -> Dict[str, object]:
        doc = {p: b.census() for p, b in self.breakers.items()}
        with self._lock:
            return {
                "quiet": self.quiet,
                "pending_recovery": list(self._pending_recovery),
                "breakers": doc,
            }
