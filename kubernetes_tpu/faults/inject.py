"""Seeded fault injection: the deterministic chaos harness.

A ``FaultPlan`` is a schedule of injected faults keyed by INJECTION-SITE
name. Sites are fixed, annotated points in the pipeline where a real
fault can occur; each calls ``plan.fire(site, arg)`` — a counted,
deterministic trigger — and raises ``InjectedFault`` (or performs the
site's side effect, e.g. closing a watch stream) when the schedule says
so. With no plan configured the cost at every site is ONE attribute read
(``self._fault_plan is None`` — the FlightRecorder disabled-path idiom),
and because every site lives inside a ``# ktpu: hot-path`` function, a
site that forced a device value to decide whether to fire would be a
KTPU004 lint violation, not a code-review hope (the injection-site
fixture pair pins both directions).

Registered sites (driver + banks + informer + monitor sync point):

  ``uploader-death``   arg=ingest|terms   the bank drain thread raises and dies
  ``device-raise``     arg=solve|arbiter|fold|gather-stage|gather-terms|patch|apply
                       the named device dispatch raises
  ``watch-break``      arg=<kind>         the informer drops its watch stream
  ``list-error``       arg=<kind>         the informer's relist raises
  ``bind-error``       (no arg)           the bind RPC raises
  ``bank-skew``        (no arg)           a device bank row is corrupted (+1),
                       so the next shadow audit reports divergence

KILL-POINTS (the crash-restart harness, ``kubernetes_tpu/restart``): the
``crash`` site simulates ``kill -9`` at a named pipeline stage — it
raises ``SimulatedCrash`` (a BaseException on purpose: every ``except
Exception`` fault handler in the pipeline must NOT absorb a process
death — nothing recovers, nothing rolls back, the supervisor rebuilds
the whole instance from the API server) and latches ``plan.crashed`` so
the dead instance's surviving threads are fenced from the API server
(``crash_gate``). Registered kill-points, by arg:

  ``crash``  arg=post-solve          after the solve result lands, before
                                     any commit touches the cache
             arg=mid-apply           on the commit worker, mid columnar
                                     apply (assumes landed, zero binds)
             arg=mid-bind-chunk      between two binds of one lean chunk
             arg=post-bind           after a bind POST landed, before the
                                     confirm/finish bookkeeping
             arg=mid-preemption      between victim eviction and the
                                     preemptor's nomination write
             arg=mid-uploader-flush  inside a staged-bank dirty-row flush

Spec grammar (``KTPU_FAULTS`` / ``FaultPlan.parse``), semicolon-joined:

    site[:arg][@n][xk]     fire on the n-th matching call (default 1),
                           k consecutive times (default 1)

    KTPU_FAULTS="uploader-death:ingest@2;device-raise:solve@3x2;bank-skew@4"
    KTPU_FAULTS="crash:mid-bind-chunk@2"   # die at the 2nd chunk boundary

``FaultPlan.seeded(seed, sites)`` draws each event's trigger count from
``random.Random(seed)`` instead — same seed, same schedule, every run
(the perf_smoke ``faults`` mode's chaos drain is built on this).
"""

from __future__ import annotations

import random
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple


class InjectedFault(RuntimeError):
    """Raised by an injection site the active FaultPlan triggered. A
    plain RuntimeError subclass on purpose: the pipeline's fault handling
    must treat it exactly like the real failure it stands in for."""


#: the ``crash`` injection site's name (kill-points pass the stage as arg)
CRASH_SITE = "crash"


class SimulatedCrash(BaseException):
    """A deterministic stand-in for ``kill -9`` at a pipeline kill-point.

    BaseException, NOT Exception, on purpose: the fault plane's handlers
    (fold fallback, commit-worker unwind, bank death recording, the
    black-box dump) all catch ``Exception`` — a process death must sail
    through every one of them untouched, exactly like a real SIGKILL
    gives no thread a chance to clean up. Only the restart supervisor
    (``kubernetes_tpu/restart``) catches it, and its response is to
    abandon the instance and rebuild from the API server."""


@dataclass
class FaultEvent:
    site: str
    arg: str = ""  # "" matches any arg at the site
    at: int = 1  # fire on the at-th matching call (1-based)
    times: int = 1  # ... and the next times-1 calls too
    fired: int = field(default=0, compare=False)  # runtime bookkeeping

    def spec(self) -> str:
        s = self.site + (f":{self.arg}" if self.arg else "")
        if self.at != 1:
            s += f"@{self.at}"
        if self.times != 1:
            s += f"x{self.times}"
        return s


class FaultPlan:
    """A deterministic, counted schedule of injected faults. Thread-safe
    (sites fire from informer/uploader/bind threads); the lock is a plain
    ``threading.Lock`` — injection is a test/chaos facility, never on by
    default, so it stays outside the audited-lock role set."""

    def __init__(self, events: Sequence[FaultEvent], seed: int = 0):
        self.events: List[FaultEvent] = list(events)
        self.seed = seed
        # (site, arg) per-arg call counts + (site, None) site-wide totals
        self._counts: Dict[Tuple[str, Optional[str]], int] = {}  # ktpu: guarded-by(self._lock)
        self._fired: List[str] = []  # ktpu: guarded-by(self._lock)
        self._lock = threading.Lock()
        # latched by the FIRST crash kill-point to fire (the stage name):
        # the supervisor polls it to detect deaths on worker threads, and
        # crash_gate() fences the dead instance's surviving threads off
        # the API server — kill -9 stops every thread at once; this is
        # the in-process equivalent. Never reset: a plan is one process
        # lifetime, the supervisor hands the next incarnation a fresh
        # view via `rearm()`.
        # ktpu: allow(KTPU006) monotone crash latch: one None->site
        # transition by whichever thread hits a kill-point; every other
        # thread reads it racily ON PURPOSE (crash_gate fences outward
        # writes even before the latch propagates)
        self.crashed: Optional[str] = None

    # -- construction --------------------------------------------------------

    @classmethod
    def parse(cls, spec: str, seed: int = 0) -> "FaultPlan":
        """Parse the KTPU_FAULTS grammar (module docstring). Unknown
        sites are accepted verbatim — the plan is a schedule, the sites
        define the vocabulary."""
        import re

        pat = re.compile(
            r"^(?P<site>[A-Za-z_][\w.-]*)"
            r"(?::(?P<arg>[\w./-]*))?"
            r"(?:@(?P<at>\d+))?"
            r"(?:x(?P<times>\d+))?$"
        )
        events: List[FaultEvent] = []
        for part in spec.split(";"):
            part = part.strip()
            if not part:
                continue
            m = pat.match(part)
            if m is None:
                raise ValueError(f"bad KTPU_FAULTS entry: {part!r}")
            events.append(FaultEvent(
                site=m.group("site"),
                arg=m.group("arg") or "",
                at=int(m.group("at") or 1),
                times=int(m.group("times") or 1),
            ))
        return cls(events, seed=seed)

    @classmethod
    def seeded(
        cls, seed: int, sites: Sequence[Tuple[str, str, int]],
        times: int = 1,
    ) -> "FaultPlan":
        """Draw each site's trigger count deterministically from the
        seed: ``sites`` is [(site, arg, max_at)] and each event fires on
        a call index drawn uniformly from [1, max_at]. Same seed, same
        schedule — the chaos drain's reproducibility contract."""
        rng = random.Random(seed)
        events = [
            FaultEvent(site=s, arg=a, at=rng.randint(1, max(m, 1)), times=times)
            for s, a, m in sites
        ]
        return cls(events, seed=seed)

    # -- the trigger ---------------------------------------------------------

    def fire(self, site: str, arg: str = "") -> bool:
        """Count this call against every matching event and report
        whether an injected fault is due NOW. Sites call this only after
        the one-attribute-read plan-present check. Events WITH an arg
        count that arg's calls; events WITHOUT one count the site's
        calls across all args ("the n-th matching call" means exactly
        that — an any-arg event must not re-fire at the n-th call of
        every distinct arg)."""
        with self._lock:
            n_arg = self._counts[(site, arg)] = (
                self._counts.get((site, arg), 0) + 1
            )
            n_site = self._counts[(site, None)] = (
                self._counts.get((site, None), 0) + 1
            )
            for ev in self.events:
                if ev.site != site or (ev.arg and ev.arg != arg):
                    continue
                n = n_arg if ev.arg else n_site
                if ev.at <= n < ev.at + ev.times and ev.fired < ev.times:
                    ev.fired += 1
                    self._fired.append(f"{ev.spec()}#{n}")
                    del self._fired[:-64]
                    return True
        return False

    def raise_if(self, site: str, arg: str = "") -> None:
        """fire() + raise — the one-liner most sites use."""
        if self.fire(site, arg):
            raise InjectedFault(f"injected: {site}" + (f":{arg}" if arg else ""))

    # -- kill-points (crash-restart harness) ---------------------------------

    def crash_if(self, point: str) -> None:
        """The kill-point one-liner: counted like any site, but a firing
        ``crash:<point>`` latches ``crashed`` BEFORE raising, so every
        other thread's next ``crash_gate()`` dies too — the whole
        instance stops acting, not just the thread that hit the point."""
        if self.crashed is not None:
            raise SimulatedCrash(self.crashed)
        if self.fire(CRASH_SITE, point):
            self.crashed = point
            raise SimulatedCrash(point)

    def crash_gate(self) -> None:
        """Fence for outward-facing writes (binds, victim deletes,
        nomination patches): once any kill-point fired, the dead
        instance's surviving threads must not keep mutating the API
        server. One attribute read when no crash has happened."""
        if self.crashed is not None:
            raise SimulatedCrash(self.crashed)

    def rearm(self) -> "FaultPlan":
        """The restarted incarnation's view of the SAME schedule: shared
        events and call counts (a ``crash:<site>@n`` that fired stays
        fired — the matrix drives one kill per cell unless the spec says
        otherwise), but a cleared ``crashed`` latch so the new instance's
        writes pass the gate. Returns a plan sharing this plan's
        bookkeeping."""
        twin = FaultPlan.__new__(FaultPlan)
        twin.events = self.events
        twin.seed = self.seed
        with self._lock:  # bookkeeping aliased under the shared lock
            twin._counts = self._counts
            twin._fired = self._fired
        twin._lock = self._lock
        twin.crashed = None
        return twin

    def exhausted(self) -> bool:
        """True once every scheduled event has fully fired — the chaos
        harness's 'all faults delivered' assertion."""
        with self._lock:
            return all(ev.fired >= ev.times for ev in self.events)

    def census(self) -> Dict[str, object]:
        with self._lock:
            return {
                "seed": self.seed,
                "events": [
                    {"spec": ev.spec(), "fired": ev.fired} for ev in self.events
                ],
                "recent_fired": list(self._fired),
            }


def apply_bank_skew(mirror) -> None:
    """The ``bank-skew`` site's side effect: nudge one device bank array
    (+1 on the node allocatable column) WITHOUT touching host truth, so
    the device twin is verifiably wrong and the next shadow audit must
    report divergence — the forced-skew sensitivity probe of PR 9/10, as
    an injectable fault. `alloc` on purpose: the usage columns
    (requested/pod_count) are re-shipped host-wins by every post-commit
    patch, which would quietly heal the skew before an audit ever saw
    it; allocatable only ships on full node-row patches (node events).
    Non-donating (builds a fresh array), so in-flight dispatches holding
    the previous buffers are unaffected."""
    dev = mirror._dev_nodes
    if dev is None:
        return
    key = "alloc" if "alloc" in dev else next(iter(dev))
    mirror._dev_nodes = {**dev, key: dev[key] + 1}


def plan_from_env(environ) -> Optional[FaultPlan]:
    """Build the plan KTPU_FAULTS names, or None (the zero-overhead
    default). ``KTPU_FAULTS_SEED`` seeds the plan's RNG bookkeeping."""
    spec = environ.get("KTPU_FAULTS", "")
    if not spec:
        return None
    return FaultPlan.parse(spec, seed=int(environ.get("KTPU_FAULTS_SEED", "0")))
