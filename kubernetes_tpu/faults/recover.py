"""Recovery actions: what a tripped breaker DOES, on the driver thread.

Every action here mutates driver-confined state (the tensor mirror, the
staged banks' device twins, the columnar cache attachment), so the board
only QUEUES recoveries at trip time; the driver executes them at its
post-sync safe point (``Scheduler._fault_service`` — commit pipeline
drained, mirror freshly synced, the same designated window the PR 10
shadow audits use). The actions:

* **bank resync** (ingest/terms trip) — the slab's device twin is
  re-uploaded from host truth: ``StageBank.resync()`` drops the resident
  dict so the next covered dispatch's flush takes the full-upload path.
  Full uploads are ``_to_dev`` placements of existing host arrays — NO
  new XLA programs — and any subsequent dirty-row scatters land on the
  already-warmed KIND_STAGE/KIND_TERM rungs: resync never compiles.

* **uploader restart** (dead drain thread) — restarted EXACTLY ONCE per
  trip, with the dirty backlog flushed synchronously first so the new
  worker starts from a clean slate (and a restart loop can never spin:
  the next death is a fresh counted fault that must re-trip the breaker
  before anyone restarts again).

* **mirror/fold resync** — ``TensorMirror.mark_device_stale()``: the next
  ``device_arrays()`` re-uploads the full banks from host truth (host
  wins, the resident-state plane's own recovery primitive), clearing any
  partially-applied fold or patch. Same no-new-compiles argument.

* **columns re-attach probe** — a columns trip DETACHES the columnar
  cache inline (the cache materializes every lazy view from its journal
  first, so object truth survives the broken columns); the probe path
  re-attaches fresh columns built from current object truth, and the
  shadow audit's columns-vs-banks cross-check gates the close.

* **divergence escalation** — a divergent shadow audit (PR 10) stops
  being just a metric: it force-trips the mirror breaker (the banks are
  KNOWN wrong — no counted threshold), queues the resync, and dumps the
  flight recorder's black box for the post-mortem.
"""

from __future__ import annotations

import logging
from typing import List

logger = logging.getLogger("kubernetes_tpu.faults")


def resync_bank(bank) -> None:
    """Re-upload one staged bank's device twin from host truth (and
    restart its uploader if the thread died). Driver thread only."""
    if bank is None:
        return
    restarted = bank.restart_uploader()
    bank.resync()
    if restarted:
        logger.warning(
            "fault recovery: %s uploader restarted (restart #%d), dirty "
            "backlog flushed synchronously",
            bank.THREAD_NAME, bank.uploader_restarts,
        )


def resync_mirror(sched) -> None:
    """Force the next device_arrays() to re-upload the full banks from
    host truth — clears partially-applied folds/patches/skew. No new
    compiles: the full upload is placement, not a program."""
    sched.mirror.mark_device_stale()


def reattach_columns(sched) -> bool:
    """Columns probe: rebuild the columnar cache from current object
    truth (attach_columns is idempotent and journal-safe). Returns True
    when columns are attached after the call."""
    if not sched.columnar_cache:
        return False
    try:
        sched.cache.attach_columns(sched.mirror.vocab)
        return True
    except Exception:
        logger.exception("fault recovery: columns re-attach failed")
        return False


def detach_columns(sched) -> None:
    sched.cache.detach_columns()


def run_recoveries(sched, planes: List[str]) -> None:
    """Execute the queued recovery action for each tripped plane.
    Driver thread, at the post-sync safe point, holding no locks."""
    for plane in planes:
        try:
            if plane == "ingest":
                resync_bank(sched.stage_bank)
            elif plane == "terms":
                resync_bank(sched.term_bank)
            elif plane in ("fold", "mirror"):
                resync_mirror(sched)
            elif plane == "columns":
                # the inline fault handler already detached (object truth
                # preserved); nothing to do until the probe re-attaches
                detach_columns(sched)
            elif plane == "commit":
                # the pipeline worker survives (exceptions are captured
                # by its Future); the open breaker routes batches to the
                # scalar loop — no state to repair
                pass
        except Exception:
            logger.exception("fault recovery for plane %r failed", plane)


def escalate_divergence(sched, divergence: List[str]) -> None:
    """A shadow audit found device/host divergence: automatic trip +
    resync + black-box dump (metric → action). Driver thread (the audit
    runs at the safe sync point by construction)."""
    board = getattr(sched, "faults", None)
    if board is None:
        return
    logger.error(
        "shadow audit DIVERGENT (%s) — tripping mirror breaker, resyncing "
        "device banks from host truth", ", ".join(divergence[:8]),
    )
    board.record_failure("mirror", "shadow-divergence", force=True)
    try:
        sched.obs.dump_blackbox("shadow-divergence")
    except Exception:  # the dump is forensics, never load-bearing
        logger.exception("black-box dump after divergence failed")
