"""Fault plane: runtime degradation ladder + deterministic chaos harness.

The six device-residency planes each ship a bit-identical legacy host
path behind a static env kill switch; this package flips those paths at
RUNTIME and proves the machinery with seeded fault injection:

* ``breaker``  — per-plane circuit breakers (closed → open → half-open,
  counted thresholds, injectable clock) + the BreakerBoard trip →
  recovery handshake; an open breaker routes a plane's dispatches to its
  legacy path, a half-open one re-closes only through a shadow-audit-
  gated probe batch.
* ``recover``  — the driver-thread recovery actions: bank/mirror resync
  from host truth through already-warmed programs, exactly-once uploader
  restarts, columns detach/re-attach, divergence escalation.
* ``inject``   — ``FaultPlan``: a seeded, counted schedule of injected
  faults keyed by annotated injection-site names, reachable via
  ``Scheduler(fault_plan=...)`` or ``KTPU_FAULTS=<spec>``; zero overhead
  when absent (one attribute read per site).
"""

from .breaker import (
    BreakerBoard,
    CLOSED,
    DEFAULT_COOLDOWN_S,
    DEFAULT_THRESHOLD,
    HALF_OPEN,
    OPEN,
    PLANES,
    PlaneBreaker,
)
from .inject import (
    CRASH_SITE,
    FaultEvent,
    FaultPlan,
    InjectedFault,
    SimulatedCrash,
    apply_bank_skew,
    plan_from_env,
)

__all__ = [
    "CRASH_SITE",
    "SimulatedCrash",
    "BreakerBoard",
    "CLOSED",
    "DEFAULT_COOLDOWN_S",
    "DEFAULT_THRESHOLD",
    "FaultEvent",
    "FaultPlan",
    "HALF_OPEN",
    "InjectedFault",
    "OPEN",
    "PLANES",
    "PlaneBreaker",
    "apply_bank_skew",
    "plan_from_env",
]
