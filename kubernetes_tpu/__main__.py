from .cmd import main

raise SystemExit(main())
