"""kubectl-subset ops CLI over the apiserver HTTP transport.

The honest minimum of the reference's ops tooling
(staging/src/k8s.io/kubectl): get / describe / cordon / uncordon / drain /
delete against any server speaking the list+watch transport
(apiserver/http.py — e.g. `--mode sim --serve-api PORT`). Being a separate
process talking wire JSON is the point: it proves the control plane is
reachable the way the reference's is.

  python -m kubernetes_tpu.kubectl --server http://127.0.0.1:18080 get pods
  python -m kubernetes_tpu.kubectl ... get nodes
  python -m kubernetes_tpu.kubectl ... describe pod default/web-1
  python -m kubernetes_tpu.kubectl ... describe node node-3
  python -m kubernetes_tpu.kubectl ... cordon node-3
  python -m kubernetes_tpu.kubectl ... drain node-3
  python -m kubernetes_tpu.kubectl ... delete pod default/web-1

Reference behaviors mirrored: cordon sets spec.unschedulable
(kubectl/pkg/drain), drain = cordon + evict every pod bound to the node
(pods with a controller owner are deleted and re-created elsewhere by
their ReplicaSet — the same flow `kubectl drain` relies on).
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from .client.remote import RemoteAPIServer


def _fmt_table(headers: List[str], rows: List[List[str]]) -> str:
    widths = [len(h) for h in headers]
    for r in rows:
        for i, c in enumerate(r):
            widths[i] = max(widths[i], len(c))
    out = ["  ".join(h.ljust(w) for h, w in zip(headers, widths))]
    for r in rows:
        out.append("  ".join(c.ljust(w) for c, w in zip(r, widths)))
    return "\n".join(out)


_KIND_ALIASES = {
    "pod": "pods", "node": "nodes", "rs": "replicasets",
    "replicaset": "replicasets", "deploy": "deployments",
    "deployment": "deployments", "job": "jobs", "event": "events", "ev": "events",
    "sts": "statefulsets", "statefulset": "statefulsets",
    "ds": "daemonsets", "daemonset": "daemonsets",
    "svc": "services", "service": "services",
    "ep": "endpoints", "ns": "namespaces", "namespace": "namespaces",
    "pc": "priorityclasses", "priorityclass": "priorityclasses",
}
_KINDS = (
    "pods", "nodes", "replicasets", "deployments", "jobs", "events",
    "statefulsets", "daemonsets", "services", "endpoints", "namespaces",
    "priorityclasses",
)
# wire Kind (manifest .kind) → store kind
_WIRE_KINDS = {
    "Pod": "pods", "Node": "nodes", "ReplicaSet": "replicasets",
    "Deployment": "deployments", "Job": "jobs",
    "StatefulSet": "statefulsets", "DaemonSet": "daemonsets",
    "Service": "services", "Namespace": "namespaces",
    "PriorityClass": "priorityclasses",
}
# kinds whose reconcile loops read .spec.replicas (kubectl scale targets)
_SCALABLE = ("replicasets", "deployments", "statefulsets", "jobs")


def cmd_get(api: RemoteAPIServer, kind: str) -> int:
    kind = _KIND_ALIASES.get(kind, kind)
    if kind not in _KINDS:
        print(f"unknown kind {kind}", file=sys.stderr)
        return 1
    items, _ = api.list(kind)
    if kind == "pods":
        rows = [[p.key(), p.phase, p.node_name or "<none>",
                 str(p.get_priority())] for p in items]
        print(_fmt_table(["NAME", "STATUS", "NODE", "PRIORITY"], rows))
    elif kind == "nodes":
        rows = []
        for n in items:
            status = "SchedulingDisabled" if n.unschedulable else "Ready"
            for c in n.conditions:
                if c.get("type") == "Ready" and c.get("status") != "True":
                    status = "NotReady"
            taints = ",".join(f"{t.key}:{t.effect}" for t in n.taints) or "<none>"
            rows.append([n.name, status, taints])
        print(_fmt_table(["NAME", "STATUS", "TAINTS"], rows))
    elif kind in ("replicasets", "deployments"):
        rows = [[rs.key(), str(rs.replicas)] for rs in items]
        print(_fmt_table(["NAME", "DESIRED"], rows))
    elif kind == "jobs":
        rows = [[j.key(), str(j.parallelism), str(j.completions)] for j in items]
        print(_fmt_table(["NAME", "PARALLELISM", "COMPLETIONS"], rows))
    elif kind == "events":
        import time as _t

        items.sort(key=lambda e: e.last_timestamp)
        rows = [[f"{max(_t.time() - e.last_timestamp, 0):.0f}s", e.type,
                 e.reason, e.object_key, str(e.count), e.message[:60]]
                for e in items]
        print(_fmt_table(["LAST SEEN", "TYPE", "REASON", "OBJECT", "COUNT", "MESSAGE"], rows))
    else:
        print(f"unknown kind {kind}", file=sys.stderr)
        return 1
    return 0


def cmd_describe(api: RemoteAPIServer, kind: str, name: str) -> int:
    if kind in ("pod", "pods"):
        p = api.get("pods", name if "/" in name else f"default/{name}")
        print(f"Name:         {p.name}")
        print(f"Namespace:    {p.namespace}")
        print(f"Node:         {p.node_name or '<none>'}")
        print(f"Status:       {p.phase}")
        print(f"Priority:     {p.get_priority()}")
        print(f"Labels:       {p.labels}")
        if p.nominated_node_name:
            print(f"NominatedNodeName: {p.nominated_node_name}")
        for c in p.containers:
            reqs = {k: str(q.value_exact) for k, q in c.requests.items()}
            print(f"Container {c.name}: requests={reqs}")
        if p.tolerations:
            print("Tolerations: " + "; ".join(
                f"{t.key} {t.operator} {t.value} {t.effect}".strip()
                for t in p.tolerations))
        if p.owner_references:
            print(f"Controlled By: " + ", ".join(
                f"{r.get('kind')}/{r.get('name')}" for r in p.owner_references))
        return 0
    if kind in ("node", "nodes"):
        n = api.get("nodes", name)
        print(f"Name:          {n.name}")
        print(f"Labels:        {n.labels}")
        print(f"Unschedulable: {n.unschedulable}")
        print("Taints:        " + (", ".join(
            f"{t.key}={t.value}:{t.effect}" for t in n.taints) or "<none>"))
        alloc = {k: str(q.value_exact) for k, q in n.allocatable.items()}
        print(f"Allocatable:   {alloc}")
        pods, _ = api.list("pods")
        mine = [p for p in pods if p.node_name == n.name]
        print(f"Non-terminated Pods: ({len(mine)} in total)")
        for p in mine:
            print(f"  {p.key()}")
        return 0
    print(f"unknown kind {kind}", file=sys.stderr)
    return 1


def _set_unschedulable(api: RemoteAPIServer, name: str, value: bool) -> int:
    """CAS loop on resourceVersion: a blind PUT would clobber concurrent
    controller writes (taints, conditions) — real kubectl cordon PATCHes
    spec.unschedulable for the same reason."""
    from .apiserver.store import ConflictError

    for _ in range(10):
        n = api.get("nodes", name)
        n.unschedulable = value
        try:
            api.update("nodes", n, check_rv=True)
        except ConflictError:
            continue  # re-read and retry against the newer version
        print(f"node/{name} {'cordoned' if value else 'uncordoned'}")
        return 0
    print(f"node/{name}: too many conflicting writers", file=sys.stderr)
    return 1


def cmd_apply(api: RemoteAPIServer, filename: str) -> int:
    """kubectl apply -f: create-or-update by kind+name (the declarative
    workflow, staging/src/k8s.io/kubectl/pkg/cmd/apply/apply.go:38 —
    without the three-way strategic merge: the manifest's spec REPLACES
    the live spec, which is exact for the typed subset modeled here).
    Accepts one JSON object or a JSON list of objects."""
    import json

    from .apiserver.store import NotFoundError
    from .client.remote import _CODECS

    with (sys.stdin if filename == "-" else open(filename)) as f:
        body = json.load(f)
    docs = body if isinstance(body, list) else [body]
    rc = 0
    for doc in docs:
        kind = _WIRE_KINDS.get(doc.get("kind", ""))
        if kind is None or kind not in _CODECS:
            print(f"unsupported kind {doc.get('kind')!r}", file=sys.stderr)
            rc = 1
            continue
        _, from_k8s = _CODECS[kind]
        obj = from_k8s(doc)
        try:
            live = api.get(kind, obj.key() if callable(getattr(obj, "key", None)) else obj.name)
        except (KeyError, NotFoundError):
            live = None
        if live is None:
            api.create(kind, obj)
            print(f"{doc.get('kind', '').lower()}/{obj.name} created")
        else:
            # keep the live object's identity (uid) so ownerReferences on
            # existing children stay valid; everything else comes from the
            # manifest
            if hasattr(live, "uid") and hasattr(obj, "uid"):
                obj.uid = live.uid
            api.update(kind, obj)
            print(f"{doc.get('kind', '').lower()}/{obj.name} configured")
    return rc


def cmd_scale(api: RemoteAPIServer, ref: str, replicas: int) -> int:
    """kubectl scale <kind>/<name> --replicas=N (scale.go): CAS-update
    .spec.replicas; the kind's controller reconciles the rest."""
    from .apiserver.store import ConflictError

    if "/" not in ref:
        print("usage: scale <kind>/<name> --replicas=N", file=sys.stderr)
        return 1
    kind_raw, name = ref.split("/", 1)
    kind = _KIND_ALIASES.get(kind_raw, kind_raw)
    if kind not in _SCALABLE:
        print(f"cannot scale kind {kind_raw}", file=sys.stderr)
        return 1
    key = name if "/" in name else f"default/{name}"
    for _ in range(10):
        obj = api.get(kind, key)
        if not hasattr(obj, "replicas"):
            print(f"{kind}/{name} has no replicas field", file=sys.stderr)
            return 1
        obj.replicas = replicas
        try:
            api.update(kind, obj, check_rv=True)
        except ConflictError:
            continue
        print(f"{kind_raw}/{name} scaled to {replicas}")
        return 0
    print(f"{kind}/{name}: too many conflicting writers", file=sys.stderr)
    return 1


def cmd_drain(api: RemoteAPIServer, name: str) -> int:
    """cordon + evict everything bound to the node (kubectl drain's core:
    pkg/drain — controller-owned pods are re-created elsewhere)."""
    if _set_unschedulable(api, name, True) != 0:
        # real kubectl drain aborts when the cordon fails — evicting from an
        # uncordoned node just lets the scheduler re-place replicas onto it
        print(f"node/{name}: cordon failed, aborting drain", file=sys.stderr)
        return 1
    pods, _ = api.list("pods")
    evicted = 0
    for p in pods:
        if p.node_name != name:
            continue
        api.delete("pods", p.key())
        evicted += 1
        print(f"evicting pod {p.key()}")
    print(f"node/{name} drained ({evicted} pods evicted)")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(prog="kubectl", description=__doc__)
    p.add_argument("--server", required=True, help="apiserver base URL")
    sub = p.add_subparsers(dest="verb", required=True)
    g = sub.add_parser("get")
    g.add_argument("kind")
    d = sub.add_parser("describe")
    d.add_argument("kind")
    d.add_argument("name")
    for verb in ("cordon", "uncordon", "drain"):
        s = sub.add_parser(verb)
        s.add_argument("node")
    dl = sub.add_parser("delete")
    dl.add_argument("kind")
    dl.add_argument("name")
    ap = sub.add_parser("apply")
    ap.add_argument("-f", "--filename", required=True,
                    help="JSON manifest (or '-' for stdin)")
    sc = sub.add_parser("scale")
    sc.add_argument("ref", help="<kind>/<name>")
    sc.add_argument("--replicas", type=int, required=True)
    args = p.parse_args(argv)
    api = RemoteAPIServer(args.server)
    if args.verb == "get":
        return cmd_get(api, args.kind)
    if args.verb == "describe":
        return cmd_describe(api, args.kind, args.name)
    if args.verb == "cordon":
        return _set_unschedulable(api, args.node, True)
    if args.verb == "uncordon":
        return _set_unschedulable(api, args.node, False)
    if args.verb == "drain":
        return cmd_drain(api, args.node)
    if args.verb == "delete":
        kind = _KIND_ALIASES.get(args.kind, args.kind)
        if kind not in _KINDS:
            print(f"unknown kind {args.kind}", file=sys.stderr)
            return 1
        key = args.name if "/" in args.name or kind == "nodes" else f"default/{args.name}"
        api.delete(kind, key)
        print(f"{kind}/{args.name} deleted")
        return 0
    if args.verb == "apply":
        return cmd_apply(api, args.filename)
    if args.verb == "scale":
        return cmd_scale(api, args.ref, args.replicas)
    return 1


def run() -> int:
    """CLI entry with expected-failure mapping: missing objects and an
    unreachable server print one-line errors (exit 1), not tracebacks."""
    from .apiserver.store import ConflictError, NotFoundError

    try:
        return main()
    except NotFoundError as e:
        print(f"Error: not found: {e}", file=sys.stderr)
        return 1
    except ConflictError as e:
        print(f"Error: conflict: {e}", file=sys.stderr)
        return 1
    except (ConnectionError, OSError) as e:
        print(f"Error: cannot reach server: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(run())
