"""kubectl-subset ops CLI over the apiserver HTTP transport.

The honest minimum of the reference's ops tooling
(staging/src/k8s.io/kubectl): get / describe / cordon / uncordon / drain /
delete against any server speaking the list+watch transport
(apiserver/http.py — e.g. `--mode sim --serve-api PORT`). Being a separate
process talking wire JSON is the point: it proves the control plane is
reachable the way the reference's is.

  python -m kubernetes_tpu.kubectl --server http://127.0.0.1:18080 get pods
  python -m kubernetes_tpu.kubectl ... get nodes
  python -m kubernetes_tpu.kubectl ... describe pod default/web-1
  python -m kubernetes_tpu.kubectl ... describe node node-3
  python -m kubernetes_tpu.kubectl ... cordon node-3
  python -m kubernetes_tpu.kubectl ... drain node-3
  python -m kubernetes_tpu.kubectl ... delete pod default/web-1

Reference behaviors mirrored: cordon sets spec.unschedulable
(kubectl/pkg/drain), drain = cordon + evict every pod bound to the node
(pods with a controller owner are deleted and re-created elsewhere by
their ReplicaSet — the same flow `kubectl drain` relies on).
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from .client.remote import RemoteAPIServer


def _fmt_table(headers: List[str], rows: List[List[str]]) -> str:
    widths = [len(h) for h in headers]
    for r in rows:
        for i, c in enumerate(r):
            widths[i] = max(widths[i], len(c))
    out = ["  ".join(h.ljust(w) for h, w in zip(headers, widths))]
    for r in rows:
        out.append("  ".join(c.ljust(w) for c, w in zip(r, widths)))
    return "\n".join(out)


_KIND_ALIASES = {
    "pod": "pods", "node": "nodes", "rs": "replicasets",
    "replicaset": "replicasets", "deploy": "deployments",
    "deployment": "deployments", "job": "jobs", "event": "events", "ev": "events",
}
_KINDS = ("pods", "nodes", "replicasets", "deployments", "jobs", "events")


def cmd_get(api: RemoteAPIServer, kind: str) -> int:
    kind = _KIND_ALIASES.get(kind, kind)
    if kind not in _KINDS:
        print(f"unknown kind {kind}", file=sys.stderr)
        return 1
    items, _ = api.list(kind)
    if kind == "pods":
        rows = [[p.key(), p.phase, p.node_name or "<none>",
                 str(p.get_priority())] for p in items]
        print(_fmt_table(["NAME", "STATUS", "NODE", "PRIORITY"], rows))
    elif kind == "nodes":
        rows = []
        for n in items:
            status = "SchedulingDisabled" if n.unschedulable else "Ready"
            for c in n.conditions:
                if c.get("type") == "Ready" and c.get("status") != "True":
                    status = "NotReady"
            taints = ",".join(f"{t.key}:{t.effect}" for t in n.taints) or "<none>"
            rows.append([n.name, status, taints])
        print(_fmt_table(["NAME", "STATUS", "TAINTS"], rows))
    elif kind in ("replicasets", "deployments"):
        rows = [[rs.key(), str(rs.replicas)] for rs in items]
        print(_fmt_table(["NAME", "DESIRED"], rows))
    elif kind == "jobs":
        rows = [[j.key(), str(j.parallelism), str(j.completions)] for j in items]
        print(_fmt_table(["NAME", "PARALLELISM", "COMPLETIONS"], rows))
    elif kind == "events":
        import time as _t

        items.sort(key=lambda e: e.last_timestamp)
        rows = [[f"{max(_t.time() - e.last_timestamp, 0):.0f}s", e.type,
                 e.reason, e.object_key, str(e.count), e.message[:60]]
                for e in items]
        print(_fmt_table(["LAST SEEN", "TYPE", "REASON", "OBJECT", "COUNT", "MESSAGE"], rows))
    else:
        print(f"unknown kind {kind}", file=sys.stderr)
        return 1
    return 0


def cmd_describe(api: RemoteAPIServer, kind: str, name: str) -> int:
    if kind in ("pod", "pods"):
        p = api.get("pods", name if "/" in name else f"default/{name}")
        print(f"Name:         {p.name}")
        print(f"Namespace:    {p.namespace}")
        print(f"Node:         {p.node_name or '<none>'}")
        print(f"Status:       {p.phase}")
        print(f"Priority:     {p.get_priority()}")
        print(f"Labels:       {p.labels}")
        if p.nominated_node_name:
            print(f"NominatedNodeName: {p.nominated_node_name}")
        for c in p.containers:
            reqs = {k: str(q.value_exact) for k, q in c.requests.items()}
            print(f"Container {c.name}: requests={reqs}")
        if p.tolerations:
            print("Tolerations: " + "; ".join(
                f"{t.key} {t.operator} {t.value} {t.effect}".strip()
                for t in p.tolerations))
        if p.owner_references:
            print(f"Controlled By: " + ", ".join(
                f"{r.get('kind')}/{r.get('name')}" for r in p.owner_references))
        return 0
    if kind in ("node", "nodes"):
        n = api.get("nodes", name)
        print(f"Name:          {n.name}")
        print(f"Labels:        {n.labels}")
        print(f"Unschedulable: {n.unschedulable}")
        print("Taints:        " + (", ".join(
            f"{t.key}={t.value}:{t.effect}" for t in n.taints) or "<none>"))
        alloc = {k: str(q.value_exact) for k, q in n.allocatable.items()}
        print(f"Allocatable:   {alloc}")
        pods, _ = api.list("pods")
        mine = [p for p in pods if p.node_name == n.name]
        print(f"Non-terminated Pods: ({len(mine)} in total)")
        for p in mine:
            print(f"  {p.key()}")
        return 0
    print(f"unknown kind {kind}", file=sys.stderr)
    return 1


def _set_unschedulable(api: RemoteAPIServer, name: str, value: bool) -> int:
    """CAS loop on resourceVersion: a blind PUT would clobber concurrent
    controller writes (taints, conditions) — real kubectl cordon PATCHes
    spec.unschedulable for the same reason."""
    from .apiserver.store import ConflictError

    for _ in range(10):
        n = api.get("nodes", name)
        n.unschedulable = value
        try:
            api.update("nodes", n, check_rv=True)
        except ConflictError:
            continue  # re-read and retry against the newer version
        print(f"node/{name} {'cordoned' if value else 'uncordoned'}")
        return 0
    print(f"node/{name}: too many conflicting writers", file=sys.stderr)
    return 1


def cmd_drain(api: RemoteAPIServer, name: str) -> int:
    """cordon + evict everything bound to the node (kubectl drain's core:
    pkg/drain — controller-owned pods are re-created elsewhere)."""
    if _set_unschedulable(api, name, True) != 0:
        # real kubectl drain aborts when the cordon fails — evicting from an
        # uncordoned node just lets the scheduler re-place replicas onto it
        print(f"node/{name}: cordon failed, aborting drain", file=sys.stderr)
        return 1
    pods, _ = api.list("pods")
    evicted = 0
    for p in pods:
        if p.node_name != name:
            continue
        api.delete("pods", p.key())
        evicted += 1
        print(f"evicting pod {p.key()}")
    print(f"node/{name} drained ({evicted} pods evicted)")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(prog="kubectl", description=__doc__)
    p.add_argument("--server", required=True, help="apiserver base URL")
    sub = p.add_subparsers(dest="verb", required=True)
    g = sub.add_parser("get")
    g.add_argument("kind")
    d = sub.add_parser("describe")
    d.add_argument("kind")
    d.add_argument("name")
    for verb in ("cordon", "uncordon", "drain"):
        s = sub.add_parser(verb)
        s.add_argument("node")
    dl = sub.add_parser("delete")
    dl.add_argument("kind")
    dl.add_argument("name")
    args = p.parse_args(argv)
    api = RemoteAPIServer(args.server)
    if args.verb == "get":
        return cmd_get(api, args.kind)
    if args.verb == "describe":
        return cmd_describe(api, args.kind, args.name)
    if args.verb == "cordon":
        return _set_unschedulable(api, args.node, True)
    if args.verb == "uncordon":
        return _set_unschedulable(api, args.node, False)
    if args.verb == "drain":
        return cmd_drain(api, args.node)
    if args.verb == "delete":
        kind = _KIND_ALIASES.get(args.kind, args.kind)
        if kind not in _KINDS:
            print(f"unknown kind {args.kind}", file=sys.stderr)
            return 1
        key = args.name if "/" in args.name or kind == "nodes" else f"default/{args.name}"
        api.delete(kind, key)
        print(f"{kind}/{args.name} deleted")
        return 0
    return 1


def run() -> int:
    """CLI entry with expected-failure mapping: missing objects and an
    unreachable server print one-line errors (exit 1), not tracebacks."""
    from .apiserver.store import ConflictError, NotFoundError

    try:
        return main()
    except NotFoundError as e:
        print(f"Error: not found: {e}", file=sys.stderr)
        return 1
    except ConflictError as e:
        print(f"Error: conflict: {e}", file=sys.stderr)
        return 1
    except (ConnectionError, OSError) as e:
        print(f"Error: cannot reach server: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(run())
