"""Vectorized Filter kernels: the pods x nodes feasibility mask.

The reference evaluates predicates one (pod, node) pair at a time inside
ParallelizeUntil(16, checkNode) (core/generic_scheduler.go:523, predicates
ordered per predicates.go:147-153, short-circuiting in podFitsOnNode:612).
Here the ENTIRE pods x nodes boolean matrix is computed in one fused XLA
program over the padded tensor encoding (state/tensors.py): every predicate
is a broadcasted integer-compare reduction, so XLA fuses them into a single
pass over the node axis with no interpreter in the loop.

Covered (the non-topology predicates — topology ones live in topology.py):
  CheckNodeUnschedulable, PodFitsHost, PodFitsHostPorts, PodMatchNodeSelector
  (incl. required NodeAffinity with In/NotIn/Exists/DoesNotExist/Gt/Lt and
  metadata.name matchFields), PodFitsResources, PodToleratesNodeTaints.

Parity: tests/test_filter_parity.py asserts bit-for-bit agreement with
kubernetes_tpu.oracle.predicates on randomized clusters.
"""

from __future__ import annotations

from functools import partial
from typing import Dict

import jax
import jax.numpy as jnp

from ..state.tensors import (
    EFFECT_NO_EXECUTE,
    EFFECT_NO_SCHEDULE,
    OP_DOES_NOT_EXIST,
    OP_EXISTS,
    OP_GT,
    OP_IN,
    OP_LT,
    OP_NAME_IN,
    OP_NAME_NOT_IN,
    OP_NEVER,
    OP_NOT_IN,
    TOL_EXISTS,
)

Arrays = Dict[str, jnp.ndarray]


def _tolerates(pods: Arrays, taint_key, taint_val, taint_effect):
    """Broadcast Toleration.ToleratesTaint over a taint tensor.

    pods tol_* arrays are [B, TL]; taint_* are [..., T] (any leading shape
    broadcastable against B). Returns [..., T] bool: taint tolerated by ANY
    of the pod's tolerations. Semantics (api/core/v1/toleration.go):
      effect: empty toleration effect matches all; else exact match
      key: empty toleration key matches all; else exact match
      operator Exists: value ignored; Equal: values must be equal
    """
    # shapes: tol [B, 1, TL], taint [B-or-1, T, 1]
    tk = taint_key[..., :, None]
    tv = taint_val[..., :, None]
    te = taint_effect[..., :, None]
    ok_effect = (pods["tol_effect"][:, None, :] == 0) | (pods["tol_effect"][:, None, :] == te)
    ok_key = (pods["tol_key"][:, None, :] == 0) | (pods["tol_key"][:, None, :] == tk)
    is_exists = pods["tol_op"][:, None, :] == TOL_EXISTS
    ok_value = is_exists | (pods["tol_val"][:, None, :] == tv)
    match = pods["tol_valid"][:, None, :] & ok_effect & ok_key & ok_value
    return jnp.any(match, axis=-1)


def check_node_unschedulable(nodes: Arrays, pods: Arrays, ids: Arrays) -> jnp.ndarray:
    """CheckNodeUnschedulablePredicate (predicates.go:1584)."""
    b = pods["valid"].shape[0]
    taint_key = jnp.broadcast_to(ids["unschedulable_key"], (b, 1))
    taint_val = jnp.broadcast_to(ids["empty_val"], (b, 1))
    taint_effect = jnp.full((b, 1), EFFECT_NO_SCHEDULE, jnp.int32)
    tol = _tolerates(pods, taint_key, taint_val, taint_effect)[:, 0]  # [B]
    return (~nodes["unschedulable"])[None, :] | tol[:, None]


def pod_fits_host(nodes: Arrays, pods: Arrays) -> jnp.ndarray:
    """PodFitsHost (predicates.go:991)."""
    pinned = pods["node_name_id"][:, None]
    return (pinned == 0) | (pinned == nodes["name_id"][None, :])


def pod_fits_resources(nodes: Arrays, pods: Arrays) -> jnp.ndarray:
    """PodFitsResources (predicates.go:854): pod-count always; resource rows
    only when the pod requests anything."""
    count_ok = (nodes["pod_count"] + 1 <= nodes["allowed_pods"])[None, :]
    free = nodes["alloc"] - nodes["requested"]  # [N, R]
    ok = pods["req"][:, None, :] <= free[None, :, :]  # [B, N, R]
    # slots 0..2 (cpu/mem/ephemeral) are checked unconditionally; scalar
    # slots only when requested (reference predicates.go:886-907)
    r = free.shape[-1]
    always = jnp.arange(r) < 3
    checked = always[None, None, :] | (pods["req"][:, None, :] > 0)
    fits = jnp.all(ok | ~checked, axis=-1)
    return count_ok & (fits | ~pods["req_any"][:, None])


def port_clash(num_a, proto_a, ip_a, num_b, proto_b, ip_b, wild) -> jnp.ndarray:
    """HostPortInfo.CheckConflict core: same (protocol, port) conflicts
    when either IP is the wildcard or they're equal. Inputs broadcast; the
    port-list axes are reduced by the caller. The ONE definition shared by
    the pod-vs-node mask (pod_fits_host_ports) and the pod-vs-pod in-batch
    tracking matrix (pipeline._inbatch_tensors) so they can never
    diverge."""
    ip_clash = (ip_a == wild) | (ip_b == wild) | (ip_a == ip_b)
    return (num_a > 0) & (num_b > 0) & (num_a == num_b) & (proto_a == proto_b) & ip_clash


def pod_fits_host_ports(nodes: Arrays, pods: Arrays, ids: Arrays) -> jnp.ndarray:
    """PodFitsHostPorts (predicates.go:1161)."""
    conflict = port_clash(
        pods["port_num"][:, None, :, None],  # [B, 1, PP, 1]
        pods["port_proto"][:, None, :, None],
        pods["port_ip"][:, None, :, None],
        nodes["port_num"][None, :, None, :],  # [1, N, 1, P]
        nodes["port_proto"][None, :, None, :],
        nodes["port_ip"][None, :, None, :],
        ids["wildcard_ip"],
    )
    return ~jnp.any(conflict, axis=(2, 3))


def _eval_requirements(nodes: Arrays, op, slot, vals, num) -> jnp.ndarray:
    """Evaluate compiled node-selector requirements against every node.

    op/slot/num: [B, T, R]; vals: [B, T, R, V]. Returns [B, T, R, N] bool
    (PAD requirements evaluate True so they AND away)."""
    slot_c = jnp.clip(slot, 0, nodes["label_vals"].shape[1] - 1)
    # node label value id at the requirement's key slot: [B, T, R, N]
    node_val = nodes["label_vals"].T[slot_c]  # label_vals.T is [K, N]
    known = slot >= 0
    present = known[..., None] & (node_val != 0)
    node_num = nodes["label_num"].T[slot_c]
    node_num_ok = nodes["label_num_ok"].T[slot_c] & known[..., None]
    in_set = jnp.any(node_val[..., None, :] == vals[..., :, None], axis=-2)
    name_eq = nodes["name_id"][None, None, None, :] == vals[..., 0:1]

    res = jnp.ones_like(present)
    opx = op[..., None]
    res = jnp.where(opx == OP_IN, present & in_set, res)
    res = jnp.where(opx == OP_NOT_IN, ~present | ~in_set, res)
    res = jnp.where(opx == OP_EXISTS, present, res)
    res = jnp.where(opx == OP_DOES_NOT_EXIST, ~present, res)
    res = jnp.where(opx == OP_GT, node_num_ok & (node_num > num[..., None]), res)
    res = jnp.where(opx == OP_LT, node_num_ok & (node_num < num[..., None]), res)
    res = jnp.where(opx == OP_NAME_IN, name_eq, res)
    res = jnp.where(opx == OP_NAME_NOT_IN, ~name_eq, res)
    res = jnp.where(opx == OP_NEVER, jnp.zeros_like(res), res)
    return res


def pod_match_node_selector(nodes: Arrays, pods: Arrays) -> jnp.ndarray:
    """PodMatchNodeSelector (predicates.go:979): nodeSelector map pairs ANDed
    with required node-affinity terms (terms ORed; reqs in a term ANDed;
    empty/absent term list matches nothing when required != nil)."""
    # nodeSelector map: [B, NSP] pairs
    slot = pods["sel_pair_slot"]
    slot_c = jnp.clip(slot, 0, nodes["label_vals"].shape[1] - 1)
    node_val = nodes["label_vals"].T[slot_c]  # [B, NSP, N]
    pair_ok = (slot[..., None] < 0) | (node_val == pods["sel_pair_val"][..., None])
    map_ok = jnp.all(pair_ok, axis=1)  # [B, N]

    req_ok = _eval_requirements(
        nodes, pods["term_req_op"], pods["term_req_slot"], pods["term_req_vals"], pods["term_req_num"]
    )  # [B, TERMS, REQS, N]
    term_ok = pods["term_valid"][..., None] & jnp.all(req_ok, axis=2)  # [B, TERMS, N]
    affinity_ok = jnp.any(term_ok, axis=1) | ~pods["has_required"][:, None]
    return map_ok & affinity_ok


def pod_tolerates_node_taints(nodes: Arrays, pods: Arrays) -> jnp.ndarray:
    """PodToleratesNodeTaints (predicates.go:1604): every NoSchedule/NoExecute
    taint must be tolerated."""
    blocking = (nodes["taint_effect"] == EFFECT_NO_SCHEDULE) | (
        nodes["taint_effect"] == EFFECT_NO_EXECUTE
    )  # [N, T]
    tol = _tolerates(
        pods,
        nodes["taint_key"][None, :, :].reshape(1, -1),
        nodes["taint_val"][None, :, :].reshape(1, -1),
        nodes["taint_effect"][None, :, :].reshape(1, -1),
    )  # [B, N*T]
    n, t = nodes["taint_key"].shape
    tol = tol.reshape(-1, n, t)
    return jnp.all(~blocking[None, :, :] | tol, axis=-1)


@jax.jit
def filter_masks(nodes: Arrays, pods: Arrays, ids: Arrays) -> Dict[str, jnp.ndarray]:
    """All non-topology predicate masks, individually (for parity tests and
    failure-reason reporting) — callers normally use combined_mask."""
    return {
        "unschedulable": check_node_unschedulable(nodes, pods, ids),
        "host": pod_fits_host(nodes, pods),
        "ports": pod_fits_host_ports(nodes, pods, ids),
        "selector": pod_match_node_selector(nodes, pods),
        "resources": pod_fits_resources(nodes, pods),
        "taints": pod_tolerates_node_taints(nodes, pods),
    }


# mask key → Policy/provider registration name (predicates.go:56-110;
# GeneralPredicates expands per predicates.go:1204)
_MASK_PRED_NAMES = {
    "unschedulable": "CheckNodeUnschedulable",
    "host": "HostName",
    "ports": "PodFitsHostPorts",
    "selector": "MatchNodeSelector",
    "resources": "PodFitsResources",
    "taints": "PodToleratesNodeTaints",
}
from ..oracle.predicates import GENERAL_PREDICATES_EXPANSION as _GENERAL


@partial(jax.jit, static_argnames=("predicates",))
def combined_mask(
    nodes: Arrays, pods: Arrays, ids: Arrays, predicates=None
) -> jnp.ndarray:
    """findNodesThatFit's feasibility matrix [B, N]: AND of the ENABLED
    predicates (None = all; a Policy's set gates at trace time — each
    distinct set is one extra compile, not a runtime branch), masked by
    row/col validity."""
    m = filter_masks(nodes, pods, ids)
    out = pods["valid"][:, None] & jnp.ones_like(m["resources"])

    def on(key: str) -> bool:
        if predicates is None:
            return True
        name = _MASK_PRED_NAMES[key]
        return name in predicates or (name in _GENERAL and "GeneralPredicates" in predicates)

    for key in ("unschedulable", "host", "ports", "selector", "resources", "taints"):
        if on(key):
            out = out & m[key]
    # nodes whose structures overflowed the encoding are excluded from the
    # fast path entirely (conservative; the driver may oracle-check them)
    ok_nodes = nodes["valid"] & ~nodes.get("fallback", jnp.zeros_like(nodes["valid"]))
    return out & ok_nodes[None, :]


def make_ids(vocab) -> Dict[str, jnp.ndarray]:
    """Interned constants the kernels need, as device scalars."""
    from ..api.types import TAINT_NODE_UNSCHEDULABLE

    return {
        "wildcard_ip": jnp.int32(vocab.wildcard_ip),
        "unschedulable_key": jnp.int32(vocab.id(TAINT_NODE_UNSCHEDULABLE)),
        "empty_val": jnp.int32(vocab.id("")),
    }
