"""Vectorized Score kernels: pods x nodes priority matrices.

The reference computes priorities with per-node goroutine map/reduce
(PrioritizeNodes, core/generic_scheduler.go:699-830). Here every priority is
a broadcasted [B, N] arithmetic expression over the tensor encoding, fused by
XLA; normalization reduces ride the node axis.

MaxNodeScore = 10 (framework/v1alpha1/interface.go:77). Integer divisions
replicate Go's truncating semantics on non-negative operands; Balanced
allocation uses float64 like the reference, then truncates.

Covered here (non-topology): LeastRequested, MostRequested,
BalancedResourceAllocation, NodeAffinity(preferred), TaintToleration
(PreferNoSchedule), NodePreferAvoidPods, ImageLocality. Topology-coupled
priorities (SelectorSpread, EvenPodsSpread-soft, InterPodAffinity) live in
topology.py. Parity: tests/test_score_parity.py.
"""

from __future__ import annotations

from functools import partial
from typing import Dict

import jax
import jax.numpy as jnp

from ..state.tensors import EFFECT_PREFER_NO_SCHEDULE, TOL_EXISTS
from .filters import _eval_requirements

Arrays = Dict[str, jnp.ndarray]

MAX_NODE_SCORE = 10

# image_locality.go thresholds
_MB = 1024 * 1024
IMAGE_MIN = 23 * _MB
IMAGE_MAX = 1000 * _MB


def normalize_reduce(scores: jnp.ndarray, node_valid: jnp.ndarray, reverse: bool) -> jnp.ndarray:
    """NormalizeReduce (priorities/reduce.go): scale each row to [0, 10] by
    its max over valid nodes; all-zero rows stay 0 (or become 10 reversed)."""
    masked = jnp.where(node_valid[None, :], scores, 0)
    row_max = jnp.max(masked, axis=1, keepdims=True)
    scaled = jnp.where(row_max > 0, MAX_NODE_SCORE * scores // jnp.maximum(row_max, 1), 0)
    if reverse:
        scaled = jnp.where(row_max > 0, MAX_NODE_SCORE - scaled, MAX_NODE_SCORE)
    return scaled


def _requested_both(nodes: Arrays, pods: Arrays):
    """allocatable and (non-zero accumulated + incoming scoring) requested for
    cpu/mem (calculateResourceAllocatableRequest)."""
    alloc_cpu = nodes["alloc"][:, 0][None, :]
    alloc_mem = nodes["alloc"][:, 1][None, :]
    req_cpu = nodes["nonzero_req"][:, 0][None, :] + pods["scoring_req"][:, 0][:, None]
    req_mem = nodes["nonzero_req"][:, 1][None, :] + pods["scoring_req"][:, 1][:, None]
    return alloc_cpu, req_cpu, alloc_mem, req_mem


def _least_score(req, cap):
    ok = (cap > 0) & (req <= cap)
    return jnp.where(ok, (cap - req) * MAX_NODE_SCORE // jnp.maximum(cap, 1), 0)


def _most_score(req, cap):
    ok = (cap > 0) & (req <= cap)
    return jnp.where(ok, req * MAX_NODE_SCORE // jnp.maximum(cap, 1), 0)


def least_requested(nodes: Arrays, pods: Arrays) -> jnp.ndarray:
    ac, rc, am, rm = _requested_both(nodes, pods)
    return (_least_score(rc, ac) + _least_score(rm, am)) // 2


def most_requested(nodes: Arrays, pods: Arrays) -> jnp.ndarray:
    ac, rc, am, rm = _requested_both(nodes, pods)
    return (_most_score(rc, ac) + _most_score(rm, am)) // 2


def balanced_allocation(nodes: Arrays, pods: Arrays) -> jnp.ndarray:
    """balanced_resource_allocation.go: (1 - |cpuFrac - memFrac|) * 10
    truncated; 0 when either fraction >= 1; missing capacity -> fraction 1."""
    ac, rc, am, rm = _requested_both(nodes, pods)
    cpu_frac = jnp.where(ac > 0, rc.astype(jnp.float64) / jnp.maximum(ac, 1), 1.0)
    mem_frac = jnp.where(am > 0, rm.astype(jnp.float64) / jnp.maximum(am, 1), 1.0)
    diff = jnp.abs(cpu_frac - mem_frac)
    score = ((1.0 - diff) * MAX_NODE_SCORE).astype(jnp.int64)
    return jnp.where((cpu_frac >= 1) | (mem_frac >= 1), 0, score)


def node_affinity(nodes: Arrays, pods: Arrays) -> jnp.ndarray:
    """CalculateNodeAffinityPriorityMap + NormalizeReduce(10, false): sum of
    weights of matching preferred terms; a term with no expressions matches
    everywhere (plain selector semantics)."""
    req_ok = _eval_requirements(
        nodes, pods["pref_req_op"], pods["pref_req_slot"], pods["pref_req_vals"], pods["pref_req_num"]
    )  # [B, PT, REQS, N]
    term_ok = jnp.all(req_ok, axis=2) & pods["pref_valid"][..., None]  # [B, PT, N]
    counts = jnp.sum(term_ok * pods["pref_weight"][..., None], axis=1)  # [B, N]
    return normalize_reduce(counts.astype(jnp.int64), nodes["valid"], reverse=False)


def taint_toleration(nodes: Arrays, pods: Arrays) -> jnp.ndarray:
    """ComputeTaintTolerationPriorityMap + NormalizeReduce(10, true): count of
    intolerable PreferNoSchedule taints, inverted. Only tolerations with
    empty or PreferNoSchedule effect participate."""
    prefer = nodes["taint_effect"] == EFFECT_PREFER_NO_SCHEDULE  # [N, T]
    # eligible tolerations: effect in {all(0), PreferNoSchedule}
    tol_eligible = pods["tol_valid"] & (
        (pods["tol_effect"] == 0) | (pods["tol_effect"] == EFFECT_PREFER_NO_SCHEDULE)
    )  # [B, TL]
    tk = nodes["taint_key"][None, :, :, None]  # [1, N, T, 1]
    tv = nodes["taint_val"][None, :, :, None]
    te = nodes["taint_effect"][None, :, :, None]
    pk = pods["tol_key"][:, None, None, :]  # [B, 1, 1, TL]
    pv = pods["tol_val"][:, None, None, :]
    pe = pods["tol_effect"][:, None, None, :]
    po = pods["tol_op"][:, None, None, :]
    ok = (
        tol_eligible[:, None, None, :]
        & ((pe == 0) | (pe == te))
        & ((pk == 0) | (pk == tk))
        & ((po == TOL_EXISTS) | (pv == tv))
    )
    tolerated = jnp.any(ok, axis=-1)  # [B, N, T]
    intolerable = jnp.sum(prefer[None, :, :] & ~tolerated, axis=-1)  # [B, N]
    return normalize_reduce(intolerable.astype(jnp.int64), nodes["valid"], reverse=True)


def prefer_avoid_pods(nodes: Arrays, pods: Arrays) -> jnp.ndarray:
    """CalculateNodePreferAvoidPodsPriorityMap: 0 when the node's
    preferAvoidPods signatures name the pod's RC/RS controller, else 10."""
    kind = pods["ctrl_kind"][:, None, None]  # [B, 1, 1]
    uid = pods["ctrl_uid"][:, None, None]
    hit = (nodes["avoid_kind"][None, :, :] == kind) & (nodes["avoid_uid"][None, :, :] == uid)
    avoided = (kind[..., 0] > 0) & jnp.any(hit, axis=-1)
    return jnp.where(avoided, 0, MAX_NODE_SCORE).astype(jnp.int64)


def image_locality(nodes: Arrays, pods: Arrays) -> jnp.ndarray:
    """ImageLocalityPriorityMap: gather spread-scaled image sizes per
    (pod image, node), clamp to [23MB, 1000MB], map to [0, 10]."""
    table = nodes["image_scaled"]  # [N, V_img]
    # ids beyond the table width are images no node has (interned after the
    # table was built) — they contribute 0, not an aliased column
    in_vocab = (pods["image_ids"] > 0) & (pods["image_ids"] < table.shape[1])
    img = jnp.where(in_vocab, pods["image_ids"], 0)  # [B, CI]; col 0 is zeros
    sums = jnp.sum(table[:, img], axis=-1)  # [N, B] (gather then sum CI)
    total = sums.T  # [B, N]
    clamped = jnp.clip(total, IMAGE_MIN, IMAGE_MAX)
    return MAX_NODE_SCORE * (clamped - IMAGE_MIN) // (IMAGE_MAX - IMAGE_MIN)


def resource_limits(nodes: Arrays, pods: Arrays) -> jnp.ndarray:
    """ResourceLimitsPriorityMap (resource_limits.go:36-88): 1 when the node
    can hold the pod's cpu OR memory limit (both quantities nonzero), else
    0 — an unnormalized tie-breaker (Reduce nil)."""
    lc = pods["limit_req"][:, 0][:, None]  # [B, 1]
    lm = pods["limit_req"][:, 1][:, None]
    ac = nodes["alloc"][:, 0][None, :]  # [1, N]
    am = nodes["alloc"][:, 1][None, :]
    cpu_ok = (lc != 0) & (ac != 0) & (lc <= ac)
    mem_ok = (lm != 0) & (am != 0) & (lm <= am)
    return (cpu_ok | mem_ok).astype(jnp.int64)


# default shape prefers least-utilized nodes (requested_to_capacity_ratio.go:40)
DEFAULT_RTCR_SHAPE = ((0, 10), (100, 0))
DEFAULT_RTCR_RESOURCES = (("cpu", 1), ("memory", 1))
# device-bank column for each RTCR-scorable resource: (alloc col, nonzero
# col, scoring col) — extended resources are host-path only
_RTCR_COLUMNS = {"cpu": 0, "memory": 1}


def _go_div(a: jnp.ndarray, b) -> jnp.ndarray:
    """Go integer division truncates toward zero; // floors. Matters on
    down-sloping shape segments where the numerator is negative."""
    q = jnp.abs(a) // abs(b)
    return jnp.where((a < 0) != (b < 0), -q, q)


def requested_to_capacity_ratio(
    nodes: Arrays,
    pods: Arrays,
    shape=DEFAULT_RTCR_SHAPE,
    resources=DEFAULT_RTCR_RESOURCES,
) -> jnp.ndarray:
    """RequestedToCapacityRatio (requested_to_capacity_ratio.go:115-167):
    per resource, utilization% through the broken-linear shape (full or
    absent capacity evaluates at 100%); resources scoring 0 are excluded
    from the weighted mean, which rounds half away from zero (math.Round).
    `shape`/`resources` are static — one compile per Policy."""

    def raw(p: jnp.ndarray) -> jnp.ndarray:
        # unrolled piecewise-linear: evaluate segments back-to-front so the
        # first matching `p <= u_i` wins (buildBrokenLinearFunction)
        out = jnp.full_like(p, shape[-1][1])
        for i in range(len(shape) - 1, -1, -1):
            u, s = shape[i]
            if i == 0:
                val = jnp.full_like(p, s)
            else:
                u0, s0 = shape[i - 1]
                val = s0 + _go_div((s - s0) * (p - u0), u - u0)
            out = jnp.where(p <= u, val, out)
        return out

    node_score = jnp.zeros((), jnp.int64)
    weight_sum = jnp.zeros((), jnp.int64)
    for rname, weight in resources:
        col = _RTCR_COLUMNS[rname]
        cap = nodes["alloc"][:, col][None, :]
        req = nodes["nonzero_req"][:, col][None, :] + pods["scoring_req"][:, col][:, None]
        full = (cap == 0) | (req > cap)
        p = jnp.where(full, 100, 100 - (cap - req) * 100 // jnp.maximum(cap, 1))
        s = raw(p)
        pos = s > 0
        node_score = node_score + jnp.where(pos, s * weight, 0)
        weight_sum = weight_sum + jnp.where(pos, weight, 0)
    return jnp.where(
        weight_sum > 0,
        (2 * node_score + weight_sum) // jnp.maximum(2 * weight_sum, 1),
        0,
    )


# default-provider weights (algorithmprovider/defaults/defaults.go:128)
DEFAULT_WEIGHTS = {
    "least_requested": 1,
    "balanced_allocation": 1,
    "node_affinity": 1,
    "taint_toleration": 1,
    "prefer_avoid_pods": 10000,
    "image_locality": 1,
}


# Policy/provider registration name → kernel (priorities.go:21-56)
_PRIORITY_KERNELS = {
    "LeastRequestedPriority": least_requested,
    "MostRequestedPriority": most_requested,
    "BalancedResourceAllocation": balanced_allocation,
    "NodeAffinityPriority": node_affinity,
    "TaintTolerationPriority": taint_toleration,
    "NodePreferAvoidPodsPriority": prefer_avoid_pods,
    "ImageLocalityPriority": image_locality,
    "ResourceLimitsPriority": resource_limits,
}

# the default provider's weighted sum in registration-name form
DEFAULT_PRIORITY_TUPLE = (
    ("LeastRequestedPriority", 1),
    ("BalancedResourceAllocation", 1),
    ("NodeAffinityPriority", 1),
    ("TaintTolerationPriority", 1),
    ("NodePreferAvoidPodsPriority", 10000),
    ("ImageLocalityPriority", 1),
)


@partial(jax.jit, static_argnames=("priorities", "rtcr"))
def score_matrix(nodes: Arrays, pods: Arrays, priorities=None, rtcr=None) -> jnp.ndarray:
    """Weighted sum of the enabled non-topology priorities → [B, N] int64
    (None = default provider weights). The topology scores (topology.py)
    are added by the solver before argmax. `priorities` is a static tuple
    of (registration name, weight) — each distinct config compiles once.
    `rtcr` is the optional (shape, resources) Policy argument for
    RequestedToCapacityRatioPriority."""
    pairs = priorities if priorities is not None else DEFAULT_PRIORITY_TUPLE
    total = jnp.zeros((), jnp.int64)
    for name, weight in pairs:
        if name == "RequestedToCapacityRatioPriority":
            shape, res = rtcr if rtcr is not None else (DEFAULT_RTCR_SHAPE, DEFAULT_RTCR_RESOURCES)
            total = total + weight * requested_to_capacity_ratio(nodes, pods, shape, res)
            continue
        kernel = _PRIORITY_KERNELS.get(name)
        if kernel is None:
            continue  # host-only priorities (SelectorSpread etc.) add later
        if name == "ImageLocalityPriority" and "image_scaled" not in nodes:
            continue
        total = total + weight * kernel(nodes, pods)
    b, n = pods["valid"].shape[0], nodes["valid"].shape[0]
    return jnp.broadcast_to(total, (b, n)) if total.ndim == 0 else total


@jax.jit
def score_components(nodes: Arrays, pods: Arrays) -> Dict[str, jnp.ndarray]:
    out = {
        "least_requested": least_requested(nodes, pods),
        "most_requested": most_requested(nodes, pods),
        "balanced_allocation": balanced_allocation(nodes, pods),
        "node_affinity": node_affinity(nodes, pods),
        "taint_toleration": taint_toleration(nodes, pods),
        "prefer_avoid_pods": prefer_avoid_pods(nodes, pods),
    }
    if "image_scaled" in nodes:
        out["image_locality"] = image_locality(nodes, pods)
    return out
