"""Device kernels: vectorized Filter/Score/solve over pods x nodes tensors.

Resource quantities are exact int64 (memory bytes exceed int32), so x64 mode
is enabled at import. Kernels keep everything else int32/bool/float32 — the
int64 use is confined to elementwise compares on [N, R]-sized arrays where
TPU's emulated 64-bit integer cost is negligible.
"""

import jax

jax.config.update("jax_enable_x64", True)
