"""Fused solve pipeline: the whole scheduling cycle as ONE XLA program.

The reference splits a cycle into findNodesThatFit → PrioritizeNodes →
selectHost (core/generic_scheduler.go:174-280), each walking the node set.
Here every Filter mask, every Score matrix, and the greedy batch assignment
fuse into a single jitted computation — one device dispatch, one transfer
of results, no host round-trips between stages. On a remote-attached TPU
each eager op costs a network round-trip, so fusion is not just an
optimization: it is the difference between milliseconds and seconds per
batch.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from . import filters as F
from . import scores as S
from . import topology as T
from .solver import pop_order, solve_gang, solve_greedy

Arrays = Dict[str, jnp.ndarray]


@dataclass(frozen=True)
class SolveConfig:
    """Device-solve policy (hashable → one XLA compile per distinct config):
    which predicates gate the mask and which (priority, weight) pairs sum
    into the score — the algorithm-provider / Policy selection
    (factory.go CreateFromKeys) expressed as jit statics. None = the
    default provider."""

    predicates: Optional[frozenset] = None
    priorities: Optional[Tuple[Tuple[str, int], ...]] = None
    # RequestedToCapacityRatio Policy argument: (shape points, resource
    # weights), both tuples (api/types.go RequestedToCapacityRatioArguments)
    rtcr: Optional[Tuple[Tuple[Tuple[int, int], ...], Tuple[Tuple[str, int], ...]]] = None

    def priority_weight(self, name: str, default: int) -> int:
        if self.priorities is None:
            return default
        for n, w in self.priorities:
            if n == name:
                return w
        return 0


DEFAULT_SOLVE_CONFIG = SolveConfig()


def mask_and_score(
    na: Arrays,
    pa: Arrays,
    ea: Arrays,
    ta: Arrays,
    xa: Arrays,
    au: Arrays,
    ids: Arrays,
    config: Optional[SolveConfig] = None,
    term_kinds: Optional[frozenset] = None,
    n_buckets: Optional[int] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """The fused Filter+Score stage shared by every solve entry point
    (plain, gang, sharded) — one definition so they can never diverge.

    `n_buckets` (jit static) bounds the per-topology-value segment axis:
    the distinct dense values per label key are few (zones, hostnames seen
    in terms...), so aggregating into a [*, n_buckets] table instead of
    [*, N] keeps the scatter outputs tiny. None = N (always safe).

    `term_kinds` (jit static) names the term kinds PRESENT this batch —
    {"spread_hard","spread_soft","aff_req","anti_req","pref","sel_spread",
    "et_anti","et_score"}; None means assume everything. The driver
    computes it host-side so a batch without, say, inter-pod terms never
    executes (or compiles) the inter-pod kernels: a skipped kernel's
    term-absent identity (pass-everything mask / zero score) is exact."""
    cfg = config or DEFAULT_SOLVE_CONFIG
    preds = cfg.predicates
    k = term_kinds

    def have(*names):
        return k is None or any(n in k for n in names)

    mask = F.combined_mask(na, pa, ids, predicates=preds)
    sel = F.pod_match_node_selector(na, pa)
    if (preds is None or "EvenPodsSpread" in preds) and have("spread_hard"):
        mask = mask & T.spread_filter(na, ea, ta, sel, n_buckets=n_buckets)
    if preds is None or "MatchInterPodAffinity" in preds:
        parts = tuple(
            p for p, kinds in (
                ("existing", ("et_anti",)),
                ("aff", ("aff_req",)),
                ("anti", ("anti_req",)),
            ) if have(*kinds)
        )
        if parts:
            mask = mask & T.interpod_filter(
                na, ea, ta, au, xa, pa, parts=parts, n_buckets=n_buckets
            )
    score = S.score_matrix(na, pa, priorities=cfg.priorities, rtcr=cfg.rtcr)
    w = cfg.priority_weight("InterPodAffinityPriority", 1)
    if w:
        parts = tuple(
            p for p, kinds in (("pref", ("pref",)), ("existing", ("et_score",)))
            if have(*kinds)
        )
        if parts:
            score = score + w * T.interpod_score(
                na, ea, ta, xa, pa, parts=parts, n_buckets=n_buckets
            )
    w = cfg.priority_weight("EvenPodsSpreadPriority", 1)
    if w and have("spread_soft"):
        score = score + w * T.spread_score(na, ea, ta, au, sel, n_buckets=n_buckets)
    w = cfg.priority_weight("SelectorSpreadPriority", 1)
    if w and have("sel_spread"):
        score = score + w * T.selector_spread_score(na, ea, ta, au, n_buckets=n_buckets)
    elif w:
        # term-absent identity is NOT zero here: a pod with no controller
        # selectors scores MaxNodeScore on every node (the map counts 0,
        # the reduce turns all-zero into all-max — selector_spreading.go)
        score = score + w * T.MAX_NODE_SCORE
    return mask, score


@partial(jax.jit, static_argnames=("config", "term_kinds", "n_buckets"))
def filter_mask(
    na: Arrays,
    pa: Arrays,
    ea: Arrays,
    ta: Arrays,
    xa: Arrays,
    au: Arrays,
    ids: Arrays,
    config: Optional[SolveConfig] = None,
    term_kinds: Optional[frozenset] = None,
    n_buckets: Optional[int] = None,
) -> jnp.ndarray:
    """Filter-only entry point (the extender /filter path): shares
    mask_and_score so the gating can never diverge; XLA dead-code-eliminates
    the unused score computation."""
    mask, _ = mask_and_score(na, pa, ea, ta, xa, au, ids, config, term_kinds, n_buckets)
    return mask


def _pod_axis(pa: Arrays, pb: Optional[Arrays]):
    """Resolve the per-POD axis: (sig, valid, priority, B). `pa` rows are
    per unique SPEC; `pb` (when given) maps batch positions onto them —
    replica sets collapse to one mask/score row. pb=None is the identity
    (one spec row per pod; the pre-dedup contract kept for tests/tools)."""
    if pb is None:
        b = pa["valid"].shape[0]
        return None, pa["valid"], pa["priority"], b
    sig = pb["sig"]
    return sig, pb["valid"], pb["priority"], sig.shape[0]


def apply_carry(na: Arrays, carry: Optional[Tuple]) -> Arrays:
    """Overlay a previous batch's device residual carry onto the node
    bank's pod-driven columns (the speculative-pipelining contract). The
    ONE definition shared by solve_pipeline, solve_pipeline_gang, and the
    sharded _prep so the three paths can never desync."""
    if carry is None:
        return na
    free_in, count_in, nz_in = carry
    return {
        **na,
        "requested": na["alloc"] - free_in,
        "pod_count": count_in,
        "nonzero_req": nz_in,
    }


def _inbatch_tensors(na, pa, ta, ids, n_buckets):
    """Build solve_greedy's `inb` dict: the device-side state that lets the
    solver sequentialize required anti-affinity and host-port conflicts
    WITHIN the batch (kills the commit loop's per-pod LIGHT rechecks)."""
    from .topology import ANTI_REQ, _bucket_of, match_terms

    N = na["valid"].shape[0]
    U = pa["valid"].shape[0]
    V = n_buckets or N
    anti = ta["valid"] & (ta["kind"] == ANTI_REQ)
    m_bb = match_terms(ta, pa["label_vals"], pa["ns_id"])  # [TT, U]
    bucket_n, haskey_n = _bucket_of(na, ta["topo_slot"])  # [TT, N]
    TT = anti.shape[0]
    # pairwise spec port conflicts — same CheckConflict core as the
    # pod-vs-node mask (filters.port_clash), reduced over both port lists
    pconf = jnp.any(
        F.port_clash(
            pa["port_num"][:, None, :, None],
            pa["port_proto"][:, None, :, None],
            pa["port_ip"][:, None, :, None],
            pa["port_num"][None, :, None, :],
            pa["port_proto"][None, :, None, :],
            pa["port_ip"][None, :, None, :],
            ids["wildcard_ip"],
        ),
        axis=(2, 3),
    )  # [U, U]
    return {
        "anti": anti,
        "owner": ta["owner"].astype(jnp.int32),
        "m_bb": m_bb,
        "bucket_n": bucket_n,
        "haskey_n": haskey_n,
        "port_conflict": pconf,
        "ca0": jnp.zeros((TT, V), jnp.float32),
        "cb0": jnp.zeros((TT, V), jnp.float32),
        "cs0": jnp.zeros((U, N), jnp.float32),
    }


@partial(jax.jit, static_argnames=(
    "deterministic", "config", "term_kinds", "n_buckets", "return_carry",
    "track_inbatch",
))
def solve_pipeline(
    na: Arrays,  # NodeBank arrays
    pa: Arrays,  # PodBatch arrays (one row per unique pod spec)
    ea: Arrays,  # SigBank arrays (existing-pod label signatures + per-node counts)
    ta: Arrays,  # batch TermBank arrays (host-compiled, or gathered on
    # device from the resident term bank — terms_plane/gather; the two
    # transports are bit-identical by construction)
    xa: Arrays,  # existing-pods TermBank arrays
    au: Arrays,  # compile_batch_terms aux
    ids: Arrays,  # interned constants (filters.make_ids)
    key,  # PRNG key for selectHost tie-breaks
    pb: Optional[Arrays] = None,  # per-pod axis: sig/valid/priority [B]
    carry: Optional[Tuple] = None,  # (free, count, nz) from the PREVIOUS batch
    deterministic: bool = False,
    config: Optional[SolveConfig] = None,
    term_kinds: Optional[frozenset] = None,
    n_buckets: Optional[int] = None,
    return_carry: bool = False,
    track_inbatch: bool = False,
):
    """mask → score → greedy solve. Returns (assign [B], score [U, N])
    (+ the post-batch (free, count, nz) residual carry when return_carry).

    `carry` enables SPECULATIVE PIPELINING (SURVEY §2.3, the reference's
    assume-then-async-bind applied to the solve): the previous batch's
    device-computed residuals replace the pod-driven node columns
    (requested/pod_count/nonzero_req), so this batch can be dispatched
    before the host has committed the previous one. Node identity columns
    (labels/taints/...) are untouched by pod commits, and the driver
    re-solves from trued-up banks whenever a commit diverged from the
    device's choice."""
    na = apply_carry(na, carry)
    mask, score = mask_and_score(na, pa, ea, ta, xa, au, ids, config, term_kinds, n_buckets)
    free0 = na["alloc"] - na["requested"]
    sig, pvalid, prio, b = _pod_axis(pa, pb)
    order = pop_order(prio, jnp.arange(b, dtype=jnp.int32), pvalid)
    result = solve_greedy(
        mask,
        score,
        pa["req"],
        free0,
        na["pod_count"].astype(free0.dtype),
        na["allowed_pods"].astype(free0.dtype),
        order,
        key,
        deterministic=deterministic,
        req_any=pa["req_any"],
        sig=sig,
        pod_valid=pvalid,
        return_carry=return_carry,
        nz0=na["nonzero_req"].astype(free0.dtype) if return_carry else None,
        scoring_req=pa["scoring_req"] if return_carry else None,
        inb=_inbatch_tensors(na, pa, ta, ids, n_buckets) if track_inbatch else None,
    )
    if return_carry:
        assign, carry_out = result
        return assign, score, carry_out
    return result, score


@partial(jax.jit, static_argnames=(
    "deterministic", "config", "term_kinds", "n_buckets", "return_carry"
))
def solve_pipeline_gang(
    na: Arrays,
    pa: Arrays,
    ea: Arrays,
    ta: Arrays,
    xa: Arrays,
    au: Arrays,
    ids: Arrays,
    key,
    group: jnp.ndarray,  # [B] group id, -1 = ungrouped (per batch position)
    pb: Optional[Arrays] = None,
    carry: Optional[Tuple] = None,
    deterministic: bool = False,
    config: Optional[SolveConfig] = None,
    term_kinds: Optional[frozenset] = None,
    n_buckets: Optional[int] = None,
    return_carry: bool = False,
):
    """Gang variant: same fused mask/score, then the all-or-nothing
    two-pass solve (ops/solver.solve_gang). Returns (assign, score,
    gang_ok[, carry]) — members of dropped groups come back assign=-1,
    gang_ok False, and their capacity is released to other pods in pass 2.
    `carry`/`return_carry` follow the solve_pipeline contract so gang
    batches participate in speculative pipelining."""
    na = apply_carry(na, carry)
    mask, score = mask_and_score(na, pa, ea, ta, xa, au, ids, config, term_kinds, n_buckets)
    free0 = na["alloc"] - na["requested"]
    sig, pvalid, prio, b = _pod_axis(pa, pb)
    order = pop_order(prio, jnp.arange(b, dtype=jnp.int32), pvalid)
    result = solve_gang(
        mask,
        score,
        pa["req"],
        free0,
        na["pod_count"].astype(free0.dtype),
        na["allowed_pods"].astype(free0.dtype),
        order,
        group,
        key,
        deterministic=deterministic,
        req_any=pa["req_any"],
        sig=sig,
        pod_valid=pvalid,
        return_carry=return_carry,
        nz0=na["nonzero_req"].astype(free0.dtype) if return_carry else None,
        scoring_req=pa["scoring_req"] if return_carry else None,
    )
    if return_carry:
        assign, gang_ok, carry_out = result
        return assign, score, gang_ok, carry_out
    assign, gang_ok = result
    return assign, score, gang_ok


def encode_solve_args(snapshot, pods, spread_selectors=None, key=None):
    """One-shot encode of (snapshot, pending pods) → solve_pipeline args.

    Test/tooling convenience for driving the pipeline outside the
    Scheduler's incremental TensorMirror path: full snapshot encode
    (state/tensors.encode_snapshot), batch + term compilation, interned
    constants, PRNG key. Returns the positional argument tuple for
    solve_pipeline / make_sharded_pipeline(mesh).
    """
    from ..state.tensors import PodBatch, _bucket, encode_snapshot
    from ..state.terms import compile_batch_terms, compile_existing_patterns

    bank, epsb, row_of = encode_snapshot(snapshot)
    vocab = bank.vocab
    batch = PodBatch(vocab, _bucket(len(pods)))
    for i, p in enumerate(pods):
        batch.set_pod(i, p)
    tb, aux = compile_batch_terms(
        vocab, pods, spread_selectors=spread_selectors, b_capacity=batch.capacity
    )
    etb = compile_existing_patterns(vocab, snapshot, row_of, bank.capacity)
    dev = lambda d: {k: jnp.asarray(v) for k, v in d.items()}
    return (
        dev(bank.arrays()),
        dev(batch.arrays()),
        dev(epsb.arrays()),
        dev(tb.arrays()),
        dev(etb.arrays()),
        dev(aux),
        F.make_ids(vocab),
        key if key is not None else jax.random.PRNGKey(0),
    )


@jax.jit
def gather_score_rows(score: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """Device-side row gather so the host fetches ONLY the score rows it
    needs for oracle re-placement. On a remote-attached TPU a device→host
    copy has ~100ms fixed latency and low bandwidth — fetching the full
    [B, N] matrix (hundreds of MB at 10k nodes) must never happen."""
    return score[idx]
