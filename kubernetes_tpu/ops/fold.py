"""Resident-state fold kernels: apply commit deltas to the device banks
IN PLACE (buffer donation), so a covered batch's solve inputs never make
the device→host→device round trip.

The mirror's patch path (state/cache.TensorMirror.device_arrays) re-ships
every dirty row as a host slice + scatter: after a 4096-pod commit batch
that is ~600 bytes/row of usage columns and signature counts crossing the
wire — `patch_s`/`fetch_s` seconds per drain on a remote-attached chip.
But the host applies those SAME deltas as integer adds (NodeBank
.apply_pod_deltas_bulk, SigBank.apply_adds_bulk, PatternBank.apply_delta)
— a pure function of tiny control data the host already has at commit
time. These kernels run that function ON DEVICE instead: ship only the
control (rows, request vectors, signature indices — a few hundred KB at
worst), scatter-add into the resident banks, and DONATE the input buffers
so the tens-of-MB banks are updated in place rather than copied.

Bit-exactness contract: integer adds commute with the dtype truncation
the upload path applies (two's-complement wrap), and the control values
come from the exact memoized sources the host delta path reads
(_req_slot_pairs, pod_non_zero_request, SigBank/PatternBank interning) —
so a folded row is bit-identical to what the host scatter would have
shipped. tests/test_fold_plane.py pins this after seeded drains.

Padding discipline: control arrays are padded to ladder buckets with
OUT-OF-BOUNDS sentinel indices (row = N, sig = S, ...) and mode="drop" —
padded lanes scatter nowhere, so any bucket executes exactly.

Multi-chip: `make_sharded_fold_fns(mesh)` builds the node-sharded twins —
the banks stay split over the mesh's "nodes" axis (NamedSharding preserved
through donation), the replicated control arrays are re-based per shard
(global row → local row, out-of-shard lanes dropped), and no collective is
needed at all: a node row lives on exactly one shard, so every scatter is
shard-local. Bit-identical to the single-device kernels by construction
(same adds, same dtypes, disjoint row ownership).
"""

from __future__ import annotations

from functools import partial

import jax


@partial(jax.jit, donate_argnums=(0, 1, 2, 3, 4))
def fold_commit_banks(
    requested,    # [N, R] node usage matrix (donated)
    nonzero_req,  # [N, 2] (donated)
    pod_count,    # [N]    (donated)
    sig_counts,   # [N, S] SigBank.counts (donated)
    pat_counts,   # [N, PT] PatternBank.counts (donated)
    rows,         # [B] int32 node row per commit (sentinel N = pad)
    req,          # [B, R] request vector per commit (_req_slot_pairs)
    nz,           # [B, 2] pod_non_zero_request per commit
    cnt,          # [B] int32 1 per real commit, 0 pad
    sig,          # [B] int32 signature row per commit (sentinel S = pad)
    pat_row,      # [T] int32 node row per pattern instance (sentinel N)
    pat_col,      # [T] int32 pattern row (sentinel PT)
    pat_cnt,      # [T] int16 instance count (0 pad)
):
    """One committed batch folded into the resident banks. Returns the
    post-commit (requested, nonzero_req, pod_count, sig_counts,
    pat_counts) — aliased into the donated input buffers by XLA."""
    requested = requested.at[rows].add(req.astype(requested.dtype), mode="drop")
    nonzero_req = nonzero_req.at[rows].add(nz.astype(nonzero_req.dtype), mode="drop")
    pod_count = pod_count.at[rows].add(cnt.astype(pod_count.dtype), mode="drop")
    sig_counts = sig_counts.at[rows, sig].add(cnt.astype(sig_counts.dtype), mode="drop")
    pat_counts = pat_counts.at[pat_row, pat_col].add(
        pat_cnt.astype(pat_counts.dtype), mode="drop"
    )
    return requested, nonzero_req, pod_count, sig_counts, pat_counts


@partial(jax.jit, donate_argnums=(0, 1))
def fold_usage(
    requested,  # [N, R] (donated)
    pod_count,  # [N]    (donated)
    rows,       # [B] int32 node row (sentinel N = pad)
    vecs,       # [B, R] request vector per entry
    cnt,        # [B] int32 pod-count delta per entry
):
    """Usage-column-only fold (the out-of-batch NOMINEE overlay): adds the
    nominees' requests to the resident columns in place. Because integer
    adds are exactly invertible, the caller restores the pristine bank by
    calling this again with negated vecs/cnt — donation both ways, zero
    bank copies (the old overlay path copied the entire node-bank dict
    per dispatch)."""
    return (
        requested.at[rows].add(vecs.astype(requested.dtype), mode="drop"),
        pod_count.at[rows].add(cnt.astype(pod_count.dtype), mode="drop"),
    )


_SHARDED_FOLD_CACHE = {}


def make_sharded_fold_fns(mesh):
    """(fold_commit_banks, fold_usage) twins bound to `mesh`: every bank's
    leading (node) axis stays sharded over the mesh's "nodes" axis and the
    donated buffers keep their NamedSharding — the sharded pipeline's
    solve inputs never reshard after a fold. The control arrays arrive
    replicated; each shard rebases the global node rows onto its own
    columns and drops foreign lanes (sentinel n_local + mode="drop"), so
    the whole fold is collective-free. Memoized per mesh: the jitted
    closures are the program cache the warmup service and the mirror must
    share."""
    cached = _SHARDED_FOLD_CACHE.get(mesh)
    if cached is not None:
        return cached
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import AXIS_NODES, shard_map

    def _local_rows(rows, base, n_local):
        # global row → shard-local row; foreign/sentinel lanes → n_local
        # (out of bounds, dropped). Sentinel N is foreign to every shard:
        # for the LAST shard N - base == n_local, already out of bounds.
        mine = (rows >= base) & (rows < base + n_local)
        return jnp.where(mine, rows - base, n_local).astype(jnp.int32)

    def _commit_body(
        requested, nonzero_req, pod_count, sig_counts, pat_counts,
        rows, req, nz, cnt, sig, pat_row, pat_col, pat_cnt,
    ):
        n_local = requested.shape[0]
        base = (jax.lax.axis_index(AXIS_NODES) * n_local).astype(rows.dtype)
        lrows = _local_rows(rows, base, n_local)
        lprow = _local_rows(pat_row, base, n_local)
        requested = requested.at[lrows].add(
            req.astype(requested.dtype), mode="drop"
        )
        nonzero_req = nonzero_req.at[lrows].add(
            nz.astype(nonzero_req.dtype), mode="drop"
        )
        pod_count = pod_count.at[lrows].add(
            cnt.astype(pod_count.dtype), mode="drop"
        )
        sig_counts = sig_counts.at[lrows, sig].add(
            cnt.astype(sig_counts.dtype), mode="drop"
        )
        pat_counts = pat_counts.at[lprow, pat_col].add(
            pat_cnt.astype(pat_counts.dtype), mode="drop"
        )
        return requested, nonzero_req, pod_count, sig_counts, pat_counts

    def _usage_body(requested, pod_count, rows, vecs, cnt):
        n_local = requested.shape[0]
        base = (jax.lax.axis_index(AXIS_NODES) * n_local).astype(rows.dtype)
        lrows = _local_rows(rows, base, n_local)
        return (
            requested.at[lrows].add(vecs.astype(requested.dtype), mode="drop"),
            pod_count.at[lrows].add(cnt.astype(pod_count.dtype), mode="drop"),
        )

    nl = P(AXIS_NODES)
    commit = jax.jit(
        shard_map(
            _commit_body, mesh=mesh,
            in_specs=(nl,) * 5 + (P(),) * 8,
            out_specs=(nl,) * 5,
        ),
        donate_argnums=(0, 1, 2, 3, 4),
    )
    usage = jax.jit(
        shard_map(
            _usage_body, mesh=mesh,
            in_specs=(nl, nl, P(), P(), P()),
            out_specs=(nl, nl),
        ),
        donate_argnums=(0, 1),
    )
    _SHARDED_FOLD_CACHE[mesh] = (commit, usage)
    return commit, usage
