"""Batch assignment solver: the departure from the reference's one-pod loop.

The reference schedules strictly one pod per cycle (scheduleOne,
scheduler.go:579): filter -> score -> selectHost -> assume, with the cache
mutated between pods. Here a whole BATCH of pending pods is solved in one
compiled XLA program: a lax.scan walks the pods in the same order the
reference's queue would pop them (priority desc, then enqueue time asc —
internal/queue/scheduling_queue.go activeQ comparator), committing each pod
to its best feasible node and updating the resource residuals in the scan
carry. One device dispatch replaces B scheduling cycles.

Intra-batch semantics contract:
* Resources and pod counts are EXACT within the batch (the carry).
* Topology masks/scores (spread, inter-pod affinity) are computed against
  the pre-batch snapshot; pods earlier in the batch do not update them for
  later pods. Pods carrying topology constraints (or matched by existing
  anti-affinity terms) should be committed through the host-side oracle
  re-check (scheduler/driver.py) — the same optimistic-assume + re-queue
  discipline the reference applies across its async bind boundary
  (scheduler.go:631-673, MakeDefaultErrorFunc re-queue on conflict).
* selectHost tie-break: uniform among max-score nodes via the PRNG key
  (core/generic_scheduler.go:278 reservoir sampling).

Gang/all-or-nothing (absent upstream, natural here): pods may carry a group
id; a second scan pass drops groups that did not fully fit.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

Arrays = Dict[str, jnp.ndarray]


def pop_order(priority: jnp.ndarray, enqueue_seq: jnp.ndarray, valid: jnp.ndarray) -> jnp.ndarray:
    """Queue pop order: priority desc, then enqueue sequence asc (activeQ
    comparator podsCompareBackoffCompleted / higher-priority-first); invalid
    rows last. Returns the permutation [B]."""
    return jnp.lexsort((enqueue_seq, -priority.astype(jnp.int64), ~valid))


def _select_host(score: jnp.ndarray, feasible: jnp.ndarray, key) -> jnp.ndarray:
    """selectHost semantics: uniform among the max-score feasible nodes."""
    neg = jnp.iinfo(score.dtype).min
    masked = jnp.where(feasible, score, neg)
    best = jnp.max(masked)
    ties = feasible & (masked == best)
    # random tie-break: pick max over uniform noise restricted to ties
    noise = jax.random.uniform(key, score.shape)
    pick = jnp.argmax(jnp.where(ties, noise, -1.0))
    return jnp.where(jnp.any(feasible), pick, -1)


@partial(jax.jit, static_argnames=("deterministic",))
def solve_greedy(
    mask: jnp.ndarray,  # [B, N] feasibility from filter kernels
    score: jnp.ndarray,  # [B, N] weighted priority sums
    req: jnp.ndarray,  # [B, R] pod requests (GetResourceRequest)
    free0: jnp.ndarray,  # [N, R] alloc - requested at batch start
    count0: jnp.ndarray,  # [N] pod counts at batch start
    allowed: jnp.ndarray,  # [N] allowed pod numbers
    order: jnp.ndarray,  # [B] scan order (pop_order)
    rng_key,  # PRNG key for tie-breaks
    deterministic: bool = False,
    req_any: Optional[jnp.ndarray] = None,  # [B] pod requests anything at all
) -> jnp.ndarray:
    """Greedy-by-priority batch assignment → node row per pod, -1 = no fit.

    Each scan step re-checks resource fit against the carry residuals, so an
    earlier pod consuming a node's last CPU makes it infeasible for later
    pods — exactly as if the reference had scheduled them sequentially."""
    B, N = mask.shape
    if req_any is None:
        req_any = jnp.any(req > 0, axis=-1)

    def step(carry, inp):
        free, count = carry
        i, key = inp
        m = mask[i]
        # PodFitsResources (predicates.go:854): the pod-count check always
        # applies; the resource rows only when the pod requests anything, so
        # empty-request pods pass even on overcommitted (free < 0) nodes.
        res_ok = ~req_any[i] | jnp.all(req[i][None, :] <= free, axis=-1)
        fits = res_ok & (count + 1 <= allowed)
        feasible = m & fits
        if deterministic:
            neg = jnp.iinfo(score.dtype).min
            masked = jnp.where(feasible, score[i], neg)
            choice = jnp.where(jnp.any(feasible), jnp.argmax(masked), -1)
        else:
            choice = _select_host(score[i], feasible, key)
        committed = choice >= 0
        sel = jnp.where(committed, choice, 0)
        free = jnp.where(
            committed,
            free.at[sel].add(-req[i]),
            free,
        )
        count = jnp.where(committed, count.at[sel].add(1), count)
        return (free, count), choice

    keys = jax.random.split(rng_key, B)
    (_, _), choices = jax.lax.scan(step, (free0, count0), (order, keys))
    # scatter back to original pod positions
    out = jnp.full((B,), -1, jnp.int32)
    return out.at[order].set(choices.astype(jnp.int32))


@partial(jax.jit, static_argnames=("deterministic",))
def solve_gang(
    mask: jnp.ndarray,
    score: jnp.ndarray,
    req: jnp.ndarray,
    free0: jnp.ndarray,
    count0: jnp.ndarray,
    allowed: jnp.ndarray,
    order: jnp.ndarray,
    group: jnp.ndarray,  # [B] group id, -1 = ungrouped
    rng_key,
    deterministic: bool = False,
    req_any: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """All-or-nothing gang assignment: two-pass greedy. Pass 1 places
    everything; groups with any unplaced member are dropped and pass 2
    re-solves without them (their capacity is released for other pods).
    Returns (assignment [B], gang_ok [B])."""
    B = mask.shape[0]
    k1, k2 = jax.random.split(rng_key)
    first = solve_greedy(mask, score, req, free0, count0, allowed, order, k1, deterministic=deterministic, req_any=req_any)
    grouped = group >= 0
    failed_member = grouped & (first < 0)
    # group failed if ANY member failed (segment max over group ids)
    ngroups = B  # group ids are < B by construction
    fail_by_group = jnp.zeros(ngroups, bool).at[jnp.where(grouped, group, 0)].max(failed_member)
    dropped = grouped & fail_by_group[jnp.where(grouped, group, 0)]
    mask2 = mask & ~dropped[:, None]
    second = solve_greedy(mask2, score, req, free0, count0, allowed, order, k2, deterministic=deterministic, req_any=req_any)
    gang_ok = ~dropped
    return jnp.where(dropped, -1, second), gang_ok
