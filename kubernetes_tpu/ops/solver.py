"""Batch assignment solver: the departure from the reference's one-pod loop.

The reference schedules strictly one pod per cycle (scheduleOne,
scheduler.go:579): filter -> score -> selectHost -> assume, with the cache
mutated between pods. Here a whole BATCH of pending pods is solved in one
compiled XLA program, bit-identical to walking the pods in the order the
reference's queue would pop them (priority desc, then enqueue time asc —
internal/queue/scheduling_queue.go activeQ comparator): chunks of pods
choose nodes vectorized, per-node in-order prefix sums accept everything
up to the first misfit, and the rest repair against updated residuals.
One device dispatch replaces B scheduling cycles.

Intra-batch semantics contract:
* Resources and pod counts are EXACT within the batch (the carry).
* Topology masks/scores (spread, inter-pod affinity) are computed against
  the pre-batch snapshot; pods earlier in the batch do not update them for
  later pods. Pods carrying topology constraints (or matched by existing
  anti-affinity terms) should be committed through the host-side oracle
  re-check (scheduler/driver.py) — the same optimistic-assume + re-queue
  discipline the reference applies across its async bind boundary
  (scheduler.go:631-673, MakeDefaultErrorFunc re-queue on conflict).
* selectHost tie-break: uniform among max-score nodes via the PRNG key
  (core/generic_scheduler.go:278 reservoir sampling).

Gang/all-or-nothing (absent upstream, natural here): pods may carry a group
id; a second scan pass drops groups that did not fully fit.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Optional

import jax
import jax.numpy as jnp

Arrays = Dict[str, jnp.ndarray]

# chunk width of the prefix-acceptance commit loop — shared with the
# sharded twin (parallel/sharded.py) so the two stay in lockstep.
# 128 measured best on TPU at [1024, 10240]: the solve's cost is serial
# scan steps (B/K of them), not FLOPs — K=128 halves the steps vs 64 and
# the repair loop still converges in ~1-2 iterations/chunk
# (scripts/microbench_solver_ab.py; sequential equivalence holds for any K)
DEFAULT_CHUNK = 128


def pop_order(priority: jnp.ndarray, enqueue_seq: jnp.ndarray, valid: jnp.ndarray) -> jnp.ndarray:
    """Queue pop order: priority desc, then enqueue sequence asc (activeQ
    comparator podsCompareBackoffCompleted / higher-priority-first); invalid
    rows last. Returns the permutation [B]."""
    return jnp.lexsort((enqueue_seq, -priority.astype(jnp.int64), ~valid))


def tie_noise(rng_key, b: int, n: int) -> jnp.ndarray:
    """selectHost tie-break noise [b, n] — the ONE noise stream shared by
    the single-chip solver, the sharded twin, and the host-side parity
    walks, so their tie-breaks are identical by construction.

    Counter-based bitmix (murmur3 fmix32 over (pod, node, key) lanes), not
    threefry: the reference's contract is only "uniform among max-score
    nodes" (reservoir sampling, core/generic_scheduler.go:278), which any
    well-mixed keyed hash satisfies. The previous per-pod
    split+vmap(uniform) lowered to B separate threefry programs — ~1.5s a
    batch at [1024, 10240] on TPU vs ~0 for the elementwise mix. A shard
    holding node columns [lo, hi) reproduces exactly its slice from the
    global column index, so sharded solves need no noise transfer."""
    kd = (
        jax.random.key_data(rng_key)
        if jnp.issubdtype(rng_key.dtype, jax.dtypes.prng_key)
        else jnp.asarray(rng_key)
    )
    kd = kd.astype(jnp.uint32).reshape(-1)
    # both key words enter BEFORE the avalanche (multiplied by odd
    # constants so low-bit-only keys like PRNGKey(small) spread over all
    # lanes), then a full fmix32 — every output bit depends on every input
    seed = kd[0] * jnp.uint32(0x27220A95) ^ kd[-1] * jnp.uint32(0x01000193)
    i = jnp.arange(b, dtype=jnp.uint32)[:, None]
    j = jnp.arange(n, dtype=jnp.uint32)[None, :]
    x = i * jnp.uint32(0x9E3779B1) + j * jnp.uint32(0x85EBCA77) + seed
    x = x ^ (x >> jnp.uint32(16))
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> jnp.uint32(13))
    x = x * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> jnp.uint32(16))
    # top 24 bits → [0, 1) exactly representable in f32
    return (x >> jnp.uint32(8)).astype(jnp.float32) * jnp.float32(1.0 / (1 << 24))


@partial(jax.jit, static_argnames=("deterministic", "chunk", "return_carry"))
def solve_greedy(
    mask: jnp.ndarray,  # [U, N] feasibility from filter kernels (spec rows)
    score: jnp.ndarray,  # [U, N] weighted priority sums
    req: jnp.ndarray,  # [U, R] pod requests (GetResourceRequest)
    free0: jnp.ndarray,  # [N, R] alloc - requested at batch start
    count0: jnp.ndarray,  # [N] pod counts at batch start
    allowed: jnp.ndarray,  # [N] allowed pod numbers
    order: jnp.ndarray,  # [B] scan order (pop_order)
    rng_key,  # PRNG key for tie-breaks
    deterministic: bool = False,
    req_any: Optional[jnp.ndarray] = None,  # [U] pod requests anything at all
    sig: Optional[jnp.ndarray] = None,  # [B] pod → spec row (None: identity)
    pod_valid: Optional[jnp.ndarray] = None,  # [B] (None: all valid)
    chunk: int = DEFAULT_CHUNK,
    return_carry: bool = False,
    nz0: Optional[jnp.ndarray] = None,  # [N, 2] non-zero scoring accumulators
    scoring_req: Optional[jnp.ndarray] = None,  # [U, 2] per-spec scoring request
    inb: Optional[Dict[str, jnp.ndarray]] = None,  # in-batch anti/port tracking
):
    """Greedy-by-priority batch assignment → node row per pod, -1 = no fit.

    BIT-IDENTICAL to scheduling the pods one at a time in `order` (the
    reference's scheduleOne sequence): each pod picks the max-score node
    feasible against the residuals left by every earlier pod, with the
    selectHost noise tie-break. But instead of a B-step sequential scan
    (whose per-step overhead dominates at B=1024), pods are processed in
    CHUNKS: every undecided pod in the chunk computes its choice in one
    vectorized [K, N] pass, then per-node in-order prefix sums accept all
    pods up to the first one whose choice no longer fits, and the rest
    retry against updated residuals (a lax.while_loop, ≥1 pod decided per
    iteration). Sequential equivalence: an accepted pod's chosen node
    survives every earlier commit, and the (score, noise) argmax over a
    subset that retains the superset's maximum is that same maximum — so
    each accepted choice equals the choice the sequential scan would have
    made. A pod with no feasible node stays infeasible forever (residuals
    only shrink), so -1 can be finalized immediately.

    The mask/score/req rows are per unique pod SPEC (replica sets collapse
    to one row each; state/tensors dedup); `sig` maps each batch position to
    its spec row. With sig=None the mapping is the identity (one row per
    pod) — the pre-dedup behavior, kept for tests and small callers.

    `inb` (optional) turns on IN-BATCH sequentialization of required
    anti-affinity and host-port conflicts on device: the solver carries
    per-(term, topology-value) commit counts (both directions — my term vs
    committed matchers, committed owners vs my labels) plus a per-(spec,
    node) commit table for port conflicts, masking later pods exactly the
    way the sequential walk would (predicates.go:1284
    satisfiesExistingPodsAntiAffinity applied within the batch). Without it
    those conflicts are the host commit loop's LIGHT-recheck business.
    Keys: anti [TT]b, owner [TT]i32, m_bb [TT,U]b (term matches spec
    labels+ns), bucket_n [TT,N]i32, haskey_n [TT,N]b, port_conflict [U,U]b,
    ca0/cb0 [TT,V]f32, cs0 [U,N]f32.

    Sequential equivalence with tracking: commits stay a strict prefix of
    the undecided order, truncated at the first pod whose anti/port mask an
    EARLIER in-round candidate commit could actually change (same term at
    the same topology bucket, or a port conflict on the same node) — the
    pairwise barrier in the body; every committed pod's mask reflects
    exactly the commits sequentially before it."""
    U, N = mask.shape
    if req_any is None:
        req_any = jnp.any(req > 0, axis=-1)
    B = order.shape[0]
    if sig is None:
        sig = jnp.arange(B, dtype=jnp.int32)
    if pod_valid is None:
        pod_valid = jnp.ones((B,), bool)
    K = min(chunk, B)
    if B % K:
        K = B  # non-bucketed caller: one chunk covers everything
    n_chunks = B // K
    if deterministic:
        noise = jnp.zeros((n_chunks, K, 1))  # unused; keeps the scan xs structure
    else:
        noise = jnp.reshape(tie_noise(rng_key, B, N), (n_chunks, K, N))
    neg = jnp.iinfo(score.dtype).min
    jrange = jnp.arange(K)
    # non-zero scoring accumulators ride the carry only when the caller
    # wants the post-batch residual state back (speculative pipelining)
    if nz0 is None:
        nz0 = jnp.zeros((N, 2), free0.dtype)
    if scoring_req is None:
        scoring_req = jnp.zeros((U, 2), free0.dtype)
    track = inb is not None
    if track:
        t_anti = inb["anti"]  # [TT] bool: valid required-anti term rows
        t_owner = inb["owner"]  # [TT] int32 spec row owning the term
        m_bb = inb["m_bb"] & t_anti[:, None]  # [TT, U]
        bucket_n = inb["bucket_n"]  # [TT, N] topo value per node (term's key)
        haskey_n = inb["haskey_n"]  # [TT, N] node carries the topo key
        pconf = inb["port_conflict"]  # [U, U]
        ca0, cb0, cs0 = inb["ca0"], inb["cb0"], inb["cs0"]
        TT = t_anti.shape[0]
        t_rows = jnp.arange(TT, dtype=jnp.int32)[:, None]
        Vb = ca0.shape[1]

    def chunk_step(carry, inp):
        free, count, nzacc, ca, cb, cs = carry
        idx, nz = inp  # [K] pod positions in order; [K, N] noise rows
        sg = sig[idx]
        pv = pod_valid[idx]
        m_r = mask[sg] & pv[:, None]  # [K, N]
        s_r = score[sg]
        r_q = req[sg]  # [K, R]
        r_any = req_any[sg]  # [K]
        s_q = scoring_req[sg]  # [K, 2]
        if track:
            ownK = (t_owner[None, :] == sg[:, None]) & t_anti[None, :]  # [K, TT]
            mbbK = m_bb[:, sg].T  # [K, TT]
            pconfK = pconf[sg].astype(jnp.float32)  # [K, U]

        def not_done(st):
            return ~jnp.all(st[6])

        def body(st):
            free, count, nzacc, ca, cb, cs, decided, choice = st
            # PodFitsResources (predicates.go:854): the pod-count check
            # always applies; the resource rows only when the pod requests
            # anything, so empty-request pods pass even on overcommitted
            # (free < 0) nodes.
            res_ok = (~r_any[:, None]) | jnp.all(
                r_q[:, None, :] <= free[None, :, :], axis=-1
            )  # [K, N]
            feas = m_r & res_ok & (count[None, :] + 1 <= allowed[None, :])
            if track:
                # in-batch anti/port exclusion from commits so far (exact:
                # the commit barrier below guarantees these counts cover
                # every sequentially-earlier sensitive commit)
                hp = jax.lax.Precision.HIGHEST
                ca_pos = ((jnp.take_along_axis(ca, bucket_n, axis=1) > 0) & haskey_n)
                cb_pos = ((jnp.take_along_axis(cb, bucket_n, axis=1) > 0) & haskey_n)
                blockA = jnp.matmul(
                    ownK.astype(jnp.float32), ca_pos.astype(jnp.float32), precision=hp
                ) > 0.5
                blockB = jnp.matmul(
                    mbbK.astype(jnp.float32), cb_pos.astype(jnp.float32), precision=hp
                ) > 0.5
                blockP = jnp.matmul(
                    pconfK, (cs > 0).astype(jnp.float32), precision=hp
                ) > 0.5
                feas = feas & ~(blockA | blockB | blockP)
            feas = feas & ~decided[:, None]
            anyf = jnp.any(feas, axis=1)
            masked = jnp.where(feas, s_r, neg)
            if deterministic:
                cand = jnp.argmax(masked, axis=1)
            else:
                # selectHost: uniform among max-score nodes — max noise wins
                best = jnp.max(masked, axis=1, keepdims=True)
                ties = feas & (masked == best)
                cand = jnp.argmax(jnp.where(ties, nz, -1.0), axis=1)
            cand = jnp.where(anyf, cand.astype(jnp.int32), -1)
            newly_none = ~decided & ~anyf
            active = ~decided & (cand >= 0)
            # per-node in-order prefix: what earlier active chunk pods would
            # consume on this pod's chosen node
            same = (
                active[:, None]
                & active[None, :]
                & (cand[:, None] == cand[None, :])
                & (jrange[None, :] < jrange[:, None])
            )  # [K, K] same-node strictly-earlier
            # broadcast-sum, not matmul: an s64 dot has no TPU x64 rewrite
            prefix_req = jnp.sum(
                same[:, :, None] * r_q[None, :, :], axis=1
            )  # [K, R]
            prefix_cnt = jnp.sum(same, axis=1)  # [K]
            cidx = jnp.where(cand >= 0, cand, 0)
            fits = (
                (~r_any) | jnp.all(r_q <= free[cidx] - prefix_req, axis=-1)
            ) & (count[cidx] + prefix_cnt + 1 <= allowed[cidx])
            rejected = active & ~fits
            first_rej = jnp.min(jnp.where(rejected, jrange, K))
            commit = active & (jrange < first_rej)
            if track:
                # commit barrier, PAIRWISE-EXACT via scatter-min: pod j must
                # not commit this round if an earlier candidate commit i
                # could change j's anti/port mask — i contributes to a ca/cb
                # row j reads AT THE SAME topology bucket, or i's commit
                # port-conflicts j's spec on j's chosen node. Everything
                # before the first such j commits together (an all-sensitive
                # batch of same-spec anti pods landing in DISTINCT buckets
                # commits as one round — the old first-sensitive-pod barrier
                # made that one pod per round, B serial iterations on the
                # quadratic config). Computed as min-candidate-index tables
                # per (term, bucket) and (spec, node) — the same shapes as
                # the ca/cb/cs updates, not a [TT, K, K] pairwise tensor.
                # The first active pod is never blocked → progress holds; a
                # committed pod's mask saw every sequentially-earlier commit.
                cand_ok = active & (jrange < first_rej)
                cidx3 = jnp.where(cand_ok, cand, 0)
                bK = bucket_n[:, cidx3]  # [TT, K] bucket of each choice
                hkK = haskey_n[:, cidx3] & cand_ok[None, :]
                contrib = m_bb[:, sg] & hkK  # i bumps ca[t, bK[t, i]]
                ownk_t = ownK.T & hkK  # j reads ca[t, bK[t, j]]
                idxK = jnp.broadcast_to(jrange[None, :], bK.shape).astype(jnp.int32)
                mi_contrib = jnp.full((TT, Vb), K, jnp.int32).at[
                    t_rows, jnp.where(contrib, bK, Vb)
                ].min(idxK, mode="drop")
                mi_own = jnp.full((TT, Vb), K, jnp.int32).at[
                    t_rows, jnp.where(ownk_t, bK, Vb)
                ].min(idxK, mode="drop")
                g_contrib = jnp.take_along_axis(
                    mi_contrib, jnp.where(hkK, bK, 0), axis=1
                )  # [TT, K] earliest same-bucket contributor
                g_own = jnp.take_along_axis(mi_own, jnp.where(hkK, bK, 0), axis=1)
                blockA_j = jnp.any(ownk_t & (g_contrib < jrange[None, :]), axis=0)
                blockB_j = jnp.any(contrib & (g_own < jrange[None, :]), axis=0)
                # ports: earliest candidate per (spec, node); j blocked when
                # a port-conflicting spec has an earlier candidate on j's
                # chosen node
                mi_sn = jnp.full((U, N), K, jnp.int32).at[
                    jnp.where(cand_ok, sg, U), cidx3
                ].min(jnp.where(cand_ok, jrange, K).astype(jnp.int32), mode="drop")
                g_sn = mi_sn[:, cidx3]  # [U, K] per spec, at j's node
                blockP_j = jnp.any(
                    (pconfK.T > 0.5)[:, :] & (g_sn < jrange[None, :]), axis=0
                )
                blocked = cand_ok & (blockA_j | blockB_j | blockP_j)
                first_block = jnp.min(jnp.where(blocked, jrange, K))
                commit = commit & (jrange < first_block)
            # apply commits (duplicate indices accumulate; index N drops)
            target = jnp.where(commit, cand, N)
            free = free.at[target].add(
                -(commit[:, None] * r_q), mode="drop"
            )
            count = count.at[target].add(
                commit.astype(count.dtype), mode="drop"
            )
            nzacc = nzacc.at[target].add(commit[:, None] * s_q, mode="drop")
            if track:
                # record the commits into the in-batch anti/port state
                cidx2 = jnp.where(commit, cand, 0)
                bcand = bucket_n[:, cidx2]  # [TT, K] topo value of each commit
                hk = haskey_n[:, cidx2] & commit[None, :]
                one = jnp.float32(1.0)
                ca = ca.at[
                    t_rows, jnp.where(m_bb[:, sg] & hk, bcand, Vb)
                ].add(one, mode="drop")
                cb = cb.at[
                    t_rows, jnp.where(ownK.T & hk, bcand, Vb)
                ].add(one, mode="drop")
                cs = cs.at[
                    jnp.where(commit, sg, U), jnp.where(commit, cand, 0)
                ].add(one, mode="drop")
            choice = jnp.where(commit, cand, choice)
            decided = decided | commit | newly_none
            return free, count, nzacc, ca, cb, cs, decided, choice

        decided0 = ~pv  # padding/invalid pods are decided at -1
        choice0 = jnp.full((K,), -1, jnp.int32)
        free, count, nzacc, ca, cb, cs, _, choice = jax.lax.while_loop(
            not_done, body, (free, count, nzacc, ca, cb, cs, decided0, choice0)
        )
        return (free, count, nzacc, ca, cb, cs), choice

    if track:
        carry0 = (free0, count0, nz0, ca0, cb0, cs0)
    else:
        _z = jnp.zeros((1, 1), jnp.float32)
        carry0 = (free0, count0, nz0, _z, _z, _z)
    order_c = jnp.reshape(order, (n_chunks, K))
    (free_f, count_f, nz_f, _, _, _), choices = jax.lax.scan(
        chunk_step, carry0, (order_c, noise)
    )
    # scatter back to original pod positions
    out = jnp.full((B,), -1, jnp.int32)
    out = out.at[order].set(jnp.reshape(choices, (B,)))
    if return_carry:
        return out, (free_f, count_f, nz_f)
    return out


@partial(jax.jit, static_argnames=("deterministic", "return_carry"))
def solve_gang(
    mask: jnp.ndarray,
    score: jnp.ndarray,
    req: jnp.ndarray,
    free0: jnp.ndarray,
    count0: jnp.ndarray,
    allowed: jnp.ndarray,
    order: jnp.ndarray,
    group: jnp.ndarray,  # [B] group id, -1 = ungrouped
    rng_key,
    deterministic: bool = False,
    req_any: Optional[jnp.ndarray] = None,
    sig: Optional[jnp.ndarray] = None,
    pod_valid: Optional[jnp.ndarray] = None,
    return_carry: bool = False,
    nz0: Optional[jnp.ndarray] = None,
    scoring_req: Optional[jnp.ndarray] = None,
):
    """All-or-nothing gang assignment: two-pass greedy. Pass 1 places
    everything; groups with any unplaced member are dropped and pass 2
    re-solves without them (their capacity is released for other pods).
    Returns (assignment [B], gang_ok [B]) — plus pass 2's residual carry
    with return_carry, which reflects exactly the surviving members'
    consumption, so the NEXT batch's speculative solve can chain on a
    gang batch like on any other."""
    B = order.shape[0]
    k1, k2 = jax.random.split(rng_key)
    first = solve_greedy(mask, score, req, free0, count0, allowed, order, k1,
                         deterministic=deterministic, req_any=req_any,
                         sig=sig, pod_valid=pod_valid)
    grouped = group >= 0
    failed_member = grouped & (first < 0)
    # group failed if ANY member failed (segment max over group ids)
    ngroups = B  # group ids are < B by construction
    fail_by_group = jnp.zeros(ngroups, bool).at[jnp.where(grouped, group, 0)].max(failed_member)
    dropped = grouped & fail_by_group[jnp.where(grouped, group, 0)]
    # drop members by invalidating their batch position (dropped is per pod,
    # so it cannot mask the shared spec rows)
    alive = (
        ~dropped if pod_valid is None else (pod_valid & ~dropped)
    )
    result = solve_greedy(mask, score, req, free0, count0, allowed, order, k2,
                          deterministic=deterministic, req_any=req_any,
                          sig=sig, pod_valid=alive,
                          return_carry=return_carry, nz0=nz0,
                          scoring_req=scoring_req)
    gang_ok = ~dropped
    if return_carry:
        second, carry = result
        return jnp.where(dropped, -1, second), gang_ok, carry
    return jnp.where(dropped, -1, result), gang_ok
