"""Topology kernels: EvenPodsSpread, InterPodAffinity, SelectorSpread.

These are the reference's quadratic (pod x pod) plugins — its known
bottleneck (predicates.go:1269/:1778, interpod_affinity.go, metadata.go
topology-pair maps). The TPU formulation:

* Terms are SPARSE rows (state/terms.py). Matching a term against all
  existing pods / the incoming batch is one broadcasted compare.
* Per-topology-value aggregation uses segment_sum/segment_max keyed by the
  DENSE value index (NodeBank.label_dense), vmapped over the term axis.
* The symmetric direction (existing pods' terms vs incoming pods) becomes a
  [B, ET] @ [ET, N] matmul over term-match and same-topology incidence
  matrices — this is what the MXU is for.
* Per-owner combining (a pod's terms AND/OR/sum together) uses scatter
  (.at[owner].min/max/add), which XLA turns into on-chip scatters.

Semantics parity-tested bit-for-bit against the oracle in
tests/test_topology_parity.py.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

from ..state.terms import (
    AFF_PREF,
    AFF_REQ,
    ANTI_PREF,
    ANTI_REQ,
    SEL_SPREAD,
    SPREAD_HARD,
    SPREAD_SOFT,
)
from ..state.tensors import OP_DOES_NOT_EXIST, OP_EXISTS, OP_IN, OP_NEVER, OP_NOT_IN

Arrays = Dict[str, jnp.ndarray]

MAX_NODE_SCORE = 10
_BIG = 2**30  # plain int: no device array creation at import time


# ---------------------------------------------------------------------------
# term matching
# ---------------------------------------------------------------------------

def match_terms(terms: Arrays, labels: jnp.ndarray, ns: jnp.ndarray = None) -> jnp.ndarray:
    """[TT, X]: does term t's (namespace-set, label-selector) match subject x?

    labels: [X, K] value-id rows; ns: [X] namespace ids or None to skip the
    namespace check. Selector semantics = metav1.LabelSelectorAsSelector
    (nil matches nothing; empty matches everything; matchLabels AND
    matchExpressions)."""
    K = labels.shape[1]
    # matchLabels pairs
    ml_slot = jnp.clip(terms["ml_slot"], 0, K - 1)  # [TT, ML]
    vals_at = labels.T[ml_slot]  # [TT, ML, X]
    ml_ok = (terms["ml_slot"][..., None] < 0) | (vals_at == terms["ml_val"][..., None])
    sel_ok = jnp.all(ml_ok, axis=1)  # [TT, X]
    # matchExpressions
    ex_slot = jnp.clip(terms["ex_slot"], 0, K - 1)
    ex_vals_at = labels.T[ex_slot]  # [TT, EX, X]
    present = ex_vals_at != 0
    in_set = jnp.any(ex_vals_at[..., None, :] == terms["ex_vals"][..., :, None], axis=-2)
    op = terms["ex_op"][..., None]
    ex_ok = jnp.ones_like(present)
    ex_ok = jnp.where(op == OP_IN, present & in_set, ex_ok)
    ex_ok = jnp.where(op == OP_NOT_IN, ~present | ~in_set, ex_ok)
    ex_ok = jnp.where(op == OP_EXISTS, present, ex_ok)
    ex_ok = jnp.where(op == OP_DOES_NOT_EXIST, ~present, ex_ok)
    ex_ok = jnp.where(op == OP_NEVER, jnp.zeros_like(present), ex_ok)
    sel_ok = sel_ok & jnp.all(ex_ok, axis=1)
    sel_ok = sel_ok & terms["has_selector"][:, None]
    if ns is not None:
        ns_in = jnp.any(ns[None, None, :] == terms["ns_ids"][..., None], axis=1)  # [TT, X]
        sel_ok = sel_ok & (terms["ns_any"][:, None] | ns_in)
    return sel_ok & terms["valid"][:, None]


def _bucket_of(nodes: Arrays, slot: jnp.ndarray, idx: jnp.ndarray = None):
    """Dense topology bucket at per-term key slots. slot: [TT]; idx: [X] node
    rows shared by all terms (or None = all nodes).
    Returns (bucket [TT, X] clipped ≥0, has_key [TT, X])."""
    dense = nodes["label_dense"]  # [N, K]
    if idx is not None:
        dense = dense[idx]  # [X, K]
    slot_c = jnp.clip(slot, 0, dense.shape[1] - 1)
    b = dense.T[slot_c]  # [TT, X]
    has = (b >= 0) & (slot[:, None] >= 0)
    return jnp.maximum(b, 0), has


def _seg_sum(values: jnp.ndarray, buckets: jnp.ndarray, num: int) -> jnp.ndarray:
    """vmapped segment_sum over the leading term axis.

    For a SMALL static segment count (the n_buckets bound: zone-keyed
    topologies have ~8 distinct values) a scatter would serialize on the
    massive index collisions — a one-hot batched matmul keeps it on the
    MXU instead. Exact: the summed counts stay far below 2^24. The one-hot
    operand materializes [TT, X, V] f32 (XLA cannot fuse it away), so the
    path is also gated on that transient staying under ~256 MB."""
    if num <= 64 and values.shape[0] * values.shape[1] * num * 4 <= (1 << 28):
        onehot = jax.nn.one_hot(buckets, num, dtype=jnp.float32)  # [TT, X, V]
        return jnp.einsum(
            "tx,txv->tv",
            values.astype(jnp.float32),
            onehot,
            precision=jax.lax.Precision.HIGHEST,
        ).astype(values.dtype if values.dtype != jnp.bool_ else jnp.int32)
    return jax.vmap(lambda v, s: jax.ops.segment_sum(v, s, num_segments=num))(values, buckets)


def _sig_cnt_node(m_sig: jnp.ndarray, counts: jnp.ndarray) -> jnp.ndarray:
    """Per-node match counts from signature matches: [T, S] boolean matches
    × [N, S] per-node signature counts → [T, N] int32, as ONE f32 MXU
    matmul (exact: counts and their sums stay far below 2^24). This is the
    step that replaced per-existing-pod gathers/segment-sums — matching
    runs against S signature rows, never against individual pods.
    Precision HIGHEST is REQUIRED: the TPU default truncates f32 matmul
    operands to bf16, which misrounds any count above 256."""
    return jnp.matmul(
        m_sig.astype(jnp.float32),
        counts.astype(jnp.float32).T,
        precision=jax.lax.Precision.HIGHEST,
    ).astype(jnp.int32)


def _gather_rows(table: jnp.ndarray, buckets: jnp.ndarray) -> jnp.ndarray:
    """table: [TT, V]; buckets: [TT, X] → [TT, X] (per-row gather)."""
    return jax.vmap(lambda t, b: t[b])(table, buckets)


def _merge_same_key(terms: Arrays, mask: jnp.ndarray, table: jnp.ndarray) -> jnp.ndarray:
    """Sum rows of `table` over terms sharing (owner, topo_slot) — replicates
    the reference's per-(key,value) pair maps being shared across constraints
    with the same topology key (metadata.go tpPairToMatchNum).

    Computed as an f32 HIGHEST matmul, not an integer one: XLA lowers
    integer matmuls to scalar loops (~100x slower than the MXU), and the
    summed match counts stay far below 2^24 so f32 accumulation is exact."""
    same = (
        mask[:, None]
        & mask[None, :]
        & (terms["owner"][:, None] == terms["owner"][None, :])
        & (terms["topo_slot"][:, None] == terms["topo_slot"][None, :])
    )
    return jnp.matmul(
        same.astype(jnp.float32),
        table.astype(jnp.float32),
        precision=jax.lax.Precision.HIGHEST,
    ).astype(table.dtype)


def _scatter_and(ok_t: jnp.ndarray, owner: jnp.ndarray, mask_t: jnp.ndarray, B: int) -> jnp.ndarray:
    """AND of ok_t rows per owner → [B, N] (terms not in mask contribute 1)."""
    contrib = jnp.where(mask_t[:, None], ok_t, True).astype(jnp.int32)
    out = jnp.ones((B, ok_t.shape[1]), jnp.int32)
    out = out.at[jnp.where(mask_t, owner, B)].min(contrib, mode="drop")
    return out.astype(bool)


def _scatter_or(bad_t: jnp.ndarray, owner: jnp.ndarray, mask_t: jnp.ndarray, B: int) -> jnp.ndarray:
    contrib = jnp.where(mask_t[:, None], bad_t, False).astype(jnp.int32)
    out = jnp.zeros((B, bad_t.shape[1]), jnp.int32)
    out = out.at[jnp.where(mask_t, owner, B)].max(contrib, mode="drop")
    return out.astype(bool)


def _scatter_add(val_t: jnp.ndarray, owner: jnp.ndarray, mask_t: jnp.ndarray, B: int) -> jnp.ndarray:
    contrib = jnp.where(mask_t[:, None], val_t, 0)
    out = jnp.zeros((B, val_t.shape[1]), val_t.dtype)
    out = out.at[jnp.where(mask_t, owner, B)].add(contrib, mode="drop")
    return out


# ---------------------------------------------------------------------------
# EvenPodsSpread
# ---------------------------------------------------------------------------

def spread_filter(
    nodes: Arrays, eps: Arrays, terms: Arrays, selector_mask: jnp.ndarray,
    n_buckets: int = None,
) -> jnp.ndarray:
    """EvenPodsSpreadPredicate (predicates.go:1778) with metadata computed on
    device (metadata.go:399 getEvenPodsSpreadMetadata). selector_mask is the
    PodMatchNodeSelector matrix [B, N] (candidate nodes must pass the
    incoming pod's node selector/affinity)."""
    B, N = selector_mask.shape
    V = n_buckets or N  # distinct topology values bound (jit static)
    hard = terms["valid"] & (terms["kind"] == SPREAD_HARD)
    owner = terms["owner"]

    bucket_n, haskey_n = _bucket_of(nodes, terms["topo_slot"])  # [TT, N]
    # candidate nodes per pod: selector ∧ ALL hard topo keys present ∧ valid
    all_keys = _scatter_and(haskey_n, owner, hard, B)
    cand = selector_mask & all_keys & nodes["valid"][None, :]

    # existing-pod match per term (same namespace as the incoming pod —
    # ns_ids were compiled to [pod.namespace] for hard constraints),
    # evaluated against label SIGNATURES then expanded to per-node counts
    m_sig = match_terms(terms, eps["label_vals"], eps["ns_id"]) & eps["valid"][None, :] & hard[:, None]
    cnt_node = _sig_cnt_node(m_sig, eps["counts"])  # [TT, N]
    cand_t = cand[owner]  # [TT, N]
    pair_cnt = _seg_sum(jnp.where(cand_t, cnt_node, 0), bucket_n, V)  # [TT, V]
    pair_present = _seg_sum((cand_t & haskey_n).astype(jnp.int32), bucket_n, V) > 0

    merged_cnt = _merge_same_key(terms, hard, pair_cnt)
    merged_present = _merge_same_key(terms, hard, pair_present.astype(jnp.int32)) > 0

    min_match = jnp.min(jnp.where(merged_present, merged_cnt, jnp.asarray(_BIG, merged_cnt.dtype)), axis=1)  # [TT]
    match_num_n = jnp.where(
        _gather_rows(merged_present, bucket_n), _gather_rows(merged_cnt, bucket_n), 0
    )  # [TT, N]
    self_m = terms["self_match"].astype(jnp.int32)[:, None]
    skew_ok = match_num_n + self_m - min_match[:, None] <= terms["weight"][:, None]
    ok_t = haskey_n & skew_ok
    ok = _scatter_and(ok_t, owner, hard, B)

    # empty pair map → predicate passes (predicates.go:1800)
    any_pair_t = jnp.any(merged_present, axis=1)  # [TT]
    any_pair = jnp.zeros(B + 1, bool).at[jnp.where(hard, owner, B)].max(any_pair_t & hard)[:B]
    return ok | ~any_pair[:, None]


def spread_score(
    nodes: Arrays, eps: Arrays, terms: Arrays, aux: Arrays, selector_mask: jnp.ndarray,
    n_buckets: int = None,
) -> jnp.ndarray:
    """CalculateEvenPodsSpreadPriority (even_pods_spread.go:85): member nodes
    carry all soft topo keys; counts accumulate over nodes ALSO passing the
    pod's node selector; score = 10*(total-count)/(total-min); counts span
    all namespaces (reference quirk)."""
    B, N = selector_mask.shape
    V = n_buckets or N
    soft = terms["valid"] & (terms["kind"] == SPREAD_SOFT)
    owner = terms["owner"]
    has_soft = jnp.zeros(B + 1, bool).at[jnp.where(soft, owner, B)].max(soft)[:B]

    bucket_n, haskey_n = _bucket_of(nodes, terms["topo_slot"])
    member = _scatter_and(haskey_n, owner, soft, B) & nodes["valid"][None, :]  # [B, N]
    counting = member & selector_mask

    m_sig = match_terms(terms, eps["label_vals"], None) & eps["valid"][None, :] & soft[:, None]
    cnt_node = _sig_cnt_node(m_sig, eps["counts"])
    counting_t = counting[owner]
    member_t = member[owner]
    pair_cnt = _seg_sum(jnp.where(counting_t, cnt_node, 0), bucket_n, V)
    pair_present = _seg_sum((member_t & haskey_n).astype(jnp.int32), bucket_n, V) > 0

    merged_cnt = _merge_same_key(terms, soft, pair_cnt)
    merged_present = _merge_same_key(terms, soft, pair_present.astype(jnp.int32)) > 0

    # per-node count: Σ over the pod's soft terms of its pair count (only
    # where the pair was initialized by a member node)
    node_cnt_t = jnp.where(
        haskey_n & _gather_rows(merged_present, bucket_n), _gather_rows(merged_cnt, bucket_n), 0
    )
    node_cnt = _scatter_add(node_cnt_t, owner, soft, B)  # [B, N]

    total = jnp.sum(jnp.where(member, node_cnt, 0), axis=1)  # [B]
    min_cnt = jnp.min(jnp.where(member, node_cnt, jnp.asarray(_BIG, node_cnt.dtype)), axis=1)
    has_member = jnp.any(member, axis=1)
    min_cnt = jnp.where(has_member, min_cnt, 0)
    diff = total - min_cnt
    # int(f64(10*(total-cnt))/diff) == exact integer division here: all values
    # are non-negative ints < 2^35, exactly representable in float64
    f = jnp.where(
        diff[:, None] > 0,
        MAX_NODE_SCORE * (total[:, None] - node_cnt) // jnp.maximum(diff, 1)[:, None],
        MAX_NODE_SCORE,
    )
    return jnp.where(member & has_soft[:, None], f, 0)


# ---------------------------------------------------------------------------
# InterPodAffinity
# ---------------------------------------------------------------------------

def interpod_filter(
    nodes: Arrays,
    eps: Arrays,
    terms: Arrays,
    aux: Arrays,
    ex_terms: Arrays,
    pods: Arrays,
    parts: tuple = ("existing", "aff", "anti"),
    n_buckets: int = None,
) -> jnp.ndarray:
    """InterPodAffinityMatches (predicates.go:1269), metadata path:
      1. existing pods' required anti-affinity blocks same-topology nodes
      2. incoming required affinity: node must match topology of ALL terms
         (with the first-pod-in-series escape)
      3. incoming required anti-affinity: node matching ANY term fails.

    `parts` is a jit-static subset — the driver drops the parts whose term
    kinds are provably absent this batch (a skipped part contributes its
    term-absent identity, so dropping == computing on empty terms)."""
    B = pods["valid"].shape[0]
    N = nodes["valid"].shape[0]
    V = n_buckets or N
    result = jnp.ones((B, N), bool)

    if "existing" in parts:
        # --- 1. existing-pods anti-affinity (ex_terms = PATTERN bank with
        # per-node instance counts; state/terms.PatternBank) ---
        ex_anti = ex_terms["valid"] & (ex_terms["kind"] == ANTI_REQ)
        m_pt = match_terms(ex_terms, pods["label_vals"], pods["ns_id"]) & ex_anti[:, None]  # [PT, B]
        bucket_n, haskey_n = _bucket_of(nodes, ex_terms["topo_slot"])  # [PT, N]
        # buckets hosting ≥1 instance of the pattern (hosting node must
        # carry the topology key, like the old owner_has)
        hosted = jnp.where(haskey_n, ex_terms["counts"].T.astype(jnp.int32), 0)  # [PT, N]
        present = _seg_sum(hosted, bucket_n, V) > 0  # [PT, V]
        block_t = haskey_n & _gather_rows(present, bucket_n)  # [PT, N]
        fail_existing = (
            jnp.matmul(
                m_pt.astype(jnp.float32).T,
                block_t.astype(jnp.float32),
                precision=jax.lax.Precision.HIGHEST,
            )
            > 0.5
        )  # [B, N]
        result = result & ~fail_existing

    if "aff" in parts or "anti" in parts:
        # --- 2./3. incoming terms ------------------------------------------
        aff = terms["valid"] & (terms["kind"] == AFF_REQ)
        anti = terms["valid"] & (terms["kind"] == ANTI_REQ)
        owner = terms["owner"]
        # per-term property match of existing-pod SIGNATURES
        m_sig = match_terms(terms, eps["label_vals"], eps["ns_id"]) & eps["valid"][None, :]  # [TT, S]
        bucket_n2, haskey_n2 = _bucket_of(nodes, terms["topo_slot"])  # [TT, N]

    if "aff" in parts:
        # affinity: existing pod must match ALL of the owner's aff terms —
        # AND across terms happens at the signature level
        matchall_sig = (
            jnp.ones((B + 1, m_sig.shape[1]), jnp.int32)
            .at[jnp.where(aff, owner, B)]
            .min(jnp.where(aff[:, None], m_sig, True).astype(jnp.int32), mode="drop")[:B]
            .astype(bool)
        )  # [B, S]
        # nodes hosting ≥1 existing pod matching ALL owner terms, per bucket
        cnt_aff_node = _sig_cnt_node(matchall_sig, eps["counts"])  # [B, N]
        contrib_aff_n = jnp.where(haskey_n2 & aff[:, None], cnt_aff_node[owner], 0)  # [TT, N]
        agg_aff = _seg_sum(contrib_aff_n, bucket_n2, V) > 0  # [TT, V]
        ok_aff_t = haskey_n2 & _gather_rows(agg_aff, bucket_n2)
        aff_ok = _scatter_and(ok_aff_t, owner, aff, B)
        any_pair = jnp.zeros(B + 1, bool).at[jnp.where(aff, owner, B)].max(jnp.any(agg_aff, axis=1) & aff)[:B]
        escape = ~any_pair & aux["self_aff_match"]
        result = result & (aff_ok | escape[:, None] | ~aux["has_aff"][:, None])

    if "anti" in parts:
        cnt_anti_node = _sig_cnt_node(m_sig & anti[:, None], eps["counts"])  # [TT, N]
        agg_anti = _seg_sum(jnp.where(haskey_n2, cnt_anti_node, 0), bucket_n2, V) > 0
        bad_anti_t = haskey_n2 & _gather_rows(agg_anti, bucket_n2)
        result = result & ~_scatter_or(bad_anti_t, owner, anti, B)

    return result


def interpod_score(
    nodes: Arrays,
    eps: Arrays,
    terms: Arrays,
    ex_terms: Arrays,
    pods: Arrays,
    parts: tuple = ("pref", "existing"),
    n_buckets: int = None,
) -> jnp.ndarray:
    """CalculateInterPodAffinityPriority (interpod_affinity.go:99): weighted
    same-topology counts from (a) the incoming pod's preferred terms matched
    against existing pods, (b) existing pods' required-affinity (x hard
    weight) and preferred terms matched against the incoming pod; min-max
    normalized to [0, 10]. `parts` drops a half whose term kinds are
    provably absent (its contribution would be identically zero)."""
    B = pods["valid"].shape[0]
    N = nodes["valid"].shape[0]
    V = n_buckets or N
    counts = jnp.zeros((B, N), jnp.int64)

    if "pref" in parts:
        # (a) incoming preferred terms vs existing-pod signatures
        pref = terms["valid"] & ((terms["kind"] == AFF_PREF) | (terms["kind"] == ANTI_PREF))
        owner = terms["owner"]
        m_sig = match_terms(terms, eps["label_vals"], eps["ns_id"]) & eps["valid"][None, :] & pref[:, None]
        bucket_n, haskey_n = _bucket_of(nodes, terms["topo_slot"])
        cnt_node = _sig_cnt_node(m_sig, eps["counts"])  # [TT, N]
        cnt = _seg_sum(jnp.where(haskey_n, cnt_node, 0), bucket_n, V)  # [TT, V]
        contrib_t = jnp.where(haskey_n, _gather_rows(cnt, bucket_n), 0) * terms["weight"][:, None]
        counts = counts + _scatter_add(contrib_t.astype(jnp.int64), owner, pref, B)  # [B, N]

    if "existing" in parts:
        # (b) existing pods' terms vs the incoming pod (pattern counts;
        # one MXU matmul). A node's contribution is the pattern's instance
        # count over its topology bucket × the term weight.
        ex_score = ex_terms["valid"] & (
            (ex_terms["kind"] == AFF_REQ) | (ex_terms["kind"] == AFF_PREF) | (ex_terms["kind"] == ANTI_PREF)
        )
        m_pt = match_terms(ex_terms, pods["label_vals"], pods["ns_id"]) & ex_score[:, None]  # [PT, B]
        bucket_ne, haskey_ne = _bucket_of(nodes, ex_terms["topo_slot"])  # [PT, N]
        hosted = jnp.where(haskey_ne, ex_terms["counts"].T.astype(jnp.int32), 0)  # [PT, N]
        cnt_v = _seg_sum(hosted, bucket_ne, V)  # [PT, V]
        at_node = jnp.where(haskey_ne, _gather_rows(cnt_v, bucket_ne), 0)  # [PT, N]
        weighted = m_pt.astype(jnp.float32) * ex_terms["weight"][:, None].astype(jnp.float32)  # [PT, B]
        # HIGHEST precision: at_node holds instance COUNTS (not 0/1) — the
        # TPU default would truncate them to bf16 and misround above 256
        counts = counts + jnp.matmul(
            weighted.T,
            at_node.astype(jnp.float32),
            precision=jax.lax.Precision.HIGHEST,
        ).astype(jnp.int64)

    valid = nodes["valid"][None, :] & pods["valid"][:, None]
    masked = jnp.where(valid, counts, 0)
    max_c = jnp.maximum(jnp.max(masked, axis=1), 0)  # [B]
    min_c = jnp.minimum(jnp.min(masked, axis=1), 0)
    diff = max_c - min_c
    # exact: non-negative int64 operands, f64 division would be exact anyway
    f = jnp.where(
        diff[:, None] > 0,
        MAX_NODE_SCORE * (counts - min_c[:, None]) // jnp.maximum(diff, 1)[:, None],
        0,
    )
    return jnp.where(valid, f, 0)


# ---------------------------------------------------------------------------
# SelectorSpread
# ---------------------------------------------------------------------------

def selector_spread_score(
    nodes: Arrays, eps: Arrays, terms: Arrays, aux: Arrays,
    n_buckets: int = None,
) -> jnp.ndarray:
    """CalculateSpreadPriorityMap/Reduce (selector_spreading.go): count
    same-namespace non-deleting pods matching ALL controller selectors;
    blend 1/3 node-level + 2/3 zone-level, fewer is better."""
    B = aux["n_sel_spread"].shape[0]
    N = nodes["valid"].shape[0]
    V = n_buckets or N
    ss = terms["valid"] & (terms["kind"] == SEL_SPREAD)
    owner = terms["owner"]
    m_sig = match_terms(terms, eps["label_vals"], eps["ns_id"])  # ns compiled = pod ns
    # AND across the pod's selectors, at the signature level
    matchall = (
        jnp.ones((B + 1, m_sig.shape[1]), jnp.int32)
        .at[jnp.where(ss, owner, B)]
        .min(jnp.where(ss[:, None], m_sig, True).astype(jnp.int32), mode="drop")[:B]
        .astype(bool)
    )
    matchall = matchall & eps["valid"][None, :] & ~eps["deleting"][None, :]
    matchall = matchall & (aux["n_sel_spread"] > 0)[:, None]
    counts = _sig_cnt_node(matchall, eps["counts"]).astype(jnp.int64)  # [B, N]
    counts = jnp.where(nodes["valid"][None, :], counts, 0)

    max_node = jnp.max(counts, axis=1)  # [B]
    zone_ok = (nodes["zone_dense"] >= 0) & nodes["valid"]
    zbucket = jnp.clip(nodes["zone_dense"], 0)
    zc_in = jnp.where(zone_ok, counts, 0)
    if V <= 64:
        # shared zone buckets → one [B, N] x [N, V] MXU matmul (exact f32)
        zoh = jax.nn.one_hot(zbucket, V, dtype=jnp.float32)
        zcounts = jnp.matmul(
            zc_in.astype(jnp.float32), zoh, precision=jax.lax.Precision.HIGHEST
        ).astype(counts.dtype)
    else:
        zcounts = jax.vmap(
            lambda c: jax.ops.segment_sum(c, zbucket, num_segments=V)
        )(zc_in)  # [B, Z]
    max_zone = jnp.max(zcounts, axis=1)
    have_zones = jnp.any(zone_ok)

    fscore = jnp.where(
        max_node[:, None] > 0,
        MAX_NODE_SCORE * (max_node[:, None] - counts).astype(jnp.float64) / jnp.maximum(max_node, 1)[:, None],
        jnp.float64(MAX_NODE_SCORE),
    )
    zscore = jnp.where(
        max_zone[:, None] > 0,
        MAX_NODE_SCORE * (max_zone[:, None] - zcounts).astype(jnp.float64) / jnp.maximum(max_zone, 1)[:, None],
        jnp.float64(MAX_NODE_SCORE),
    )
    node_z = jnp.take_along_axis(
        zscore, jnp.broadcast_to(zbucket[None, :], counts.shape), axis=1
    )
    blended = jnp.where(
        have_zones & zone_ok[None, :],
        fscore * (1.0 / 3.0) + (2.0 / 3.0) * node_z,
        fscore,
    )
    return blended.astype(jnp.int64)
