"""Vectorized victim search: the device formulation of preemption.

Reference semantics (core/generic_scheduler.go): selectNodesForPreemption
(:1007) evaluates selectVictimsOnNode (:1104) on every candidate node —
remove ALL lower-priority pods, check the preemptor fits, then reprieve
candidates most-important-first (PDB-violating pods reprieved first, :1055)
— and pickOneNodeForPreemption (:878) tie-breaks across nodes. The
reference parallelizes the node loop with 16 goroutines; here the node axis
is a vector lane: one `lax.scan` step per PREEMPTOR (sequential semantics
between preemptors — earlier victims vanish, earlier nominees charge their
node) with the per-node victim search inside as an inner scan over
importance-ordered victim slots, all nodes at once.

What the kernel models exactly (the affinity-free static case — the same
preconditions as the host fast path `preemption._select_victims_fast`):
PodFitsResources (predicates.go:854 compare rules incl. the
always-check-cpu/mem/ephemeral + scalars-when-requested split and the pod
count), candidate-node pruning by the four unresolvable predicates
(nodesWherePreemptionMightHelp :1218 — the caller passes that mask, built
from the same filter kernels the solver uses), PDB-violation counting, and
the full 6-criteria pick. Host ports and (anti-)affinity interactions are
OUTSIDE this kernel — the driver routes pods/clusters carrying those
through the scalar oracle path.

Inter-preemptor state carried on device: per-node free resources and
pod-count slack (victim removals add them back), victim aliveness, and
NOMINEE charges — the reference's victim-search fit check is
nominee-aware (selectVictimsOnNode :1160 calls podFitsOnNode with the
scheduling queue, whose pass 1 counts nominated pods, :620-630), and
without it a batch of preemptors thrashes: the first eviction's freed
capacity makes every later preemptor "fit", so nobody else evicts and the
batch converges one pod per round. Charges are tracked as one aggregated
[N, R] overlay (initial out-of-batch nominations + each chosen
preemptor's request); the reference filters nominees by priority >= the
incoming pod's — the aggregate counts ALL of them, a deliberate
conservative divergence (a per-preemptor filter would need a [P, N, R]
overlay), mirrored by the host fast path so the two stay bit-identical.

Tie-break note: criterion 6 ("first") resolves by node ROW order here; the
host oracle resolves by snapshot insertion order. These coincide on a
freshly-encoded cluster; after node churn the rows may differ — both are
conformant (the reference iterates a Go map, whose order is random).

Victim slots are pre-sorted HOST-side per node: PDB-violating pods first,
then by util.MoreImportantPod order (priority desc, start-time asc) — the
reprieve order is preemptor-independent, so one sort serves every step.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

Arrays = Dict[str, jnp.ndarray]

_BIG = jnp.int64(2**62)
_TS_MIN = jnp.int64(-(2**62))


@jax.jit
def preempt_batch(
    cand: jnp.ndarray,  # [P, N] bool — candidate nodes (unresolvable preds pass)
    p_req: jnp.ndarray,  # [P, R] int64 — preemptor GetResourceRequest
    p_req_any: jnp.ndarray,  # [P] bool — requests anything at all
    p_prio: jnp.ndarray,  # [P] int32
    p_valid: jnp.ndarray,  # [P] bool
    vict_req: jnp.ndarray,  # [N, V, R] int64 — accumulated_request per victim
    vict_prio: jnp.ndarray,  # [N, V] int32
    vict_ts: jnp.ndarray,  # [N, V] int64 — creation ts (µs) for tie-break 5
    vict_pdb: jnp.ndarray,  # [N, V] bool — PDB-violating flag
    vict_valid: jnp.ndarray,  # [N, V] bool — slot holds a disruptable pod
    free0: jnp.ndarray,  # [N, R] int64 — allocatable - requested
    count_free0: jnp.ndarray,  # [N] int32 — allowed_pods - pod_count
    node_valid: jnp.ndarray,  # [N] bool
    nom_extra0: jnp.ndarray,  # [N, R] int64 — out-of-batch nominee requests
    nom_cnt0: jnp.ndarray,  # [N] int32 — out-of-batch nominee pod counts
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Returns (chosen [P] int32 node row or -1, victims [P, V] bool —
    victim slots of the chosen node, fits_free [P] bool — the pod fits a
    candidate node WITHOUT evicting anyone at its step's live state, so no
    preemption happens and the caller should simply retry the pod)."""
    n, v_cap, r = vict_req.shape
    always = (jnp.arange(r) < 3)[None, :]  # cpu/mem/ephemeral slots

    def step(carry, k):
        free, count_free, alive, nom_extra, nom_cnt = carry
        req = p_req[k]  # [R]
        checked = always | (req[None, :] > 0)  # [1->N, R]
        # nominee-adjusted view: what findNodesThatFit/podFitsOnNode pass-1
        # would see — free minus outstanding nominee reservations
        nfree = free - nom_extra
        ncount_free = count_free - nom_cnt
        # preemption only when the pod truly fits NOWHERE as-is
        # (Preempt runs after findNodesThatFit came back empty — a stale
        # speculative -1 must not evict anyone when live state fits)
        free_ok = jnp.all((nfree - req[None, :] >= 0) | ~checked, axis=1) | ~p_req_any[k]
        fits_free = jnp.any(cand[k] & node_valid & free_ok & (ncount_free >= 1))
        lower = alive & vict_valid & (vict_prio < p_prio[k])  # [N, V]
        freed = jnp.sum(jnp.where(lower[..., None], vict_req, 0), axis=1)  # [N, R]
        nfreed = jnp.sum(lower, axis=1).astype(jnp.int32)  # [N]
        head0 = nfree + freed - req[None, :]  # [N, R]
        res_ok = jnp.all((head0 >= 0) | ~checked, axis=1) | ~p_req_any[k]
        cslack0 = ncount_free + nfreed - 1  # [N]
        fits = cand[k] & node_valid & res_ok & (cslack0 >= 0) & (nfreed > 0)

        # greedy reprieve in slot order (host pre-sorted: violating first,
        # then importance) — selectVictimsOnNode's re-add loop, every node
        # in parallel
        def rep(c2, vi):
            head, cslack = c2
            is_l = lower[:, vi]
            r_v = vict_req[:, vi]  # [N, R]
            keep_res = jnp.all((head - r_v >= 0) | ~checked, axis=1) | ~p_req_any[k]
            can_keep = is_l & keep_res & (cslack >= 1)
            head = head - jnp.where(can_keep[:, None], r_v, 0)
            cslack = cslack - can_keep.astype(jnp.int32)
            return (head, cslack), is_l & ~can_keep

        (_, _), victim_cols = jax.lax.scan(
            rep, (head0, cslack0), jnp.arange(v_cap)
        )
        victims = victim_cols.T  # [N, V]
        cnt = jnp.sum(victims, axis=1).astype(jnp.int32)
        feasible = fits & (cnt > 0)

        # pickOneNodeForPreemption's lexicographic chain, vectorized as
        # successive keep-min filters
        viol = jnp.sum(victims & vict_pdb, axis=1).astype(jnp.int64)
        vp = jnp.where(victims, vict_prio, jnp.iinfo(jnp.int32).min)
        maxprio = jnp.max(vp, axis=1).astype(jnp.int64)
        # sum in int64: 3+ victims at ~2e9 priority overflow an int32 sum,
        # which would corrupt the tie-break vs the host's exact Python ints
        psum = jnp.sum(
            jnp.where(victims, vict_prio.astype(jnp.int64), 0), axis=1
        )
        is_top = victims & (vict_prio.astype(jnp.int64) == maxprio[:, None])
        maxts = jnp.max(jnp.where(is_top, vict_ts, _TS_MIN), axis=1)

        sel = feasible
        for key in (viol, maxprio, psum, cnt.astype(jnp.int64), -maxts):
            masked = jnp.where(sel, key, _BIG)
            sel = sel & (masked == jnp.min(masked))
        found = jnp.any(sel) & p_valid[k] & ~fits_free
        chosen = jnp.argmax(sel)  # lowest row among survivors
        onehot = (jnp.arange(n) == chosen) & found

        # earlier victims vanish for later preemptors, and the chosen
        # preemptor's request becomes a NOMINEE charge on its node (the
        # queue's nominated index, which pass-1 fit checks count)
        freed_sel = jnp.sum(jnp.where(victims[..., None], vict_req, 0), axis=1)
        free = free + jnp.where(onehot[:, None], freed_sel, 0)
        count_free = count_free + jnp.where(onehot, cnt, 0)
        nom_extra = nom_extra + jnp.where(onehot[:, None], req[None, :], 0)
        nom_cnt = nom_cnt + onehot.astype(nom_cnt.dtype)
        alive = alive & ~(onehot[:, None] & victims)
        out_node = jnp.where(found, chosen, -1).astype(jnp.int32)
        out_victims = victims[chosen] & found
        return (free, count_free, alive, nom_extra, nom_cnt), (
            out_node, out_victims, fits_free,
        )

    init = (
        free0,
        count_free0.astype(jnp.int32),
        jnp.ones(vict_valid.shape, bool),
        nom_extra0,
        nom_cnt0.astype(jnp.int32),
    )
    _, (nodes_out, victims_out, fits_free_out) = jax.lax.scan(
        step, init, jnp.arange(p_prio.shape[0])
    )
    return nodes_out, victims_out, fits_free_out
