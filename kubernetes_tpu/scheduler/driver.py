"""Scheduler driver: the batch scheduling loop.

The reference's scheduleOne (scheduler.go:579) does, per pod: pop →
snapshot → filter → score → selectHost → reserve → assume → async(permit →
prebind → bind → postbind). This driver keeps exactly that lifecycle and
extension-hook order but amortizes the expensive middle across a BATCH:

    pop_batch → TensorMirror.sync (dirty-row patch) → device kernels
    (filter+score+topology matrices) → lax.scan greedy solve →
    per-pod commit: [oracle re-check if topology-coupled] → reserve →
    assume → async bind pipeline

Failure handling mirrors MakeDefaultErrorFunc (factory.go:646): failed /
unfitting pods go back through AddUnschedulableIfNotPresent with the cycle
counter, and preemption (preemption.py) nominates a node when enabled.

The pipeline parallelism of assume-then-async-bind (scheduler.go:631-673) is
kept: binds run on a thread pool while the next batch solves on device.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..api.types import Pod
from ..framework.interface import CycleState, Framework, Status
from ..oracle.predicates import compute_predicate_metadata, pod_fits_on_node
from ..state.cache import SchedulerCache, TensorMirror
from ..state.queue import PodInfo, PriorityQueue
from ..state.tensors import KeySlotOverflow, PodBatch, _bucket
from ..state.terms import compile_batch_terms, compile_existing_terms
from . import preemption as preemption_mod


@dataclass
class ScheduleResult:
    scheduled: int = 0
    unschedulable: int = 0
    errors: int = 0
    preempted: int = 0
    assignments: Dict[str, str] = field(default_factory=dict)


class Binder:
    """Default binder: callable hook (pod, node_name) -> None, raising on
    failure — the equivalent of POST pods/<p>/binding (factory.go:713)."""

    def __init__(self, bind_fn: Optional[Callable[[Pod, str], None]] = None):
        self._fn = bind_fn

    def bind(self, pod: Pod, node_name: str) -> None:
        if self._fn is not None:
            self._fn(pod, node_name)


def _needs_oracle_recheck(pod: Pod) -> bool:
    """Pods whose feasibility can be perturbed by earlier pods in the same
    batch (the solver's carry only tracks resources): topology-spread or
    required (anti-)affinity terms. See ops/solver.py contract."""
    if pod.topology_spread_constraints:
        return True
    a = pod.affinity
    if a is not None and (a.pod_affinity is not None or a.pod_anti_affinity is not None):
        return True
    return False


class Scheduler:
    """The driver. One instance per scheduler process (leader)."""

    def __init__(
        self,
        cache: Optional[SchedulerCache] = None,
        queue: Optional[PriorityQueue] = None,
        binder: Optional[Binder] = None,
        framework: Optional[Framework] = None,
        batch_size: int = 256,
        enable_preemption: bool = True,
        deterministic: bool = False,
        seed: int = 0,
        error_fn: Optional[Callable[[Pod, Exception], None]] = None,
        bind_workers: int = 8,
        event_fn: Optional[Callable[[Pod, str, str], None]] = None,
    ):
        self.cache = cache or SchedulerCache()
        self.queue = queue or PriorityQueue()
        self.binder = binder or Binder()
        self.framework = framework or Framework()
        self.mirror = TensorMirror(self.cache)
        self.batch_size = batch_size
        self.enable_preemption = enable_preemption
        self.deterministic = deterministic
        self.error_fn = error_fn
        self.event_fn = event_fn or (lambda pod, reason, msg: None)
        self._bind_pool = ThreadPoolExecutor(max_workers=bind_workers, thread_name_prefix="bind")
        self._rng_seed = seed
        self._cycle = 0
        self._spread_selectors_fn: Optional[Callable[[Pod], list]] = None
        self._jax = None  # lazily imported so pure-host tests stay light

    def set_spread_selectors_fn(self, fn: Callable[[Pod], list]) -> None:
        """Install the getSelectors equivalent (services/RC/RS/SS listers,
        selector_spreading.go getSelectors) used for SelectorSpread scoring."""
        self._spread_selectors_fn = fn

    # -- device solve --------------------------------------------------------

    def _device_solve(self, infos: List[PodInfo]) -> np.ndarray:
        import jax
        import jax.numpy as jnp

        from ..ops import filters as F
        from ..ops import scores as S
        from ..ops import topology as T
        from ..ops.solver import pop_order, solve_greedy

        pods = [pi.pod for pi in infos]
        vocab = self.mirror.vocab
        while True:
            try:
                batch = PodBatch(vocab, _bucket(len(pods)))
                for i, p in enumerate(pods):
                    batch.set_pod(i, p)
                selectors = None
                if self._spread_selectors_fn is not None:
                    selectors = {id(p): self._spread_selectors_fn(p) for p in pods}
                tb, aux = compile_batch_terms(
                    vocab, pods, spread_selectors=selectors, b_capacity=batch.capacity
                )
                etb, _ = compile_existing_terms(vocab, self.cache.snapshot, self.mirror.row_of)
                break
            except KeySlotOverflow:
                self.mirror._rebuild()

        J = lambda d: {k: jnp.asarray(v) for k, v in d.items()}
        na = J(self.mirror.nodes.arrays())
        pa = J(batch.arrays())
        ea = J(self.mirror.eps.arrays())
        ta = J(tb.arrays())
        xa = J(etb.arrays())
        au = J(aux)
        ids = F.make_ids(vocab)

        base = F.combined_mask(na, pa, ids)
        sel = F.pod_match_node_selector(na, pa)
        mask = (
            base
            & T.spread_filter(na, ea, ta, sel)
            & T.interpod_filter(na, ea, ta, au, xa, pa)
        )
        score = (
            S.score_matrix(na, pa)
            + T.interpod_score(na, ea, ta, xa, pa)
            + T.spread_score(na, ea, ta, au, sel)
            + T.selector_spread_score(na, ea, ta, au)
        )
        free0 = na["alloc"] - na["requested"]
        order = pop_order(
            pa["priority"],
            jnp.asarray(np.arange(batch.capacity, dtype=np.int32)),
            pa["valid"],
        )
        self._cycle += 1
        key = jax.random.PRNGKey(self._rng_seed + self._cycle)
        assign = solve_greedy(
            mask,
            score,
            pa["req"],
            free0,
            na["pod_count"].astype(free0.dtype),
            na["allowed_pods"].astype(free0.dtype),
            order,
            key,
            deterministic=self.deterministic,
        )
        return (
            np.asarray(assign)[: len(pods)],
            np.asarray(pa["fallback"])[: len(pods)],
            np.asarray(score)[: len(pods)],
        )

    def _oracle_place(self, pod: Pod, score_row: np.ndarray, meta) -> Optional[str]:
        """Scalar fallback placement: oracle-feasible nodes against the live
        snapshot (including this batch's assumed pods), best device score
        first."""
        best = None
        best_score = None
        for cand, ni in self.cache.snapshot.node_infos.items():
            if not pod_fits_on_node(pod, ni, meta=meta)[0]:
                continue
            row = self.mirror.row_of.get(cand)
            s = int(score_row[row]) if row is not None and row < len(score_row) else 0
            if best_score is None or s > best_score:
                best, best_score = cand, s
        return best

    # -- commit path ---------------------------------------------------------

    def _commit(self, info: PodInfo, node_name: str, cycle: int) -> bool:
        """reserve → assume → async(permit → prebind → bind → postbind)."""
        pod = info.pod
        state = CycleState()
        st = self.framework.run_reserve(state, pod, node_name)
        if not st.is_success():
            self._fail(info, cycle, f"reserve: {st.message}")
            return False
        import dataclasses

        assumed = dataclasses.replace(pod, node_name=node_name)
        try:
            self.cache.assume_pod(assumed)
        except ValueError:
            self._fail(info, cycle, "already assumed")
            return False

        def bind_async():
            st = self.framework.run_permit(state, pod, node_name)
            if not st.is_success():
                self._unbind(info, assumed, node_name, state, cycle, f"permit: {st.message}")
                return
            st = self.framework.run_pre_bind(state, pod, node_name)
            if not st.is_success():
                self._unbind(info, assumed, node_name, state, cycle, f"prebind: {st.message}")
                return
            try:
                st = self.framework.run_bind(state, pod, node_name)
                if st.code != 0 and st.code != 4:  # not SUCCESS, not SKIP
                    raise RuntimeError(st.message)
                self.binder.bind(pod, node_name)
            except Exception as e:  # bind RPC failed → forget + requeue
                self._unbind(info, assumed, node_name, state, cycle, f"bind: {e}")
                return
            self.cache.finish_binding(assumed)
            self.framework.run_post_bind(state, pod, node_name)
            self.event_fn(pod, "Scheduled", f"bound to {node_name}")

        self._bind_pool.submit(bind_async)
        return True

    def _unbind(self, info: PodInfo, assumed: Pod, node_name: str, state, cycle: int, msg: str) -> None:
        self.cache.forget_pod(assumed)
        self.framework.run_unreserve(state, info.pod, node_name)
        self._fail(info, cycle, msg)

    def _fail(self, info: PodInfo, cycle: int, msg: str) -> None:
        self.event_fn(info.pod, "FailedScheduling", msg)
        self.queue.add_unschedulable(info, cycle)

    def _try_preempt(self, info: PodInfo) -> bool:
        """scheduler.go:612 preempt: nominate a node, delete victims."""
        pod = info.pod
        node, victims, clear = preemption_mod.preempt(pod, self.cache.snapshot)
        if node is None:
            return False
        for v in victims:
            self.cache.remove_pod(v)
            self.event_fn(v, "Preempted", f"by {pod.key()}")
        pod.nominated_node_name = node
        self.event_fn(pod, "Nominated", node)
        return True

    # -- main loop -----------------------------------------------------------

    def schedule_batch(self, max_pods: Optional[int] = None) -> ScheduleResult:
        res = ScheduleResult()
        infos = self.queue.pop_batch(max_pods or self.batch_size)
        if not infos:
            return res
        cycle = self.queue.scheduling_cycle()
        self.mirror.sync()
        try:
            assign, fallback, score = self._device_solve(infos)
        except Exception as e:
            for info in infos:
                res.errors += 1
                if self.error_fn:
                    self.error_fn(info.pod, e)
                self._fail(info, cycle, f"solve error: {e}")
            return res

        # commit in pop order (priority desc) so oracle re-checks see earlier
        # assumes, reproducing sequential semantics for topology pods
        order = sorted(
            range(len(infos)),
            key=lambda i: (-infos[i].pod.get_priority(), infos[i].seq),
        )
        for i in order:
            info = infos[i]
            pod = info.pod
            row = int(assign[i])
            node_name = self.mirror.node_name_of_row(row) if row >= 0 else None
            if node_name is not None and (fallback[i] or _needs_oracle_recheck(pod)):
                ni = self.cache.snapshot.get(node_name)
                meta = compute_predicate_metadata(pod, self.cache.snapshot)
                ok = ni is not None and pod_fits_on_node(pod, ni, meta=meta)[0]
                if not ok:
                    # invalidated by an earlier commit in this batch (the
                    # solver carry tracks only resources) — re-place via the
                    # oracle against the CURRENT snapshot, ranking candidates
                    # by the device score row (sequential-equivalent filter,
                    # batch-stale scores)
                    node_name = self._oracle_place(pod, score[i], meta)
            if fallback[i] and node_name is None:
                # encoding overflowed — full scalar fallback over all nodes
                meta = compute_predicate_metadata(pod, self.cache.snapshot)
                node_name = self._oracle_place(pod, score[i], meta)
            if node_name is None:
                res.unschedulable += 1
                self._fail(info, cycle, "no fit")
                if self.enable_preemption and self._try_preempt(info):
                    res.preempted += 1
                    # victim deletions are cluster events: wake the queue
                    # (eventhandlers.go:127 → MoveAllToActiveQueue); the pod
                    # retries after its backoff expires
                    self.queue.move_all_to_active()
                continue
            if self._commit(info, node_name, cycle):
                res.scheduled += 1
                res.assignments[pod.key()] = node_name
            else:
                res.unschedulable += 1
        return res

    def run_until_empty(self, max_cycles: int = 1000) -> ScheduleResult:
        total = ScheduleResult()
        for _ in range(max_cycles):
            r = self.schedule_batch()
            total.scheduled += r.scheduled
            total.unschedulable += r.unschedulable
            total.errors += r.errors
            total.preempted += r.preempted
            total.assignments.update(r.assignments)
            if r.scheduled == 0 and r.unschedulable == 0 and r.errors == 0:
                break
        return total

    def wait_for_binds(self) -> None:
        """Drain the bind pipeline (tests/benchmarks)."""
        self._bind_pool.shutdown(wait=True)
        self._bind_pool = ThreadPoolExecutor(max_workers=8, thread_name_prefix="bind")
