"""Scheduler driver: the batch scheduling loop.

The reference's scheduleOne (scheduler.go:579) does, per pod: pop →
snapshot → filter → score → selectHost → reserve → assume → async(permit →
prebind → bind → postbind). This driver keeps exactly that lifecycle and
extension-hook order but amortizes the expensive middle across a BATCH:

    pop_batch → TensorMirror.sync (dirty rows + pod deltas) → device
    kernels (filter+score+topology matrices over deduped spec rows) →
    chunked greedy solve → per-pod commit: [oracle re-check if
    topology-coupled] → reserve → assume → async bind pipeline, with the
    NEXT batch's solve speculatively dispatched against the device's own
    residual carry before this batch commits

Failure handling mirrors MakeDefaultErrorFunc (factory.go:646): failed /
unfitting pods go back through AddUnschedulableIfNotPresent with the cycle
counter, and preemption (preemption.py) nominates a node when enabled.

The pipeline parallelism of assume-then-async-bind (scheduler.go:631-673) is
kept: binds run on a thread pool while the next batch solves on device.
"""

from __future__ import annotations

import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..api.types import Pod, PodDisruptionBudget
from ..commit import (
    ColumnarApply,
    CommitPipeline,
    GangRollbackRecord,
    V_DEFER,
    V_NOFIT,
    V_PLACE,
    kinds_covered,
)
from ..compile import CompilePlan, SolveSpec, WarmupService
from ..compile.ladder import (
    KIND_ARBITER,
    KIND_FOLD,
    KIND_PREEMPT,
    KIND_SOLVE,
    KIND_SOLVE_GANG,
)
from ..compile.plan import SOURCE_INLINE, SOURCE_PERSISTED
from ..analysis.lockorder import register_thread_role
from ..framework.interface import CycleState, Framework
from ..api.selectors import match_label_selector
from ..oracle.predicates import (
    compute_predicate_metadata,
    get_pod_affinity_terms,
    get_pod_anti_affinity_terms,
    pod_fits_on_node,
    pod_fits_resources,
    pod_matches_all_term_properties,
    pod_matches_term,
)
from ..state.cache import SchedulerCache, TensorMirror
from ..state.queue import PodInfo, PriorityQueue
from ..state.tensors import KeySlotOverflow, PodBatch, _bucket, spec_key
from ..state.terms import compile_batch_terms, count_batch_terms
from ..metrics import metrics as M
from ..obs import RECORDER as OBS
from ..utils.trace import Trace
from ..volume.predicates import scheduling_relevant_volumes
from . import preemption as preemption_mod
from .preemption import fits_considering_nominated, fits_with_nominees


@dataclass
class ScheduleResult:
    scheduled: int = 0
    unschedulable: int = 0
    errors: int = 0
    preempted: int = 0
    # commit-plane defer-to-next-batch verdicts: pods returned to activeQ
    # (no backoff) because an earlier commit of their own batch conflicted
    # — NOT unschedulable, and a drain loop must not stop while any exist
    deferred: int = 0
    assignments: Dict[str, str] = field(default_factory=dict)


class ScoreRows:
    """Lazy per-row view of the device score matrix. Fetching the full
    [U, N] matrix is the single most expensive transfer in the system on a
    remote-attached TPU (100+ MB at ~15 MB/s for the 10k-node config);
    only the handful of rows the oracle re-placement path actually ranks
    with may cross the wire (ops/pipeline.gather_score_rows).

    The device matrix holds one row per unique pod SPEC; `sig` maps pod
    batch positions onto spec rows (None = identity). Indexing stays by
    batch position — duplicates share one fetched row."""

    def __init__(self, score_dev, sig: Optional[Sequence[int]] = None):
        self._dev = score_dev
        self._sig = sig
        self._cache: Dict[int, np.ndarray] = {}

    def _row_of(self, i: int) -> int:
        return int(self._sig[i]) if self._sig is not None else i

    def __getitem__(self, i: int) -> np.ndarray:
        row = self._cache.get(self._row_of(i))
        if row is None:
            self.prefetch([i])
            row = self._cache[self._row_of(i)]
        return row

    def prefetch(self, indices) -> None:
        """Fetch many rows in ONE gather+transfer. The per-row path pays the
        ~100ms round-trip fixed cost per pod — a host-rank batch (Score
        plugins / prioritize extenders) must bulk-fetch instead. The index
        count is padded to a power-of-two bucket (repeating the first index)
        so the jitted gather compiles once per bucket, not per batch."""
        from ..ops.pipeline import gather_score_rows

        import jax.numpy as jnp

        want = sorted({self._row_of(i) for i in indices} - self._cache.keys())
        if not want:
            return
        nb = min(_bucket(len(want)), int(self._dev.shape[0]))
        padded = (want + [want[0]] * nb)[:nb]
        rows = np.asarray(gather_score_rows(self._dev, jnp.asarray(padded)))
        for j, r in enumerate(padded[: len(want)]):
            self._cache[r] = rows[j]


@dataclass
class SolveOutput:
    """Device-solve result + the host-side caveats the commit loop must
    honor (overflowed encodings force the scalar oracle path)."""

    assign: np.ndarray  # [len(pods)] node row or -1
    fallback: np.ndarray  # [len(pods)] bool: encoding/term overflow → oracle
    score: "ScoreRows"  # lazy [len(pods), N] device score rows (oracle ranking)
    has_anti: np.ndarray  # [len(pods)] bool: pod carries required anti-affinity
    existing_overflow: bool  # existing pods' terms truncated → recheck all
    node_fallback_any: bool  # some node rows excluded from the fast path
    gang_ok: Optional[np.ndarray] = None  # [len(pods)] all-or-nothing verdict
    # solved speculatively against the PREVIOUS batch's device residuals:
    # topology/affinity counts are one batch stale, so LIGHT re-checks
    # escalate to the full live-snapshot oracle check
    speculative: bool = False
    # [len(pods)] RECHECK_* per pod, computed once per unique SPEC at
    # dispatch (the level is a pure function of spec-key fields)
    levels: Optional[np.ndarray] = None
    # the device solve sequentialized required anti-affinity + host ports
    # WITHIN the batch (ops/solver.py inb): non-speculative batches can skip
    # the host LIGHT rechecks while commits follow the device's choices
    inbatch_tracked: bool = False
    # queue.nomination_adds at dispatch: outstanding out-of-batch
    # nominations were folded into this solve's mask; equality with the
    # queue's current counter means no nomination appeared since (clears
    # only make the mask conservative)
    nom_adds: int = -1
    # commit-plane arbiter verdicts ([len(pods)] V_PLACE/V_DEFER/V_NOFIT,
    # None when the arbiter was not dispatched — gang batches, plane off)
    verdicts: Optional[np.ndarray] = None
    # the term kinds ACTUALLY present in this batch (exact per-batch set,
    # not the monotone compile union) — the arbiter coverage gate
    present_kinds: frozenset = frozenset()


class ExtenderError(Exception):
    """A non-ignorable extender wire failure. Distinct from 'no fit': the
    reference treats extender errors as scheduling ERRORS (retry via the
    error path) — never as FitError, so they must not trigger preemption
    (core/generic_scheduler.go:531-557 error return vs FitError)."""


class Binder:
    """Default binder: callable hook (pod, node_name) -> None, raising on
    failure — the equivalent of POST pods/<p>/binding (factory.go:713)."""

    def __init__(self, bind_fn: Optional[Callable[[Pod, str], None]] = None):
        self._fn = bind_fn

    def bind(self, pod: Pod, node_name: str) -> None:
        if self._fn is not None:
            self._fn(pod, node_name)


# Gang/co-scheduling group marker (the coscheduling plugin's PodGroup label,
# absent upstream in this version — the batched formulation makes
# all-or-nothing natural, SURVEY §7 stage 7). Label preferred; annotation
# accepted.
POD_GROUP_LABEL = "pod-group.scheduling.sigs.k8s.io/name"
POD_GROUP_MIN_AVAILABLE = "pod-group.scheduling.sigs.k8s.io/min-available"


def pod_group_name(pod: Pod) -> str:
    """Memoized (labels/annotations are spec-stable; read 3x per pod per
    batch across assembly, dispatch, and the commit loop)."""
    g = pod.__dict__.get("_grp_memo")
    if g is None:
        g = pod.labels.get(POD_GROUP_LABEL, "") or pod.annotations.get(POD_GROUP_LABEL, "")
        pod.__dict__["_grp_memo"] = g
    return g


def pod_group_min_available(pod: Pod) -> int:
    """The group's declared size: when set, a batch holding fewer members
    (the rest not yet created/queued) must not bind its slice."""
    raw = pod.labels.get(POD_GROUP_MIN_AVAILABLE, "") or pod.annotations.get(
        POD_GROUP_MIN_AVAILABLE, ""
    )
    try:
        return int(raw)
    except ValueError:
        return 0


def _term_kind_names(present, any_sel_spread: bool, etb) -> frozenset:
    """(batch term-kind ints, sel-spread flag, existing-pods bank) → the
    jit-static kind set mask_and_score gates its topology kernels on.
    Exact: a kind absent here means the corresponding kernel part would
    compute its identity. The batch half takes the PRESENT kind ints
    directly so both term transports share it — the legacy path scans the
    compiled bank (_present_term_kinds), the covered index path unions
    the interned entries' cached kind sets (no bank to scan host-side)."""
    from ..state.terms import (
        AFF_PREF,
        AFF_REQ,
        ANTI_PREF,
        ANTI_REQ,
        SEL_SPREAD,
        SPREAD_HARD,
        SPREAD_SOFT,
    )

    kinds = set()
    if SPREAD_HARD in present:
        kinds.add("spread_hard")
    if SPREAD_SOFT in present:
        kinds.add("spread_soft")
    if AFF_REQ in present:
        kinds.add("aff_req")
    if ANTI_REQ in present:
        kinds.add("anti_req")
    if AFF_PREF in present or ANTI_PREF in present:
        kinds.add("pref")
    if SEL_SPREAD in present or any_sel_spread:
        kinds.add("sel_spread")
    et_present = set(np.unique(etb.kind[etb.valid]).tolist())
    if ANTI_REQ in et_present:
        kinds.add("et_anti")
    if et_present & {AFF_REQ, AFF_PREF, ANTI_PREF}:
        kinds.add("et_score")
    return frozenset(kinds)


def _present_term_kinds(tb, etb, aux) -> frozenset:
    """Host-side scan of the compiled term banks (the legacy transport's
    half of _term_kind_names)."""
    present = set(np.unique(tb.kind[tb.valid]).tolist())
    return _term_kind_names(present, bool(np.any(aux["n_sel_spread"] > 0)), etb)


class _BatchConflictIndex:
    """Commits of the current batch indexed by (topology key, value) for the
    LIGHT intra-batch anti-affinity re-check. Two directions
    (predicates.go:1284 satisfiesExistingPodsAntiAffinity +
    satisfiesPodsAffinityAntiAffinity, anti half):

      * a committed pod's required anti term blocks later pods on nodes
        sharing the term's topology value with the commit node;
      * a later pod's own anti terms block it on nodes sharing a topology
        value with any commit whose pod the term selects.

    Rolled-back gang members are tombstoned rather than unindexed (rollback
    is rare; lookups skip them).

    Buckets group their entries by SPEC (controller replicas share labels
    and terms), and selector-match results are memoized per (direction,
    commit spec, term index, candidate spec): a domain holding hundreds of
    same-spec commits costs ONE match evaluation plus a liveness peek
    instead of a pod_matches_term call per commit — the difference between
    ~2us and ~250us per LIGHT recheck on the quadratic config."""

    def __init__(self):
        # (key, value of commit node) → {spec: {t_i: [(committed pod, term)]}}
        # — keyed by term INDEX inside the spec bucket: two anti terms
        # sharing a topology key land in the same (kv, spec) bucket and
        # must each be evaluated (one representative per term, not per
        # bucket)
        self._anti_by_kv: Dict[Tuple[str, str], Dict] = {}
        # (key, value of commit node) → {spec: [committed pods]}
        self._commits_by_kv: Dict[Tuple[str, str], Dict] = {}
        self._rolled_back: set = set()
        # handoff object: built by ONE thread (the driver's commit loop,
        # or the commit-pipeline worker via LazyConflictIndex), then read
        # after the pipeline drain's happens-before edge — never mutated
        # concurrently, so the flags carry allow(KTPU006) not a lock
        self._match_memo: Dict[Tuple, bool] = {}
        self.any_anti = False  # ktpu: allow(KTPU006) single-owner handoff
        self.any_ports = False  # ktpu: allow(KTPU006) single-owner handoff
        self.commits: List[Pod] = []  # flat, in commit order

    def add_commit(self, pod: Pod, node) -> None:
        self.commits.append(pod)
        if pod.host_ports():
            self.any_ports = True
        spec = spec_key(pod)
        for kv in node.labels.items():
            self._commits_by_kv.setdefault(kv, {}).setdefault(spec, []).append(pod)

    def add_anti(self, pod: Pod, node) -> None:
        self.any_anti = True
        spec = spec_key(pod)
        for t_i, term in enumerate(get_pod_anti_affinity_terms(pod.affinity)):
            k = term.topology_key
            v = node.labels.get(k) if k else None
            if v is not None:
                self._anti_by_kv.setdefault((k, v), {}).setdefault(
                    spec, {}
                ).setdefault(t_i, []).append((pod, term))

    def remove(self, pod: Pod) -> None:
        self._rolled_back.add(id(pod))

    def _any_live(self, entries, pod_of=lambda e: e) -> bool:
        return any(id(pod_of(e)) not in self._rolled_back for e in entries)

    def anti_conflict(self, pod: Pod, node) -> bool:
        p_spec = spec_key(pod)
        memo = self._match_memo
        for kv in node.labels.items():
            for c_spec, by_term in self._anti_by_kv.get(kv, {}).items():
                # one representative match per (commit spec, term, pod
                # spec) — every DISTINCT term of the spec is consulted
                for t_i, entries in by_term.items():
                    c, term = entries[0]
                    mk = ("A", c_spec, t_i, p_spec)
                    hit = memo.get(mk)
                    if hit is None:
                        hit = pod_matches_term(pod, c, term)
                        memo[mk] = hit
                    if hit and self._any_live(entries, lambda e: e[0]):
                        return True
        a = pod.affinity
        if a is not None and a.pod_anti_affinity is not None:
            for t_i, term in enumerate(a.pod_anti_affinity.required):
                k = term.topology_key
                v = node.labels.get(k) if k else None
                if v is None:
                    continue
                for c_spec, entries in self._commits_by_kv.get((k, v), {}).items():
                    mk = ("B", p_spec, t_i, c_spec)
                    hit = memo.get(mk)
                    if hit is None:
                        hit = pod_matches_term(entries[0], pod, term)
                        memo[mk] = hit
                    if hit and self._any_live(entries):
                        return True
        return False


class LazyConflictIndex:
    """A _BatchConflictIndex built on demand from raw (pod, node) commit
    pairs. The arbiter commit path never walks a per-pod index itself —
    but speculative-chain entries dispatched before this batch still need
    one (their masks predate these commits). Recording the pairs costs
    ~0.5us/pod on the critical path; the index materializes on the commit
    PIPELINE worker (off the hot loop) or lazily at first consume."""

    def __init__(self, pairs: List[Tuple[Pod, object]]):
        self._pairs = pairs
        # ktpu: allow(KTPU006) idempotent memo: materializes on the commit
        # worker or at first consume; callers are ordered by the pipeline
        # drain, and a duplicate build from the same pairs is identical
        self._ix: Optional[_BatchConflictIndex] = None

    def materialize(self) -> "_BatchConflictIndex":
        if self._ix is None:
            ix = _BatchConflictIndex()
            for pod, node in self._pairs:
                ix.add_commit(pod, node)
                a = pod.affinity
                if a is not None and a.pod_anti_affinity is not None and a.pod_anti_affinity.required:
                    ix.add_anti(pod, node)
            self._ix = ix
        return self._ix

    def anti_conflict(self, pod: Pod, node) -> bool:
        return self.materialize().anti_conflict(pod, node)


# spec_key moved to state/tensors.py (it is an encoding-layer concept and
# the queue's memo warming must not import the scheduler layer); re-exported
# here for the driver's own call sites and existing imports
_spec_key = spec_key

_NOM_FOLD = None


# ktpu: admitted(KIND_FOLD) dispatched only through mirror.fold_nominees,
# which admits a KIND_FOLD nominee spec; warmed at pow-2 rungs at startup
def _nominee_fold_fn():
    """Jitted overlay of out-of-batch nominees' requests onto the node
    bank's usage columns — podFitsOnNode's pass-1 nominee accounting
    (generic_scheduler.go:620-630) done ONCE per dispatch on device instead
    of per pod x node on the host. Conservative vs the reference in one
    way: all nominees count, not only those with priority >= the incoming
    pod's (a per-pod filter would need a [B, N, R] overlay); pass 2
    (without nominees) is vacuous for resource-only pods, and pods with
    topology terms keep the full host recheck path."""
    global _NOM_FOLD
    if _NOM_FOLD is None:
        import jax

        @jax.jit
        def fold(na, rows, vecs, cnt):
            out = dict(na)
            out["requested"] = na["requested"].at[rows].add(vecs)
            out["pod_count"] = na["pod_count"].at[rows].add(cnt)
            return out

        _NOM_FOLD = fold
    return _NOM_FOLD


def _no_nominations(node: str):
    """Batch-constant stand-in for queue.nominated_pods_for_node when the
    nominated index is empty: skips a lock round-trip per pod."""
    return ()


RECHECK_NONE = 0
RECHECK_LIGHT = 1  # validate against THIS BATCH's commits only (cheap)
RECHECK_FULL = 2  # full scalar oracle pass (O(cluster) metadata)


def _recheck_level(pod: Pod) -> int:
    """How much validation a pod's device placement needs against earlier
    commits in the same batch (the solver's carry only tracks resources and
    pod counts).

    FULL — the commit can be invalidated in ways only the oracle sees:
      * DoNotSchedule topology-spread (commits shift domain counts), or
      * required pod AFFINITY (the pod's anchor may itself be an in-batch
        commit — the first-pod-in-series escape let the mask pass
        everywhere, but sequential semantics pin later pods to the
        anchor's domain, predicates.go:1269).
    LIGHT — only BATCH COMMITS can break it, so checking against them
      suffices (they are already assumed into the live snapshot):
      * required ANTI-affinity (either direction), and
      * host ports (two ported pods colliding on one node).
    ScheduleAnyway spread and preferred affinity only shift SCORES —
    batch-stale scores are the accepted batching contract (ops/solver.py)."""
    a = pod.affinity
    if any(c.when_unsatisfiable == "DoNotSchedule" for c in pod.topology_spread_constraints):
        return RECHECK_FULL
    if a is not None and a.pod_affinity is not None and a.pod_affinity.required:
        return RECHECK_FULL
    if a is not None and a.pod_anti_affinity is not None and a.pod_anti_affinity.required:
        return RECHECK_LIGHT
    if pod.host_ports():
        return RECHECK_LIGHT
    return RECHECK_NONE


def _needs_oracle_recheck(pod: Pod) -> bool:
    return _recheck_level(pod) != RECHECK_NONE


def _minus_one_could_fit(
    pod: Pod, index: "_BatchConflictIndex", preempted: bool, level: int
) -> bool:
    """The device said NO node fits (against the batch-start state). Within
    the batch, feasibility can only IMPROVE through events this check
    detects — everything else (anti-affinity, ports, resource consumption)
    strictly shrinks the feasible set, so -1 stands without the O(nodes)
    oracle scan:
      * a preemption freed capacity;
      * a commit matches the pod's required affinity terms (the in-batch
        anchor case, predicates.go:1269 semantics);
      * a same-namespace commit matches a DoNotSchedule spread constraint's
        selector (raises the domain minimum, loosening the skew bound)."""
    if level != RECHECK_FULL:
        return False
    if preempted:
        return True
    a = pod.affinity
    aff_terms = get_pod_affinity_terms(a) if a is not None else []
    hard = [
        c for c in pod.topology_spread_constraints
        if c.when_unsatisfiable == "DoNotSchedule"
    ]
    for c in index.commits:
        if id(c) in index._rolled_back:
            continue
        if aff_terms and pod_matches_all_term_properties(c, pod, aff_terms):
            return True
        for con in hard:
            if c.namespace == pod.namespace and match_label_selector(
                con.label_selector, c.labels
            ):
                return True
    return False


class Scheduler:
    """The driver. One instance per scheduler process (leader)."""

    def __init__(
        self,
        cache: Optional[SchedulerCache] = None,
        queue: Optional[PriorityQueue] = None,
        binder: Optional[Binder] = None,
        framework: Optional[Framework] = None,
        batch_size: int = 256,
        enable_preemption: bool = True,
        deterministic: bool = False,
        seed: int = 0,
        error_fn: Optional[Callable[[Pod, Exception], None]] = None,
        bind_workers: int = 8,
        event_fn: Optional[Callable[[Pod, str, str], None]] = None,
        pdb_lister: Optional[Callable[[], List[PodDisruptionBudget]]] = None,
        delete_fn: Optional[Callable[[Pod], None]] = None,
        nominate_fn: Optional[Callable[[Pod, str], None]] = None,
        extenders: Optional[List] = None,
        volume_checker: Optional[Callable] = None,
        volume_binder=None,
        solve_config=None,
        speculate: bool = True,
        spec_depth: int = 2,
        mesh=None,
        compile_plan: Optional[CompilePlan] = None,
        commit_plane: bool = True,
        fold_plane: bool = True,
        ingest_plane: bool = True,
        term_plane: bool = True,
        columnar_cache: bool = True,
        trace: Optional[bool] = None,
        fault_plan=None,
    ):
        self.cache = cache or SchedulerCache()
        self.queue = queue or PriorityQueue()
        self.binder = binder or Binder()
        self.framework = framework or Framework()
        # QueueSort plugin → activeQ comparator (scheduling_queue.go:120)
        qs_less = self.framework.queue_sort_less()
        if qs_less is not None:
            self.queue.set_queue_sort(qs_less)
        self.mirror = TensorMirror(self.cache)
        # multi-chip: a jax.sharding.Mesh with a "nodes" axis routes every
        # solve through parallel.sharded.make_sharded_pipeline (node columns
        # + greedy residuals shard-local, SURVEY §2.4); the mirror keeps its
        # device banks sharded-resident so per-batch patches never reshard
        self.mesh = mesh
        self._sharded = None
        self._mesh_shards = 0
        if mesh is not None:
            from ..parallel.mesh import AXIS_NODES
            from ..parallel.sharded import make_sharded_pipeline

            self._sharded = make_sharded_pipeline(mesh)
            self._mesh_shards = mesh.shape[AXIS_NODES]
            self.mirror.set_mesh(mesh)
        self.batch_size = batch_size
        self.enable_preemption = enable_preemption
        self.deterministic = deterministic
        self.error_fn = error_fn
        self.event_fn = event_fn or (lambda pod, reason, msg: None)
        # PDB lister (preemption tie-break) and the victim-delete hook: the
        # reference issues an API delete (scheduler.go:436-470) and lets the
        # informer remove the pod; with no API, fall back to direct removal.
        self.pdb_lister = pdb_lister or (lambda: [])
        self.delete_fn = delete_fn
        # nomination write-through (podPreemptor.SetNominatedNodeName,
        # scheduler.go:436-470): persists status.nominatedNodeName at the
        # API server so an in-flight preemption SURVIVES a scheduler
        # restart — the relist reconstructs the nominated-pod overlay
        # instead of re-evicting fresh victims. None = local-only field
        # (standalone mode, no API server).
        self.nominate_fn = nominate_fn
        # HTTP extenders (core/extender.go): consulted per pod on the host
        # commit path at Filter/Prioritize time, and at Bind when one
        # handles binding (scheduler_interface.go:28-73)
        self.extenders: List = list(extenders or [])
        # volume predicates (volume.make_volume_checker) + binder seam
        # (volumebinder/volume_binder.go): pods carrying scheduling-relevant
        # volumes route through the host commit path where these run
        self.volume_checker = volume_checker
        self.volume_binder = volume_binder
        # Policy/provider selection (ops.pipeline.SolveConfig): statically
        # gates the device mask/score AND the oracle predicate chain; each
        # distinct config is one extra XLA compile
        self.solve_config = solve_config
        self._enabled_preds = solve_config.predicates if solve_config is not None else None
        self._bind_workers = bind_workers
        self._bind_pool = ThreadPoolExecutor(
            max_workers=bind_workers, thread_name_prefix="bind",
            initializer=register_thread_role, initargs=("bind",),
        )
        self._rng_seed = seed
        self._cycle = 0
        self._spread_selectors_fn: Optional[Callable[[Pod], list]] = None
        self._jax = None  # lazily imported so pure-host tests stay light
        # the compile plan owns every XLA compilation decision: the shape
        # ladder the buckets below are rungs of, the declared-spec registry,
        # hit/miss/compile telemetry, and (when configured) the persistent
        # on-disk ladder a restart re-warms from (kubernetes_tpu/compile)
        self.compile_plan = compile_plan or CompilePlan.default()
        # the mirror's dirty-row scatters are planned programs too
        # (KIND_PATCH): their post-warmup compiles were the invisible
        # mid-drain stalls on preemption/churn drains
        self.mirror.compile_plan = self.compile_plan
        # logged on every transition INTO the sharded→replicated fallback
        # (not per batch — a mid-churn indivisible bucket can persist)
        self._sharded_fallback_logged = False
        self._warm_svc: Optional[WarmupService] = None
        # growth-event AOT warming arms when warmup() runs — tests that
        # never warm up must not get surprise background compile threads
        self._aot_enabled = False
        # monotone preemptor- and victim-axis buckets for the device
        # preemption kernel (ops/preempt): a raw per-call pod/victim count
        # was one XLA signature per distinct count — the round-5
        # nominee-overlay churn. Both are passed to batch_preempt_device
        # as floors so the executed shapes equal the warmed ones.
        self._p_bucket = 0
        self._pv_bucket = 0
        # monotonic shape buckets: a smaller tail batch or a term-light batch
        # must REUSE the largest shapes seen so far — every fresh shape is a
        # fresh XLA compile (minutes on a remote TPU). Each bucket is a rung
        # of compile_plan.ladder (the quantizers are shared), so the specs
        # the driver admits are canonical by construction.
        self._b_bucket = 16
        self._u_bucket = 16  # unique-spec axis (≤ _b_bucket)
        self._t_bucket = 16
        # monotone jit-static: once a batch carries required anti-affinity
        # or host ports, compile the in-batch tracking variant and keep it
        # (a superset program is exact on batches without those features)
        self._track_inbatch = False
        self._ids = None  # cached device constants (filters.make_ids)
        # speculative pipelining state: a CHAIN of up to spec_depth
        # pre-dispatched solves, each chained on the previous dispatch's
        # device residual carry (disp=None entries hold only popped pods).
        # Depth >1 makes throughput independent of the device-result
        # round-trip: results stream back while the host commits earlier
        # batches, so even a 1.5s remote-tunnel RTT pipelines away as long
        # as RTT < depth x per-batch host time. Tradeoff: parked batches
        # are outside the priority queue, so a newly arrived high-priority
        # pod waits up to depth cycles — keep the default modest and raise
        # it for throughput-oriented drains (bench passes 8).
        self.speculate = speculate
        self.spec_depth = max(1, spec_depth)
        self._spec_chain: List[Dict] = []
        self._last_carry = None
        # anti-affinity-heavy workloads invalidate every speculation (each
        # batch commits new anti patterns): after an invalidation, skip a
        # few dispatches instead of paying wasted encode+device work
        self._spec_backoff = 0
        # per-batch oracle metadata cache (built lazily on first oracle use)
        self._aff_index = None
        self._aff_extra: List = []
        # commit plane (kubernetes_tpu/commit): device-arbitrated verdicts
        # + columnar bulk apply + double-buffered apply/bind pipelining.
        # KTPU_COMMIT_PLANE=0 is the operational kill switch.
        import os as _os

        self.commit_plane = commit_plane and _os.environ.get(
            "KTPU_COMMIT_PLANE", "1"
        ) != "0"
        # resident-state plane (ops/fold + commit/fold): covered commits
        # fold their state deltas into the device banks IN PLACE (buffer
        # donation) instead of round-tripping them host→device as dirty-
        # row scatters. Transport-only — scheduling decisions are bit-
        # identical either way (tests pin this). KTPU_FOLD_PLANE=0 is the
        # operational kill switch.
        self.fold_plane = fold_plane and _os.environ.get(
            "KTPU_FOLD_PLANE", "1"
        ) != "0"
        # with the fold plane on, the driver owns the only live reference
        # to the resident bank dicts (background warms get synthetic
        # banks), so the mirror's row scatters may donate them too
        self.mirror.donate_patches = self.fold_plane
        # columnar scheduler cache (state/columns.py): the cache's hot
        # state moves into contiguous numpy columns patched by vectorized
        # scatter-adds of the SAME interned per-spec delta rows the fold
        # plane ships (one delta source), and the per-name NodeInfo
        # object cache becomes a lazily-materialized, generation-tagged
        # view — bulk assume/forget on the covered path performs zero
        # per-pod NodeInfo/Quantity object updates. Transport/bookkeeping
        # only: placements are bit-identical either way (tests pin this).
        # KTPU_COLUMNAR_CACHE=0 is the operational kill switch.
        self.columnar_cache = columnar_cache and _os.environ.get(
            "KTPU_COLUMNAR_CACHE", "1"
        ) != "0"
        if self.columnar_cache:
            self.cache.attach_columns(self.mirror.vocab)
        # monotone pattern-triple bucket for the commit fold's [T] axis
        # and nominee-row bucket for the overlay fold's [B] axis — ladder
        # rungs, so each stays one XLA signature as it grows
        self._fp_bucket = 16
        self._nom_bucket = 16
        # pod-ingest plane (kubernetes_tpu/ingest): pod rows are encoded
        # at ADMISSION on the informer thread into a content-interned
        # slab, a device-resident staged bank is patched off-thread, and
        # a covered dispatch ships an int32 index vector instead of the
        # full pod-array upload (the input-stream counterpart of the fold
        # plane's output-stream move). Transport-only — placements are
        # bit-identical either way. KTPU_INGEST_PLANE=0 kill switch.
        self.ingest_plane = ingest_plane and _os.environ.get(
            "KTPU_INGEST_PLANE", "1"
        ) != "0"
        self.stage = None
        self.stage_bank = None
        if self.ingest_plane:
            from ..ingest import PodStage, StageBank

            self.stage = PodStage(self.mirror.vocab)
            self.stage_bank = StageBank(
                self.stage,
                place_fn=lambda v: self.mirror._to_dev(v, False),
                ship_fn=self.mirror._ship,
            )
            self.stage_bank.compile_plan = self.compile_plan
            self.queue.attach_stage(self.stage)
        # term-bank plane (kubernetes_tpu/terms_plane): the ingest move
        # applied to topology-coupled structure — each pod's spread/
        # affinity/anti-affinity terms compile ONCE at admission into a
        # content-interned slab with a device-resident twin; covered
        # dispatches gather the per-batch TermBank union from int32
        # index/owner vectors instead of rebuilding it host-side
        # (compile_batch_terms) per dispatch. Transport-only — the
        # gathered table is bit-identical to the host-built one by
        # construction. KTPU_TERM_PLANE=0 kill switch.
        self.term_plane = term_plane and _os.environ.get(
            "KTPU_TERM_PLANE", "1"
        ) != "0"
        self.tstage = None
        self.term_bank = None
        if self.term_plane:
            from ..terms_plane import TermBankDevice, TermStage

            self.tstage = TermStage(self.mirror.vocab)
            self.term_bank = TermBankDevice(
                self.tstage,
                place_fn=lambda v: self.mirror._to_dev(v, False),
                ship_fn=self.mirror._ship,
            )
            self.term_bank.compile_plan = self.compile_plan
            self.queue.attach_term_stage(self.tstage)
        self._commit_pipe = CommitPipeline()
        self._columnar = ColumnarApply(self.cache, self.queue)
        # defer-to-next-batch escalation: a pod deferred this many times
        # routes through the legacy oracle re-place instead (progress
        # guarantee against pathological repeat conflicts)
        self._defer_counts: Dict[str, int] = {}
        self._defer_escalate = 3
        # flight recorder (kubernetes_tpu/obs): span timeline + per-pod
        # attribution + black box, off by default. `trace=True` arms the
        # process-global recorder (the queue/ingest instrumentation
        # shares it, so informer/uploader spans land in one timeline);
        # trace=None defers to the KTPU_TRACE env the recorder read at
        # import. trace=False leaves the global recorder alone — a
        # second scheduler must not silence a traced one.
        if trace:
            OBS.enable(True)
        self.obs = OBS
        # steady-state health plane (obs/introspect): armed explicitly
        # via enable_health_monitor() or KTPU_HEALTH=1 — a background
        # gauge-refresh thread plus driver-executed sampled shadow
        # audits. None = no monitor thread, zero steady-state cost
        # beyond one attribute read per batch.
        self.health = None
        # last throttled observation of the O(pending) oldest-age gauge
        self._oldest_age_obs_ts = 0.0
        if _os.environ.get("KTPU_HEALTH", "") not in ("", "0"):
            self.enable_health_monitor(start=False)
        # fault plane (kubernetes_tpu/faults): the runtime degradation
        # ladder. Every plane boundary that can fail at runtime reports
        # to a per-plane circuit breaker; an open breaker routes that
        # plane's dispatches to its existing legacy host path (the
        # ON==OFF parity discipline is what makes this sound), and a
        # half-open probe re-closes only through a shadow-audit-gated
        # batch at the driver's safe sync point (_fault_service).
        # `fault_plan` (or KTPU_FAULTS=<spec>) arms seeded fault
        # injection; absent, every injection site is one attribute read.
        from ..faults import BreakerBoard, plan_from_env

        self.faults = BreakerBoard()
        self._fault_plan = fault_plan if fault_plan is not None else (
            plan_from_env(_os.environ)
        )
        # sinks route through _report_fault (not a bound board method) so
        # tests that swap self.faults for a fake-clock board keep working
        self.cache.fault_sink = self._report_fault
        self.mirror.fault_sink = self._report_fault
        self.mirror.fault_plan = self._fault_plan
        if self.stage_bank is not None:
            self.stage_bank.fault_sink = self._report_fault
            self.stage_bank.fault_plan = self._fault_plan
        if self.term_bank is not None:
            self.term_bank.fault_sink = self._report_fault
            self.term_bank.fault_plan = self._fault_plan
        if self._fault_plan is not None and self.cache._columns is not None:
            self._arm_columns_hook()
        # crash-restart plane (kubernetes_tpu/restart): the last cold-
        # start reconciliation's phase-timed report (None = this process
        # was never restarted/reconciled); surfaced through the census
        # so ktpu_top shows when and how the instance last rebuilt
        self.restart_report = None
        # close() latch + shutdown flight record (the final census)
        self._closed = False
        self.last_census: Optional[Dict] = None
        # black-box baseline: cumulative counters diffed per batch into
        # the bounded cycle ring. (This annotation previously sat inside
        # prose parentheses and NEVER PARSED — KTPU006-era rot cleanup.)
        self._bb_prev: Optional[Dict] = None  # ktpu: confined(driver)
        # per-phase wall-clock accumulators (the utiltrace/LogIfLong
        # equivalent; bench.py and metrics read these)
        self.stats: Dict[str, float] = {
            "sync_s": 0.0,
            "encode_s": 0.0,
            "solve_s": 0.0,
            "commit_s": 0.0,
            "oracle_rechecks": 0,
            "light_rechecks": 0,
            "oracle_places": 0,
            "batches": 0,
        }

    def set_spread_selectors_fn(self, fn: Callable[[Pod], list]) -> None:
        """Install the getSelectors equivalent (services/RC/RS/SS listers,
        selector_spreading.go getSelectors) used for SelectorSpread scoring."""
        self._spread_selectors_fn = fn
        if self.tstage is not None:
            # the term slab interns (spec, selectors) pairs — admission
            # must consult the same listers the dispatch dedup does, or
            # every entry would be stale by key mismatch
            self.tstage.selectors_fn = fn

    # -- observability (kubernetes_tpu/obs) ----------------------------------

    @property
    def ready(self) -> bool:
        """Readiness for /readyz: warmup completed (the reference gates
        readiness on informer sync; ours on the compile plan being armed
        — before that, the first batches pay inline XLA compiles)."""
        return bool(self.compile_plan.warmed)

    def dump_trace(self, path: str) -> str:
        """Export the flight recorder's merged span timeline as
        Chrome-trace-event JSON (open in Perfetto / chrome://tracing).
        Resolves parked device spans first — the off-hot-path half of
        the two-phase device-timing idiom."""
        self.obs.export(path)
        return path

    def enable_health_monitor(
        self, interval: float = 0.25, audit_every: int = 240,
        start: bool = True,
    ):
        """Arm the steady-state health monitor (obs/introspect):
        always-on plane gauges refreshed every `interval` seconds off a
        background thread, with a sampled shadow audit (device-bank +
        columns cross-check) executed at the driver's safe sync point
        every `audit_every` refreshes — one audit per ~minute at the
        defaults: the audit is a full-bank fetch on the driver thread,
        so its cadence is an operator dial, not a per-batch tax.
        Idempotent, and RECONFIGURES an
        existing monitor in place (a monitor pre-created by KTPU_HEALTH=1
        must not silently keep its default cadence when a caller asks
        for another). Returns the monitor. Must be called on the driver
        thread (the monitor's constructor publishes the driver-confined
        mirror census)."""
        if self.health is None:
            from ..obs.introspect import HealthMonitor

            self.health = HealthMonitor(
                self, interval=interval, audit_every=audit_every
            )
        else:
            self.health.interval = float(interval)
            self.health.audit_every = int(audit_every)
        if start:
            self.health.start()
        return self.health

    # -- fault plane (kubernetes_tpu/faults) ---------------------------------

    def _report_fault(self, plane: str, reason: str, force: bool = False) -> bool:
        """The one fault sink every reporter (banks, cache, mirror, the
        driver's own gates) routes through — reads self.faults at call
        time so a swapped board keeps receiving."""
        return self.faults.record_failure(plane, reason, force=force)

    def _arm_columns_hook(self) -> None:
        """Attach the columnar-scatter injection site to the CURRENT
        columns object (re-run after a probe re-attach)."""
        fp = self._fault_plan
        cols = self.cache._columns
        if fp is None or cols is None:
            return
        cols.fault_hook = lambda: fp.raise_if("device-raise", "columns")

    def _probe_divergence(self, planes: List[str]) -> List[str]:
        """The probe gate's shadow audit, at the driver's safe sync
        point: the PR 10 mirror probe (device_bank_divergence, including
        the columns-vs-banks cross-check) plus, for the staged-bank
        planes, each bank's own device-twin parity check. Ships pending
        dirty rows first so the probe compares a settled pair."""
        div: List[str] = []
        if self.mirror._dev_nodes is not None:
            self.mirror.device_arrays()
            div.extend(self.mirror.device_bank_divergence())
        if "ingest" in planes and self.stage_bank is not None:
            div.extend(self.stage_bank.device_divergence())
        if "terms" in planes and self.term_bank is not None:
            div.extend(self.term_bank.device_divergence())
        return div

    def _fault_service(self) -> None:
        """The fault plane's driver-side tick, at the post-sync safe
        point (commit pipeline drained, mirror freshly synced — the same
        window the PR 10 shadow audits use). In order: resolve probes
        whose covered batch has now fully settled (audit-gated close),
        run queued recovery actions for freshly tripped planes, then
        offer the gate-less planes (columns, mirror) their next probe.
        Skipped entirely — one attribute read — while the board is
        quiet."""
        from ..faults import recover as _recover

        board = self.faults
        # 1) resolve in-flight probes: the probe batch dispatched during
        # the PREVIOUS cycle; its commits/folds are drained+synced now
        probing = board.probing_planes()
        if probing:
            div = self._probe_divergence(probing)
            for plane in probing:
                b = board.breakers[plane]
                if not b.probing:
                    continue  # a fault during the probe already re-opened it
                if div:
                    b.probe_failed("audit:" + div[0])
                    # plane-appropriate repair before the NEXT probe: a
                    # divergent staged bank must resync ITS device twin
                    # (run_recoveries routes each plane to its action) —
                    # resyncing only the mirror would leave an ingest/
                    # terms twin wrong forever, probes failing at 8x
                    _recover.run_recoveries(self, [plane])
                else:
                    b.probe_passed()
        # 2) recovery actions for planes that tripped since the last tick
        pending = board.take_recoveries()
        if pending:
            _recover.run_recoveries(self, pending)
        # 3) gate-less probes: columns and the mirror have no per-dispatch
        # ok() gate, so their half-open transition is initiated here; the
        # probe resolves at the NEXT tick, after a real batch ran covered
        cb = board.breakers["columns"]
        if not cb.closed and not cb.probing and cb.allow_probe():
            if _recover.reattach_columns(self):
                self._arm_columns_hook()
            else:
                cb.probe_failed("reattach")
        mb = board.breakers["mirror"]
        if not mb.closed and not mb.probing:
            mb.allow_probe()
        board.settle()

    # ktpu: thread-entry(driver) fault recovery runs AS the driver at
    # its safe sync point — never a thread of its own
    def service_faults(self) -> None:
        """Settle the fault plane at an explicit safe point (tests,
        drain tails, idle schedulers): drain the commit pipeline, sync
        the mirror, then run the same recovery/probe service the
        per-batch hook runs. Idempotent; cheap when the board is quiet."""
        self._drain_commit()
        self.mirror.sync()
        if not self.faults.quiet:
            self._fault_service()

    def _drain_commit(self) -> None:
        """Drain the commit pipeline, then merge the worker closure's
        stat contributions into the driver-owned stats dict — the
        driver-side half of the CommitPipeline stat handoff (the stats
        dict stays single-writer; the worker writing it directly was a
        KTPU006 cross-thread read-modify-write)."""
        self._commit_pipe.drain()
        for k, v in self._commit_pipe.take_worker_stats().items():
            self.stats[k] = self.stats.get(k, 0) + v

    def _bb_counters(self) -> Dict:
        """Cumulative counters the black box diffs per batch."""
        s = self.stats
        return {
            "scheduled": 0,  # per-batch fields filled by the caller
            "bytes": dict(self.mirror.bytes_shipped),
            "fold_batches": s.get("fold_batches", 0),
            "arbiter_place": s.get("arbiter_place", 0),
            "arbiter_defer": s.get("arbiter_defer", 0),
            "ingest_index": s.get("ingest_index_batches", 0),
            "ingest_legacy": s.get("ingest_legacy_batches", 0),
            "ingest_stale": s.get("ingest_stale_rows", 0),
            "term_index": s.get("term_index_batches", 0),
            "term_legacy": s.get("term_legacy_batches", 0),
            "term_stale": s.get("term_stale_rows", 0),
            "sharded_fallbacks": s.get("sharded_fallbacks", 0),
            "spec_hits": s.get("spec_hits", 0),
            "spec_misses": s.get("spec_misses", 0),
            "compile_misses": int(
                self.compile_plan.stats.get("misses_after_warmup", 0)
            ),
        }

    # ktpu: confined(driver) called only from schedule_batch's wrapper
    def _bb_record(self, res: "ScheduleResult", cycle: int, pods: int,
                   wall: float) -> None:
        """Append one black-box cycle record (counter deltas + verdicts)
        — the artifact dumped on audit failure / LockOrderViolation /
        uncaught driver exception."""
        cur = self._bb_counters()
        prev = self._bb_prev or cur
        delta = {}
        for k, v in cur.items():
            if k == "bytes":
                pv = prev.get("bytes", {})
                delta["bytes"] = {
                    kind: n - pv.get(kind, 0) for kind, n in v.items()
                    if n - pv.get(kind, 0)
                }
            elif isinstance(v, (int, float)):
                d = v - prev.get(k, 0)
                if d:
                    delta[k] = d
        self._bb_prev = cur
        delta.update(
            cycle=cycle, pods=pods, wall_s=round(wall, 6),
            scheduled=res.scheduled, unschedulable=res.unschedulable,
            errors=res.errors, deferred=res.deferred,
            preempted=res.preempted,
        )
        self.obs.record_cycle(delta)

    # -- compile plan --------------------------------------------------------

    def _shards_now(self) -> int:
        """The node-mesh shard count the NEXT dispatch will partition
        over: the mesh's "nodes" axis when the bank capacity divides it,
        else 0 (the replicated fallback — tiny clusters on big meshes).
        Spec identity and dispatch routing share this one predicate so
        the plan can never count a fallback compile as a hit."""
        if self._sharded is None:
            return 0
        if self.mirror.nodes.capacity % self._mesh_shards != 0:
            return 0
        return self._mesh_shards

    def _solve_spec(self, gang: bool, with_carry: bool) -> SolveSpec:
        """This driver's CURRENT solve-program signature: the monotone
        buckets (ladder rungs) + every jit static. One definition so
        dispatch accounting and warmup declaration can never disagree."""
        m = self.mirror
        return SolveSpec(
            kind=KIND_SOLVE_GANG if gang else KIND_SOLVE,
            b=self._b_bucket,
            u=self._u_bucket,
            t=self._t_bucket,
            n=m.nodes.capacity,
            v=getattr(self, "_v_bucket", 16),
            k=m.nodes.key_capacity,
            r=m.nodes.alloc.shape[1],
            s=m.eps.capacity,
            pt=m.pats.capacity,
            shards=self._shards_now(),
            term_kinds=getattr(self, "_term_kinds", frozenset()),
            config_repr=repr(self.solve_config),
            deterministic=self.deterministic,
            with_carry=with_carry,
            track_inbatch=self._track_inbatch and not gang,
        )

    def _arbiter_spec(self, with_carry: bool) -> SolveSpec:
        """The commit arbiter's XLA signature: the solve's axes (it scans
        the solve's assignment at the solve's shapes), minus the statics
        the arbiter has no use for (tie-noise determinism, solver-side
        in-batch tracking) so carry variants stay the only spec split."""
        from dataclasses import replace

        return replace(
            self._solve_spec(gang=False, with_carry=with_carry),
            kind=KIND_ARBITER,
            deterministic=False,
            track_inbatch=False,
        )

    def _fold_spec(self, nominee: bool = False) -> SolveSpec:
        """The resident-state fold's XLA signature (ops/fold): commit
        variant at (b = the solve's batch rung, t = pattern-triple rung,
        bank capacities), nominee-overlay variant at (b = nominee rung)
        with s=pt=t=0 — it touches only the usage columns."""
        m = self.mirror
        r = m.nodes.alloc.shape[1]
        if nominee:
            return SolveSpec(
                kind=KIND_FOLD, b=self._nom_bucket, n=m.nodes.capacity,
                r=r, shards=self._shards_now(), config_repr="fold",
            )
        return SolveSpec(
            kind=KIND_FOLD, b=self._b_bucket, t=self._fp_bucket,
            n=m.nodes.capacity, r=r, s=m.eps.capacity, pt=m.pats.capacity,
            shards=self._shards_now(), config_repr="fold",
        )

    # ktpu: hot-path
    def _dispatch_fold(self, pairs: List[Tuple[Pod, int]]) -> bool:
        """Fold a committed batch's state deltas into the resident device
        banks (the resident-state plane's hot path). `pairs` is the FINAL
        placed set as (pod, node row). Returns True when the fold landed —
        the caller then tags the matching cache assumes `folded=True` so
        the mirror skips re-shipping those rows. Any overflow or
        non-resident bank falls back to the host scatter path silently:
        the fold is transport, never correctness."""
        if not self.fold_plane or not self.mirror.can_fold():
            return False
        if not (self.faults.quiet or self.faults.ok("fold")):
            return False  # fold breaker open: host scatter path (legacy)
        from ..commit.fold import plan_fold

        t0 = time.perf_counter()
        try:
            fp = self._fault_plan
            if fp is not None:  # injection site: one attribute read
                fp.raise_if("device-raise", "fold")
            prog = plan_fold(self.mirror, pairs, self._b_bucket, self._fp_bucket)
            if prog is None:
                return False
            self._fp_bucket = max(self._fp_bucket, prog.pat_bucket)
            spec = self._fold_spec()
            known = self.compile_plan.admit(spec)
            if not self.mirror.fold_commit(prog):
                return False
        except Exception as e:
            # a fold that raised may have PARTIALLY landed on device:
            # host wins — force a full bank re-upload before the next
            # dispatch reads them, and report to the fold breaker. The
            # caller takes the host scatter path (assumes not tagged
            # folded), so correctness never depends on the broken fold.
            self.mirror.mark_device_stale()
            self._report_fault("fold", type(e).__name__)
            self.stats["fold_fault_batches"] = (
                self.stats.get("fold_fault_batches", 0) + 1
            )
            return False
        if not known:
            self.compile_plan.note_compiled(
                spec, time.perf_counter() - t0,
                SOURCE_INLINE if self.compile_plan.warmed else "warmup",
            )
        dt = time.perf_counter() - t0
        self.stats["fold_batches"] = self.stats.get("fold_batches", 0) + 1
        self.stats["fold_pods"] = self.stats.get("fold_pods", 0) + len(pairs)
        self.stats["fold_s"] = self.stats.get("fold_s", 0.0) + dt
        M.fold_batches.inc()
        M.scheduling_stage_duration.observe(dt, "fold")
        OBS.record("fold", t0, pods=len(pairs))
        return True

    def _preempt_spec(self) -> SolveSpec:
        """The device preemption kernel's signature at current cluster
        shape (scheduler/preemption.batch_preempt_device axes, which this
        MUST mirror exactly — preempt specs are not re-rounded by the
        ladder). The victim axis uses ALL pods per node, an upper bound on
        the can_disrupt-filtered pool the runtime sees; it becomes the
        monotone `_pv_bucket` floor passed to batch_preempt_device so the
        executed v_cap equals the warmed one."""
        from ..state.tensors import _node_bucket

        snap = self.cache.snapshot
        v_max = max((len(ni.pods) for ni in snap.node_infos.values()), default=1)
        self._pv_bucket = max(self._pv_bucket, _bucket(v_max, 8))
        return SolveSpec(
            kind=KIND_PREEMPT,
            b=self._p_bucket or _bucket(self.batch_size, 8),
            n=_node_bucket(max(len(snap.node_infos), 1)),
            v=self._pv_bucket,
            # cpu/mem/ephemeral + extended-resource headroom; an exotic
            # cluster using >5 extended resources pays one inline compile
            r=8,
        )

    def _compile_growth_hook(self, spec: SolveSpec, dev) -> None:
        """Background-warm the specs one growth rung AHEAD of `spec`
        (unique-spec/term/segment buckets, signature/pattern bank growth)
        so mid-drain growth lands on a hot program instead of an inline
        compile. Armed by warmup(); `dev` is this dispatch's device-dict
        snapshot (the worker must not touch the mirror's bookkeeping)."""
        if not self._aot_enabled or self._warm_svc is None:
            return
        from dataclasses import replace

        lad = self.compile_plan.ladder
        # both carry variants: after growth, the first fresh solve runs
        # carry-less and the chained speculative ones carry — each is its
        # own program (verified: covering only one leaves the other a miss)
        specs = lad.growth_specs(spec) + lad.growth_specs(
            replace(spec, with_carry=not spec.with_carry)
        )
        if self.commit_plane and spec.kind == KIND_SOLVE:
            # the arbiter grows in lockstep with the solve it validates
            specs += lad.growth_specs(self._arbiter_spec(spec.with_carry))
            specs += lad.growth_specs(self._arbiter_spec(not spec.with_carry))
        if self.fold_plane and spec.kind == KIND_SOLVE:
            # the commit fold grows with the banks it scatters into
            # (sig/pattern capacity, pattern-triple rung)
            specs += lad.growth_specs(self._fold_spec())
        if (
            self.ingest_plane
            and self.stage_bank is not None
            and spec.kind == KIND_SOLVE
        ):
            specs = specs + self._stage_growth_specs()
        if (
            self.term_plane
            and self.term_bank is not None
            and spec.kind == KIND_SOLVE
        ):
            specs = specs + self._term_growth_specs()
        # with the fold plane on, the resident bank buffers get DONATED
        # (folds + row patches): a background warm holding this dispatch's
        # snapshot would read deleted arrays — hand it nothing and let it
        # build shape-exact synthetic banks instead
        self._warm_svc.warm_async(specs, None if self.fold_plane else dev)

    # -- pod-ingest plane (kubernetes_tpu/ingest) ----------------------------

    def _stage_growth_specs(self) -> List[SolveSpec]:
        """The index-gather's headroom set: the next unique-spec rung and
        the doubled staging slab (its growth mode on overflow). ONE
        definition shared by warmup and the dispatch-time growth hook so
        warmed and dispatched shapes can never diverge."""
        from ..compile.ladder import next_rung
        from ..ingest.stage import MAX_CAPACITY

        out: List[SolveSpec] = []
        if self._u_bucket < self._b_bucket:
            out.append(self.stage_bank.gather_spec(next_rung(self._u_bucket)))
        if self.stage.capacity * 2 <= MAX_CAPACITY:
            out.append(self.stage_bank.gather_spec(
                self._u_bucket, capacity=self.stage.capacity * 2
            ))
        return out

    # ktpu: hot-path index-only dispatch prologue: no device→host syncs
    def _stage_prologue(self, reps, rep_infos):
        """Resolve every rep's staged row and gather the batch's pod
        arrays from the device-resident staged bank (the index-only
        dispatch). Returns (pa_dev, fallback_host) or None when the batch
        cannot be covered (a stale rep that cannot re-stage: slab at its
        ceiling, vocab width growth mid-resolve) — the caller then builds
        the legacy host PodBatch, counted. Row resolution, flush, and
        gather-ARGUMENT capture run under the slab lock (concurrent
        admissions/rebuilds cannot swap rows mid-window); the gather
        dispatch itself runs after release — the captured device dicts
        are immutable (functional updates, no donation), and an unwarmed
        rung's inline compile must not stall informer admissions."""
        from ..ingest.gather import gather_stage

        stage, bank = self.stage, self.stage_bank
        t0 = time.perf_counter()
        with stage._lock:
            stage.ensure_current()
            # any rebuild DURING resolution (ensure_row hitting a full
            # slab grows it, swapping every array) invalidates the rows
            # already collected AND the row_gen reference below — detect
            # it by generation and bail to the legacy path ("one legacy
            # batch at worst", the slab-growth contract)
            gen0 = stage.generation
            rows: List[int] = []
            stale = 0
            row_gen = stage.row_gen
            for pod, pi in zip(reps, rep_infos):
                if (
                    pi.pod is pod
                    and 0 <= pi.staged_row < stage.capacity
                    and row_gen[pi.staged_row] == pi.staged_gen
                ):
                    rows.append(pi.staged_row)
                    continue
                # stale entry (updated/deleted between enqueue and pop,
                # slab rebuilt, or admitted before the plane attached):
                # re-stage from the CAPTURED pod object — the legacy
                # per-spec encode cost, paid once, then covered again
                stale += 1
                pair = stage.ensure_row(pod)
                if pair is None:
                    self.stats["ingest_stale_rows"] = (
                        self.stats.get("ingest_stale_rows", 0) + stale
                    )
                    return None
                rows.append(pair[0])
                self.stats["ingest_restaged"] = (
                    self.stats.get("ingest_restaged", 0) + 1
                )
            if stale:
                self.stats["ingest_stale_rows"] = (
                    self.stats.get("ingest_stale_rows", 0) + stale
                )
            if stage.generation != gen0:
                return None  # slab rebuilt mid-resolve: rows are garbage
            u = self._u_bucket
            idx = np.zeros(u, np.int32)
            idx[: len(rows)] = rows
            keep = np.zeros(u, bool)
            keep[: len(rows)] = True
            fb = np.zeros(u, bool)
            fb[: len(rows)] = stage.batch.fallback[np.asarray(rows, np.int64)]
            was_sync = bank.stats["sync_rows"]
            bank_dev, empty_dev = bank.current_arrays(sync=True)
            if bank.stats["sync_rows"] != was_sync:
                # rows the background uploader had not shipped yet: the
                # driver flushed them inline — observable, because a drain
                # that pays this every batch has lost the off-thread win
                self.stats["stage_sync_flushes"] = (
                    self.stats.get("stage_sync_flushes", 0) + 1
                )
            # spec captured under the lock too: it names the slab shapes
            # this dispatch's captured bank actually has
            spec = bank.gather_spec(u)
        # gather OUTSIDE the slab lock: the captured device dicts are
        # immutable (functional updates), and an unwarmed rung's inline
        # XLA compile here must not stall informer-thread admissions
        fp = self._fault_plan
        if fp is not None:  # injection site (faults/inject): one attr read
            fp.raise_if("device-raise", "gather-stage")
        known = self.compile_plan.admit(spec)
        t_g = time.perf_counter()
        pa_dev = gather_stage(bank_dev, idx, keep, empty_dev, fb)
        if not known:
            self.compile_plan.note_compiled(
                spec, time.perf_counter() - t_g,
                SOURCE_INLINE if self.compile_plan.warmed else "warmup",
            )
        self.mirror._ship("pods", idx.nbytes + keep.nbytes + fb.nbytes)
        dt_gather = time.perf_counter() - t0
        self.stats["stage_s"] = self.stats.get("stage_s", 0.0) + dt_gather
        M.scheduling_stage_duration.observe(dt_gather, "gather")
        OBS.record("gather", t0, reps=len(reps), stale=stale)
        return pa_dev, fb

    # -- term-bank plane (kubernetes_tpu/terms_plane) ------------------------

    def _term_growth_specs(self) -> List[SolveSpec]:
        """The term gather's headroom set: the next term-bucket rung and
        the doubled term slab (its growth mode on overflow). ONE
        definition shared by warmup and the dispatch-time growth hook so
        warmed and dispatched shapes can never diverge."""
        from ..compile.ladder import next_rung
        from ..terms_plane.stage import MAX_CAPACITY

        out = [self.term_bank.gather_spec(next_rung(self._t_bucket))]
        if self.tstage.capacity * 2 <= MAX_CAPACITY:
            out.append(self.term_bank.gather_spec(
                self._t_bucket, capacity=self.tstage.capacity * 2
            ))
        return out

    # ktpu: hot-path index-only term dispatch prologue: no device→host syncs
    def _term_prologue(self, reps, rep_infos, rep_keys, selectors):
        """Resolve every rep's interned term entry and gather the batch's
        term table from the device-resident term bank (the index-only
        term dispatch). Returns the covered-dispatch dict — the gathered
        `ta` device arrays, the aux arrays rebuilt from the entries'
        cached bits, the present kind ints, topology slots, and the
        overflowing rep indices — or None when the batch cannot be
        covered (a stale entry that cannot re-stage: slab at its ceiling,
        vocab width growth mid-resolve) — the caller then compiles the
        legacy host TermBank, counted. Same locking discipline as
        _stage_prologue: resolve, flush, and gather-ARGUMENT capture run
        under the slab lock; the gather dispatch itself runs after
        release."""
        from ..terms_plane.gather import gather_terms

        ts, bank = self.tstage, self.term_bank
        t0 = time.perf_counter()
        u = self._u_bucket
        self_aff = np.zeros(u, bool)
        has_aff = np.zeros(u, bool)
        has_anti = np.zeros(u, bool)
        n_sel = np.zeros(u, np.int32)
        with ts._lock:
            ts.ensure_current()
            # a slab rebuild DURING resolution (a restage growing a full
            # slab) invalidates the rows already collected — detect by
            # generation and bail to the legacy path
            gen0 = ts.generation
            idx_rows: List[int] = []
            owners: List[int] = []
            kinds: set = set()
            slots: set = set()
            overflow: List[int] = []
            stale = 0
            for b, (pod, pi) in enumerate(zip(reps, rep_infos)):
                entry = (
                    ts.entry_for(pi.term_row, pi.term_gen, rep_keys[b])
                    if pi.pod is pod and pi.term_row >= 0
                    else None
                )
                if entry is None:
                    # stale entry (updated/deleted between enqueue and
                    # pop, slab rebuilt, selector drift, or admitted
                    # before the plane attached): re-intern from the
                    # captured pod + this dispatch's getSelectors result
                    stale += 1
                    sels = selectors.get(id(pod)) if selectors else None
                    pair = ts.ensure_entry(pod, sels)
                    if pair is None:
                        self.stats["term_stale_rows"] = (
                            self.stats.get("term_stale_rows", 0) + stale
                        )
                        return None
                    entry = ts._entries[pair[0]]
                    self.stats["term_restaged"] = (
                        self.stats.get("term_restaged", 0) + 1
                    )
                    # counted here, not on the success path, so the
                    # metric can't undercount restages performed before
                    # a bail (slab ceiling, mid-resolve rebuild); the
                    # registry lock is a leaf — no lock-order edge back
                    M.term_restage.inc()
                idx_rows.extend(entry.rows)
                owners.extend([b] * len(entry.rows))
                kinds |= entry.kinds
                slots |= entry.topo_slots
                if entry.overflow:
                    overflow.append(b)
                self_aff[b] = entry.self_aff_match
                has_aff[b] = entry.has_aff
                has_anti[b] = entry.has_anti
                n_sel[b] = entry.n_sel_spread
            if stale:
                self.stats["term_stale_rows"] = (
                    self.stats.get("term_stale_rows", 0) + stale
                )
            if ts.generation != gen0:
                return None  # slab rebuilt mid-resolve: rows are garbage
            self._t_bucket = max(
                self._t_bucket, _bucket(max(len(idx_rows), 1))
            )
            t = self._t_bucket
            idx = np.zeros(t, np.int32)
            idx[: len(idx_rows)] = idx_rows
            own = np.zeros(t, np.int32)
            own[: len(idx_rows)] = owners
            keep = np.zeros(t, bool)
            keep[: len(idx_rows)] = True
            was_sync = bank.stats["sync_rows"]
            bank_dev, empty_dev = bank.current_arrays(sync=True)
            if bank.stats["sync_rows"] != was_sync:
                self.stats["term_sync_flushes"] = (
                    self.stats.get("term_sync_flushes", 0) + 1
                )
            spec = bank.gather_spec(t)
        # gather OUTSIDE the slab lock: the captured device dicts are
        # immutable (functional updates), and an unwarmed rung's inline
        # XLA compile here must not stall informer-thread admissions
        fp = self._fault_plan
        if fp is not None:  # injection site (faults/inject): one attr read
            fp.raise_if("device-raise", "gather-terms")
        known = self.compile_plan.admit(spec)
        t_g = time.perf_counter()
        ta_dev = gather_terms(bank_dev, idx, own, keep, empty_dev)
        if not known:
            self.compile_plan.note_compiled(
                spec, time.perf_counter() - t_g,
                SOURCE_INLINE if self.compile_plan.warmed else "warmup",
            )
        self.mirror._ship("terms", idx.nbytes + own.nbytes + keep.nbytes)
        dt = time.perf_counter() - t0
        self.stats["term_gather_s"] = self.stats.get("term_gather_s", 0.0) + dt
        M.scheduling_stage_duration.observe(dt, "gather")
        OBS.record("gather", t0, reps=len(reps), stale=stale, plane="terms")
        return dict(
            ta=ta_dev,
            aux={
                "self_aff_match": self_aff,
                "has_aff": has_aff,
                "has_anti": has_anti,
                "n_sel_spread": n_sel,
            },
            kinds=kinds,
            slots=slots,
            overflow=overflow,
        )

    # -- device solve --------------------------------------------------------

    # ktpu: hot-path
    def _device_solve(self, infos: List[PodInfo]) -> SolveOutput:
        return self._finish_solve(self._dispatch_solve(infos))

    # ktpu: hot-path the covered dispatch: results are fetched ONLY by
    # _finish_solve (the designated sync point)
    def _dispatch_solve(
        self, infos: List[PodInfo], carry=None, allow_rebuild: bool = True
    ) -> Dict:
        """Encode + dispatch the device solve WITHOUT fetching the result.
        `carry` is the previous batch's device residual tuple (speculative
        pipelining); with it, the solve runs against the device's own
        post-previous-batch state instead of the mirror's columns.
        `allow_rebuild=False` (speculative dispatch) re-raises encoding
        overflows instead of rebuilding: a rebuild remaps node rows while
        the CURRENT batch's assignment (row-indexed) is still being
        committed."""
        import jax

        from ..ops import filters as F
        from ..ops.pipeline import solve_pipeline

        t0 = time.perf_counter()
        pods = [pi.pod for pi in infos]
        vocab = self.mirror.vocab
        self._b_bucket = max(self._b_bucket, _bucket(len(pods)))
        custom_sort = getattr(self.queue, "_less", None) is not None
        selectors = None
        if self._spread_selectors_fn is not None:
            selectors = {id(p): self._spread_selectors_fn(p) for p in pods}
        # collapse the batch to unique pod SPECS: replicas of one controller
        # share a single row of every [U, N] mask/score matrix (the batch-
        # side counterpart of SigBank's existing-pod signatures) — the
        # device work scales with distinct specs, not batch size
        sig_list: List[int] = []
        reps: List[Pod] = []
        rep_infos: List[PodInfo] = []  # first queue entry of each spec
        rep_keys: List[tuple] = []  # the dedup key doubles as the term-
        # slab intern key, so entry validity is an equality check
        spec_index: Dict[str, int] = {}
        for pi in infos:
            p = pi.pod
            k = _spec_key(p, selectors.get(id(p)) if selectors else None)
            u = spec_index.get(k)
            if u is None:
                u = len(reps)
                spec_index[k] = u
                reps.append(p)
                rep_infos.append(pi)
                rep_keys.append(k)
            sig_list.append(u)
        self._u_bucket = max(self._u_bucket, _bucket(len(reps)))
        while True:
            try:
                # INGEST PLANE covered path: every rep resolves to a valid
                # staged row → the pod arrays are gathered from the
                # device-resident staged bank; the dispatch ships only the
                # index vector (+ tiny control arrays). Stale/unstageable
                # reps fall back to the legacy host-built PodBatch, counted.
                batch = None
                pa_dev = None
                staged = None
                if self.ingest_plane and self.stage is not None and (
                    self.faults.quiet or self.faults.ok("ingest")
                ):
                    try:
                        staged = self._stage_prologue(reps, rep_infos)
                    except KeySlotOverflow:
                        raise  # vocab growth: the outer rebuild loop owns it
                    except Exception as e:
                        # runtime plane fault: report to the breaker and
                        # take the legacy host-built PodBatch for this
                        # batch (bit-identical by the ON==OFF contract)
                        self._report_fault("ingest", type(e).__name__)
                        self.stats["ingest_fault_batches"] = (
                            self.stats.get("ingest_fault_batches", 0) + 1
                        )
                        staged = None
                if staged is not None:
                    pa_dev, fallback_arr = staged
                else:
                    batch = PodBatch(vocab, self._u_bucket)
                    for i, p in enumerate(reps):
                        batch.set_pod(i, p)
                    fallback_arr = batch.fallback
                # TERM PLANE covered path: every rep resolves to a live
                # interned term entry → the batch term table is gathered
                # from the device-resident term bank; the dispatch ships
                # only int32 index/owner vectors (+ the [U] aux bits).
                # Stale/unstageable entries fall back to the legacy host
                # compile_batch_terms build, counted. The covered path
                # never encodes terms host-side, so neither the
                # KeySlotOverflow→mirror-rebuild loop nor the old
                # compile-then-recompile-at-the-monotone-bucket retry
                # exists on it.
                tb = None
                tp = None
                if self.term_plane and self.tstage is not None and (
                    self.faults.quiet or self.faults.ok("terms")
                ):
                    try:
                        tp = self._term_prologue(
                            reps, rep_infos, rep_keys, selectors
                        )
                    except KeySlotOverflow:
                        raise
                    except Exception as e:
                        self._report_fault("terms", type(e).__name__)
                        self.stats["term_fault_batches"] = (
                            self.stats.get("term_fault_batches", 0) + 1
                        )
                        tp = None
                if tp is not None:
                    ta_arrays, aux = tp["ta"], tp["aux"]
                else:
                    # size the monotone term bucket BEFORE compiling —
                    # one compile at the final capacity (this retired the
                    # double-compile retry that rebuilt the whole bank
                    # whenever the natural bucket undershot the monotone
                    # one)
                    self._t_bucket = max(self._t_bucket, _bucket(
                        max(count_batch_terms(reps, selectors), 1)
                    ))
                    tb, aux = compile_batch_terms(
                        vocab, reps, spread_selectors=selectors,
                        capacity=self._t_bucket, b_capacity=self._u_bucket,
                    )
                    # no-op when the count was exact; self-heals the
                    # monotone bucket if compile_batch_terms clamped up
                    self._t_bucket = max(self._t_bucket, tb.capacity)
                    ta_arrays = tb.arrays()
                break
            except KeySlotOverflow:
                if not allow_rebuild:
                    raise
                self.mirror._rebuild()

        # the per-POD axis: spec row, validity, queue priority. With a
        # QueueSort plugin the comparator ordered the pop — neutralize the
        # priority key (zeros) so pop_order falls back to the enqueue (= pop)
        # sequence
        pb = {
            "sig": np.zeros(self._b_bucket, np.int32),
            "valid": np.zeros(self._b_bucket, bool),
            "priority": np.zeros(self._b_bucket, np.int32),
        }
        pb["sig"][: len(pods)] = sig_list
        pb["valid"][: len(pods)] = True
        if not custom_sort:
            pb["priority"][: len(pods)] = [p.get_priority() for p in pods]

        # term-table overflow: truncated/dropped terms under- or over-match on
        # device — route the affected pods through the scalar oracle instead
        # (ADVICE r1: overflow_owners was recorded but never consumed).
        # On the covered ingest path this patches only the HOST fallback
        # vector (the device copy of `fallback` is consumed by no kernel —
        # it rides the dict for signature stability). The covered term
        # path carries the same flag per interned entry (TermEntry.
        # overflow), already resolved to rep indices.
        for owner in (tp["overflow"] if tp is not None else tb.overflow_owners):
            if 0 <= owner < len(reps):
                fallback_arr[owner] = True
        existing_overflow = bool(self.mirror.pats.overflow_rows)
        # pod-side wire ledger (patch_bytes.pods): what THIS dispatch ships
        # for its pod arrays — the full padded PodBatch on the legacy path,
        # the index/control vectors on the covered path (KB-scale). The
        # [B]-axis pb control arrays below ship on both.
        pa_arrays = pa_dev if pa_dev is not None else batch.arrays()
        if pa_dev is None:
            self.mirror._ship(
                "pods",
                sum(int(np.asarray(v).nbytes) for v in pa_arrays.values()),
            )
            if self.ingest_plane:
                # only a plane that COULD have covered counts as legacy —
                # a plane-off run must not read like a regressed fallback
                self.stats["ingest_legacy_batches"] = (
                    self.stats.get("ingest_legacy_batches", 0) + 1
                )
            M.ingest_batches.inc("legacy" if self.ingest_plane else "off")
        else:
            self.stats["ingest_index_batches"] = (
                self.stats.get("ingest_index_batches", 0) + 1
            )
            M.ingest_batches.inc("index")
        # term-side wire ledger (patch_bytes.terms): the full padded term
        # table on the legacy path, the index/owner/keep vectors on the
        # covered path (shipped in the prologue); the [U] aux bits ship
        # on both
        if tb is not None:
            self.mirror._ship(
                "terms",
                sum(int(np.asarray(v).nbytes) for v in ta_arrays.values()),
            )
            if self.term_plane:
                self.stats["term_legacy_batches"] = (
                    self.stats.get("term_legacy_batches", 0) + 1
                )
            M.term_batches.inc("legacy" if self.term_plane else "off")
        else:
            self.stats["term_index_batches"] = (
                self.stats.get("term_index_batches", 0) + 1
            )
            M.term_batches.inc("index")
        self.mirror._ship("terms", sum(int(a.nbytes) for a in aux.values()))
        self.mirror._ship("pods", sum(int(a.nbytes) for a in pb.values()))
        t1 = time.perf_counter()
        self.stats["encode_s"] += t1 - t0
        M.scheduling_stage_duration.observe(t1 - t0, "encode")

        if self._ids is None:
            self._ids = F.make_ids(vocab)  # interned constants; stable
        ids = self._ids
        self._cycle += 1
        key = jax.random.PRNGKey(self._rng_seed + self._cycle)
        # device-RESIDENT banks patched by dirty rows (TensorMirror
        # .device_arrays) — per batch only the pod batch, the batch term
        # tables, and the dirty row slices cross the host→device wire
        # term kinds seen so far (jit statics): batches without a kind never
        # execute — or compile — that kind's kernels. MONOTONE union across
        # batches, not the exact per-batch set: a fluctuating workload would
        # otherwise compile up to 2^8 variants, while the union costs at
        # most 8 growth compiles and a superset program is still exact
        # (extra kernels compute their term-absent identities)
        if tp is not None:
            present_kinds = _term_kind_names(
                tp["kinds"], bool(np.any(aux["n_sel_spread"] > 0)),
                self.mirror.pats,
            )
        else:
            present_kinds = _present_term_kinds(tb, self.mirror.pats, aux)
        self._term_kinds = getattr(self, "_term_kinds", frozenset()) | present_kinds
        term_kinds = self._term_kinds
        # topology segment-axis bound (jit static): only the slots named by
        # CURRENT terms matter — zone-keyed terms need ~#zones buckets while
        # a [*, N] table wastes 1000x at 10k nodes (hostname-keyed terms
        # genuinely need ~N and get it). MONOTONE bucket to avoid recompiles.
        # The covered term path reads the interned entries' cached slot
        # sets instead of scanning a host bank.
        pats = self.mirror.pats
        term_slots = (
            set(tp["slots"]) if tp is not None
            else set(np.asarray(tb.topo_slot[tb.valid], np.int64).tolist())
        ) | set(np.asarray(pats.bank.topo_slot[pats.valid], np.int64).tolist())
        needed = [vocab.dense_size(int(sl)) for sl in term_slots if sl >= 0]
        needed.append(vocab.zone_count())  # selector-spread zone blending
        # NOT clamped to node capacity: dense ids are grow-only, so under
        # node churn live dense indices can exceed the live node count —
        # clamping would silently drop those nodes from the segment sums
        self._v_bucket = max(
            getattr(self, "_v_bucket", 16), _bucket(max(needed + [1]))
        )
        n_buckets = self._v_bucket
        na_dev, ea_dev, xp_dev = self.mirror.device_arrays()
        # fold OUT-OF-BATCH nominations into the mask's usage columns
        # (in-batch nominees are sequentialized by the solver's own carry;
        # chained speculative solves inherit the fold through their free
        # residuals). nomination_adds is recorded so consumers can tell
        # whether new nominations appeared after this dispatch.
        nom_adds = self.queue.nomination_adds
        if self.queue.has_nominations() and carry is None:
            # (with a carry, apply_carry REPLACES the usage columns with
            # the chained residuals — which already inherit the previous
            # dispatch's nominee fold — so overlaying na_dev would be
            # dead work: skip it entirely)
            from ..state.tensors import _req_slot_pairs

            extras = self.queue.nomination_extras({p.key() for p in pods})
            width = int(na_dev["requested"].shape[1])
            rows: List[int] = []
            vecs: List[np.ndarray] = []
            for node, npod in extras:
                row = self.mirror.row_of.get(node)
                if row is None:
                    continue
                vec = np.zeros(width, np.int64)
                ok = True
                for s, v in _req_slot_pairs(self.mirror.vocab, npod):
                    if s >= width:
                        ok = False  # exotic-slot overflow: skip (rare; the
                        break  # pod itself routes via fallback when popped)
                    vec[s] = v
                if ok:
                    rows.append(row)
                    vecs.append(vec)
            if rows and self.fold_plane and self.mirror.can_fold():
                # donated in-place overlay (ops/fold.fold_usage), restored
                # by the exact integer inverse after the dispatches below
                # — the old path copied the ENTIRE node-bank dict per
                # dispatch (XLA copies every passed-through array when
                # nothing is donated). Monotone rung + plan admission so
                # it stops showing up as an unplanned signature.
                self._nom_bucket = max(self._nom_bucket, _bucket(len(rows)))
                nb = self._nom_bucket
                pad = nb - len(rows)
                n_cap = self.mirror.nodes.capacity
                nspec = self._fold_spec(nominee=True)
                nknown = self.compile_plan.admit(nspec)
                t_nf = time.perf_counter()
                na_dev = self.mirror.fold_nominees(
                    np.asarray(rows + [n_cap] * pad, np.int32),
                    np.asarray(vecs + [np.zeros(width, np.int64)] * pad),
                    np.asarray([1] * len(rows) + [0] * pad, np.int32),
                )
                if not nknown:
                    self.compile_plan.note_compiled(
                        nspec, time.perf_counter() - t_nf,
                        SOURCE_INLINE if self.compile_plan.warmed else "warmup",
                    )
            elif rows:
                # fallback overlay (sharded/stale banks, plane off): the
                # legacy whole-dict copy
                nb = _bucket(len(rows))
                pad = nb - len(rows)
                na_dev = _nominee_fold_fn()(
                    na_dev,
                    np.asarray(rows + [rows[0]] * pad, np.int32),
                    np.asarray(vecs + [np.zeros(width, np.int64)] * pad),
                    np.asarray([1] * len(rows) + [0] * pad, np.int32),
                )
        # tiny clusters on big meshes: capacity buckets guarantee shard
        # divisibility only once capacity >= shard count — fall back to the
        # single-device pipeline instead of asserting on every batch.
        # ONE predicate (_shards_now) decides routing AND spec identity:
        # na_dev's node axis is the mirror's capacity by construction
        use_sharded = self._shards_now() > 0
        if self._sharded is not None and not use_sharded:
            # the fallback is LEGAL but must be observable: the replicated
            # solve is a different XLA program (an unwarmed inline compile
            # on a production mesh) and the whole multi-chip plane sits
            # idle while it persists — a regression here used to be
            # completely silent
            self.stats["sharded_fallbacks"] = (
                self.stats.get("sharded_fallbacks", 0) + 1
            )
            M.sharded_fallbacks.inc("indivisible")
            if not self._sharded_fallback_logged:
                self._sharded_fallback_logged = True
                import logging

                logging.getLogger("kubernetes_tpu.scheduler").warning(
                    "sharded solve FALLBACK: node capacity %d not divisible "
                    "by %d mesh shards — dispatching the replicated "
                    "pipeline until the bucket grows",
                    self.mirror.nodes.capacity, self._mesh_shards,
                )
        elif use_sharded:
            self._sharded_fallback_logged = False
        t_patch = time.perf_counter()
        self.stats["patch_s"] = self.stats.get("patch_s", 0.0) + (t_patch - t1)
        args = (
            na_dev,
            pa_arrays,
            ea_dev,
            ta_arrays,  # host-compiled TermBank dict, or the device gather
            xp_dev,
            aux,
            ids,
            key,
        )
        # gang/co-scheduling: group-annotated pods go through the
        # all-or-nothing two-pass solve (ops/solver.solve_gang)
        group_names = [pod_group_name(p) for p in pods]
        gang_dev = None
        carry_out = None
        is_gang = any(group_names)
        if not is_gang:
            # monotone jit-static: once a batch carries required
            # anti-affinity or host ports, keep the in-batch tracking
            # variant (a superset program is exact without those features)
            self._track_inbatch = self._track_inbatch or (
                "anti_req" in term_kinds
                or any(p.host_ports() for p in reps)
            )
        # route this dispatch through the compile plan: admit its full XLA
        # program signature (shape-ladder rungs + jit statics). A miss
        # after warmup is the stall this subsystem exists to kill — it is
        # counted, logged, and still compiled inline (correctness first).
        solve_spec = self._solve_spec(gang=is_gang, with_carry=carry is not None)
        spec_known = self.compile_plan.admit(solve_spec)
        fault_plan = self._fault_plan
        if fault_plan is not None:  # injection site: one attribute read
            fault_plan.raise_if("device-raise", "solve")
        t_spec = time.perf_counter()
        if is_gang:
            from ..ops.pipeline import solve_pipeline_gang

            gid_map: Dict[str, int] = {}
            garr = np.full(self._b_bucket, -1, np.int32)
            for i, gn in enumerate(group_names):
                if gn:
                    garr[i] = gid_map.setdefault(gn, len(gid_map))
            gang_fn = self._sharded.gang if use_sharded else solve_pipeline_gang
            assign, score, gang_ok, carry_out = gang_fn(
                *args, garr, pb=pb, carry=carry,
                deterministic=self.deterministic,
                config=self.solve_config, term_kinds=term_kinds,
                n_buckets=n_buckets, return_carry=True,
            )
            gang_dev = gang_ok
        else:
            t_d = time.perf_counter()
            if use_sharded:
                # same in-batch anti/port sequentialization as the
                # single-device path: commit counts replicate, the winning
                # node's topology bucket is broadcast from its owner shard
                assign, score, carry_out = self._sharded(
                    *args, pb=pb, carry=carry, deterministic=self.deterministic,
                    config=self.solve_config, term_kinds=term_kinds,
                    n_buckets=n_buckets, return_carry=True,
                    track_inbatch=self._track_inbatch,
                )
            else:
                assign, score, carry_out = solve_pipeline(
                    *args, pb=pb, carry=carry, deterministic=self.deterministic,
                    config=self.solve_config, term_kinds=term_kinds,
                    n_buckets=n_buckets, return_carry=True,
                    track_inbatch=self._track_inbatch,
                )
            # dispatch_s = host upload + trace-cache lookup + enqueue (async)
            self.stats["dispatch_s"] = self.stats.get("dispatch_s", 0.0) + (
                time.perf_counter() - t_d
            )
        if not spec_known:
            # attribute this dispatch's wall (trace + compile + enqueue; the
            # device executes async) to the spec — the compile-stall upper
            # bound the telemetry reports
            self.compile_plan.note_compiled(
                solve_spec,
                time.perf_counter() - t_spec,
                SOURCE_INLINE if self.compile_plan.warmed else "warmup",
            )
        # COMMIT ARBITER dispatch: chained on the solve's assignment ON
        # DEVICE (async, results fetched with the assign), replaying the
        # batch in pop order against tracked in-batch state so the host
        # commit loop gets per-pod place/defer verdicts instead of doing
        # per-pod rechecks itself. On a mesh the verdict scan runs through
        # the shard_map'd twin (parallel.sharded pipeline.arbitrate) over
        # the same node-sharded banks and carry the solve used. Skipped
        # for batches the verdicts could never be used on (gang,
        # uncovered term kinds).
        verdict_dev = None
        levels_arr = np.array([_recheck_level(r) for r in reps], np.int8)
        if (
            self.commit_plane
            and not is_gang
            and kinds_covered(present_kinds)
            # pure RECHECK_NONE batches are the bulk fast path's domain —
            # verdicts would go unused, so don't spend device time on them
            and bool((levels_arr != RECHECK_NONE).any())
            # a deployment whose plugins/extenders/volumes force the legacy
            # loop must not pay the verdict scan at all
            and self._commit_plane_statics_ok()
            # commit breaker open: the legacy scalar loop is the route —
            # don't pay the verdict scan for verdicts that won't be used
            and (self.faults.quiet or self.faults.ok("commit"))
        ):
            from ..commit.arbiter import arbitrate

            arb_fn = self._sharded.arbitrate if use_sharded else arbitrate
            arb_spec = self._arbiter_spec(with_carry=carry is not None)
            arb_known = self.compile_plan.admit(arb_spec)
            t_arb = time.perf_counter()
            try:
                if fault_plan is not None:  # injection site
                    fault_plan.raise_if("device-raise", "arbiter")
                verdict_dev = arb_fn(
                    na_dev, pa_arrays, ea_dev, ta_arrays, ids,
                    assign, pb=pb, carry=carry,
                    term_kinds=term_kinds, n_buckets=n_buckets,
                )
            except Exception as e:
                # arbiter dispatch fault: the scalar commit loop covers
                # this batch (verdicts are an optimization, not truth)
                self._report_fault("commit", type(e).__name__)
                verdict_dev = None
            self.stats["arbiter_dispatch_s"] = self.stats.get(
                "arbiter_dispatch_s", 0.0
            ) + (time.perf_counter() - t_arb)
            if not arb_known and verdict_dev is not None:
                self.compile_plan.note_compiled(
                    arb_spec,
                    time.perf_counter() - t_arb,
                    SOURCE_INLINE if self.compile_plan.warmed else "warmup",
                )
        # the nominee overlay's job ends with the dispatches above: fold
        # it back out (exact integer inverse, donated both ways) so the
        # resident banks return to mirroring the host before any commit
        # fold or row patch lands on them
        self.mirror._restore_nominees()
        self._compile_growth_hook(solve_spec, (na_dev, ea_dev, xp_dev))
        self.stats["batch_specs"] = self.stats.get("batch_specs", 0) + len(reps)
        self.stats["solve_s"] += time.perf_counter() - t1
        M.scheduling_stage_duration.observe(time.perf_counter() - t1, "dispatch")
        # flight recorder: the host-side dispatch span, plus the two-phase
        # DEVICE spans — the dispatched handles are parked (non-forcing,
        # KTPU004) and their end stamps land at _finish_solve's fetch or
        # via the allowlisted resolver. Rung args make a 100k-pod drain's
        # timeline filterable by batch shape.
        tok_solve = tok_arb = 0
        if OBS.enabled:
            # from t1, matching the stage="dispatch" histogram above —
            # t0→t1 is the encode wall (its own stage), carried as an arg
            OBS.record(
                "dispatch", t1, cycle=self._cycle, pods=len(pods),
                reps=len(reps), rung_b=self._b_bucket, rung_u=self._u_bucket,
                speculative=carry is not None, gang=is_gang,
                path="index" if pa_dev is not None else "legacy",
                term_path="index" if tp is not None else "legacy",
                encode_s=round(t1 - t0, 6),
            )
            tok_solve = OBS.device_begin(
                "solve", assign, cycle=self._cycle, pods=len(pods),
                rung_b=self._b_bucket, gang=is_gang,
                speculative=carry is not None,
            )
            if verdict_dev is not None:
                tok_arb = OBS.device_begin(
                    "arbitrate", verdict_dev, cycle=self._cycle,
                    pods=len(pods),
                )
        return dict(
            obs_tokens=(tok_solve, tok_arb),
            infos=infos,
            pods=pods,
            batch=batch,  # None on the covered ingest path
            fallback_arr=fallback_arr,
            aux=aux,
            levels=levels_arr,
            sig_arr=np.asarray(sig_list, np.int32),
            assign_dev=assign,
            score_dev=score,
            gang_dev=gang_dev,
            carry_dev=carry_out,
            existing_overflow=existing_overflow,
            speculative=carry is not None,
            tracked=self._track_inbatch and gang_dev is None,
            nom_adds=nom_adds,
            verdict_dev=verdict_dev,
            present_kinds=present_kinds,
        )

    def _finish_solve(self, disp: Dict) -> SolveOutput:
        """Fetch the dispatched solve's assignment and build SolveOutput."""
        import jax

        t0 = time.perf_counter()
        pods = disp["pods"]
        n = len(pods)
        sig_arr = disp["sig_arr"]
        gang_ok_arr = None
        verdicts = None
        if disp["gang_dev"] is not None:
            assign, gang_ok = jax.device_get((disp["assign_dev"], disp["gang_dev"]))
            gang_ok_arr = np.asarray(gang_ok)[:n]
        elif disp.get("verdict_dev") is not None:
            # the arbiter's verdicts ride the same fetch as the assignment
            assign, verd = jax.device_get(
                (disp["assign_dev"], disp["verdict_dev"])
            )
            verdicts = np.asarray(verd)[:n]
        else:
            # fetch_s = device execution + the [B] assign download
            assign = jax.device_get(disp["assign_dev"])
        dt = time.perf_counter() - t0
        self.stats["fetch_s"] = self.stats.get("fetch_s", 0.0) + dt
        self.stats["solve_s"] += dt
        M.scheduling_stage_duration.observe(dt, "fetch")
        if OBS.enabled:
            # the device_get above IS the designated sync point: the solve
            # (and chained arbiter) programs are complete — stamping their
            # two-phase device spans now is non-forcing and exact to
            # within this fetch's wall
            tok_solve, tok_arb = disp.get("obs_tokens", (0, 0))
            OBS.device_end(tok_solve)
            OBS.device_end(tok_arb)
            OBS.record("fetch", t0, pods=n)
        return SolveOutput(
            assign=np.asarray(assign)[:n],
            fallback=np.asarray(disp["fallback_arr"])[sig_arr],
            score=ScoreRows(disp["score_dev"], sig_arr),
            has_anti=np.asarray(disp["aux"]["has_anti"])[sig_arr],
            existing_overflow=disp["existing_overflow"],
            node_fallback_any=bool((self.mirror.nodes.fallback & self.mirror.nodes.valid).any()),
            gang_ok=gang_ok_arr,
            speculative=disp["speculative"],
            levels=disp["levels"][sig_arr],
            inbatch_tracked=disp.get("tracked", False),
            nom_adds=disp.get("nom_adds", -1),
            verdicts=verdicts,
            present_kinds=disp.get("present_kinds", frozenset()),
        )

    # ktpu: thread-entry(driver) whichever thread warms and drives this
    # scheduler IS the driver role (bench loop, supervisor, __main__)
    def warmup(self, max_pods: Optional[int] = None) -> int:
        """Pre-pay the one-time device costs BEFORE the first scheduling
        cycle: trace + XLA compile (or persistent-cache load) of the solve
        programs at the real workload's bucket shapes and term kinds, and
        the full device-bank upload (device_arrays' stale path — tens of MB
        on a remote-attached chip). Uses PEEKED queue entries, so nothing
        is popped, committed, or mutated; the solve result is discarded.
        Dispatches twice: the carry-less first-batch program AND the
        carry-chained speculative variant (different jit signatures).

        Beyond the live-peek dispatch, this is where the AOT compile plan
        arms: the persisted ladder (a previous process's declared specs)
        re-compiles against the XLA persistent cache, the device
        preemption kernel warms when preemption is enabled, headroom specs
        (one growth rung ahead on each mid-drain-growable axis) queue on
        the background warmup worker, and the plan is marked warmed — any
        later spec miss is counted and logged as a drain stall.

        The scheduler_perf-equivalent harness calls this in setup so e2e
        measures scheduling, not compilation — the production analogue is
        a scheduler warming its executables at boot before Run().
        Returns the number of pods warmed with (0 = empty queue or a
        warmup failure, both harmless)."""
        register_thread_role("driver")
        infos = self.queue.peek_batch(max_pods or self.batch_size)
        saved = dict(self.stats)
        plan = self.compile_plan
        t_warm = time.perf_counter()
        try:
            # FULL-QUEUE census (not just the peeked batch): pre-size the
            # signature/pattern banks for the whole backlog and stage any
            # entries admitted before the ingest plane attached — both
            # one-pass setup costs that kill mid-drain rebuild stalls
            self._warmup_census()
            self.mirror.sync()
            if plan.cache is not None:
                plan.cache.enable_xla_cache()
            if self._warm_svc is None:
                self._warm_svc = WarmupService(self, plan)
            # restart path: the persisted ladder re-warms first — each spec
            # is trace-only cost when the XLA persistent cache holds its
            # artifact (the >=5x warm-vs-cold win the bench asserts)
            persisted = plan.load_persisted()
            if persisted:
                dev = self.mirror.device_arrays()
                self._warm_svc.warm_specs(persisted, dev=dev, source=SOURCE_PERSISTED)
            if infos:
                # PREDICTIVE KIND ADOPTION: committing an (anti-)affinity
                # pod turns it into an existing-pod PATTERN, so the very
                # first commit grows the term-kind union (et_anti /
                # et_score) and the second batch would pay an inline
                # compile mid-drain. Seed the union with the post-commit
                # kinds of the peeked workload BEFORE dispatching, so
                # warmup compiles the superset program once (superset
                # programs are exact — absent kinds compute identities).
                kinds = set()
                for pi in infos:
                    a = pi.pod.affinity
                    if a is None:
                        continue
                    if a.pod_anti_affinity is not None:
                        if a.pod_anti_affinity.required:
                            kinds |= {"anti_req", "et_anti"}
                        if a.pod_anti_affinity.preferred:
                            kinds |= {"pref", "et_score"}
                    if a.pod_affinity is not None:
                        if a.pod_affinity.required:
                            kinds |= {"aff_req", "et_score"}
                        if a.pod_affinity.preferred:
                            kinds |= {"pref", "et_score"}
                self._term_kinds = (
                    getattr(self, "_term_kinds", frozenset()) | frozenset(kinds)
                )
                disp = self._dispatch_solve(infos)
                self._finish_solve(disp)
                if self.speculate:
                    disp2 = self._dispatch_solve(
                        infos, carry=disp["carry_dev"], allow_rebuild=False
                    )
                    self._finish_solve(disp2)
                if any(pod_group_name(pi.pod) for pi in infos):
                    # gang-flavored peek: the live dispatch above warmed
                    # ONLY the solve_gang variant — the plain variant is
                    # a distinct XLA signature, and a mixed queue's first
                    # non-gang batch would pay it inline (seen on restart
                    # reconciliation, where a gang relists into the
                    # warmup peek and the dead process's ladder never
                    # persisted). Foreground-warm the plain base too.
                    self._warm_svc.warm_specs(
                        [self._solve_spec(gang=False, with_carry=wc)
                         for wc in ((False, True) if self.speculate
                                    else (False,))],
                        dev=None if self.fold_plane
                        else self.mirror.device_arrays(),
                    )
            if self.enable_preemption:
                # pin the preemptor-axis bucket so every device preemption
                # round shares ONE signature (padded scan steps are cheap;
                # the per-distinct-fails-count compiles were not), then
                # warm it so the first failed batch doesn't pay the compile
                self._p_bucket = max(self._p_bucket, _bucket(self.batch_size, 8))
                self._warm_svc.warm_specs([self._preempt_spec()])
            if self.fold_plane:
                # resident-state fold programs at the live bank shapes
                # (foreground, synthetic zero banks — the live banks must
                # never be donated into a warm). The commit variant rides
                # the solve's batch rung; the nominee-overlay variant is
                # warmed across its pow-2 rungs up to 4x batch size, since
                # outstanding nominations accumulate across batches and
                # each rung is a trivially cheap two-scatter program.
                # the nominee variant warms regardless of preemption:
                # nominations can also arrive from the informer (a pod
                # with nominatedNodeName left by a prior incarnation), and
                # an unwarmed rung is a mid-drain inline compile
                from dataclasses import replace

                # PREDICTIVE pattern-triple rung: an affinity-heavy first
                # batch interns one triple per (pod, term pattern) pair on
                # its FIRST commit — more than the default 16-rung when
                # most pods carry terms — and the async growth warm loses
                # that race. Size the rung from the peeked batch's own
                # patterns (the predictive-kind-adoption idea applied to
                # the fold's t axis) so the foreground warm below compiles
                # the program the first commit will actually dispatch.
                if infos:
                    triples = sum(
                        len(self.mirror.pats._pod_patterns(pi.pod))
                        for pi in infos
                    )
                    if triples:
                        self._fp_bucket = max(self._fp_bucket, _bucket(triples))
                fold_specs = [self._fold_spec()]
                nom = self._fold_spec(nominee=True)
                b, cap = 16, _bucket(self.batch_size * 4)
                while b <= cap:
                    fold_specs.append(replace(nom, b=b))
                    b *= 2
                self._warm_svc.warm_specs(fold_specs)
            # dirty-row scatter programs (KIND_PATCH): every bank
            # structure x row rung the mirror can ship, pre-compiled by
            # idempotent no-op patches. Post-warmup patches — commit usage
            # rows, preemption victim deletions, node churn — land on hot
            # programs; before this, the first patch at each fresh rung
            # was an inline XLA compile billed to the DRAIN (the
            # preemption bench's cycle-2 "solve" spike was exactly these).
            self.mirror.warm_patches()
            if self.ingest_plane and self.stage_bank is not None:
                # staged-pod-bank programs: the row-scatter rungs (no-op
                # patches, the warm_patches discipline) plus the index-
                # gather prologue at the live AND headroom shapes (the
                # same _stage_growth_specs the dispatch-time growth hook
                # warms) so mid-drain growth lands on hot programs. The
                # background uploader arms here — tests that never warm
                # get no surprise threads.
                self.stage_bank.start()
                self.stage_bank.warm()
                self._warm_svc.warm_specs(
                    [self.stage_bank.gather_spec(self._u_bucket)]
                    + self._stage_growth_specs()
                )
            if self.term_plane and self.term_bank is not None:
                # term-bank programs, the same discipline: the row-
                # scatter rungs (no-op patches) plus the term index-
                # gather at the live AND headroom shapes (next term rung,
                # doubled slab); the off-thread uploader arms here
                self.term_bank.start()
                self.term_bank.warm()
                self._warm_svc.warm_specs(
                    [self.term_bank.gather_spec(self._t_bucket)]
                    + self._term_growth_specs()
                )
            if infos:
                # headroom: compile the next growth rung of each mid-drain-
                # growable axis in the background while the drain starts —
                # both carry variants (fresh solve + speculative chain).
                # The commit arbiter grows in lockstep (its live-shape
                # programs were warmed by the peeked dispatches above).
                dev = None if self.fold_plane else self.mirror.device_arrays()
                for wc in ((False, True) if self.speculate else (False,)):
                    spec = self._solve_spec(gang=False, with_carry=wc)
                    specs = plan.ladder.growth_specs(spec)
                    if self.commit_plane:
                        specs = specs + plan.ladder.growth_specs(
                            self._arbiter_spec(wc)
                        )
                    self._warm_svc.warm_async(specs, dev)
                if self.fold_plane:
                    self._warm_svc.warm_async(
                        plan.ladder.growth_specs(self._fold_spec()), None
                    )
            plan.mark_warmed()
            plan.persist()
            self._aot_enabled = True
            if self.health is not None:
                # warm banks are resident now: refresh the published
                # mirror census (still the driver thread) and arm the
                # monitor thread — like the uploaders, it starts at
                # warmup so tests that never warm get no surprise thread
                self.health.publish("mirror", self.mirror.census())
                self.health.start()
        except Exception:
            # a failed warmup is harmless for correctness but must be
            # VISIBLE: the first real batch will silently pay the compile
            # otherwise, skewing any timing built on top
            import sys
            import traceback

            print("[scheduler] warmup failed:", file=sys.stderr)
            traceback.print_exc()
            return 0
        finally:
            # warmup time is setup time: keep the per-phase accumulators
            # about real scheduling work only
            self.stats = saved
            OBS.record("warmup", t_warm, pods=len(infos))
        return len(infos)

    def _warmup_census(self) -> None:
        """Walk the FULL pending queue (active + backoff + unschedulable,
        not just the peeked batch) and (a) pre-size the signature/pattern
        banks so committing the backlog cannot overflow them mid-drain —
        the gang bench's `mirror_rebuilds: 1` root cause was exactly this:
        1k distinct gang label sets interning into a 256-slot SigBank as
        commits landed, overflowing at pod ~256·64 and forcing a rebuild +
        solve recompile mid-drain — and (b) stage every entry the ingest
        plane will pop (entries enqueued before the plane attached, e.g. a
        pre-loaded bench queue, stage here instead of on the drain's
        critical path). One pass of memoized key builds: setup cost."""
        infos = self.queue.pending_infos()
        if not infos:
            return
        # sizing lives with the banks (TensorMirror.census_reserve — it
        # mirrors SigBank/PatternBank's own interning identity)
        self.mirror.census_reserve(info.pod for info in infos)
        if self.stage is not None:
            # staging under the QUEUE lock (queue.stage_pending): an
            # unlocked acquire here would race the informer's delete/
            # update release+acquire pairs and pin orphaned slab rows
            self.queue.stage_pending()

    def _pod_meta(self, pod: Pod):
        """Predicate metadata for the oracle paths, backed by a per-batch
        SnapshotAffinityIndex (the pod-independent halves built once, not
        per pod) plus this batch's commits replayed exactly. Invalidated
        (index=None) whenever the snapshot changes in ways the extras list
        does not capture — preemption deletes, gang rollbacks."""
        from ..oracle.predicates import SnapshotAffinityIndex

        if self._aff_index is None:
            self._aff_index = SnapshotAffinityIndex(self.cache.snapshot)
            self._aff_extra = []
        return compute_predicate_metadata(
            pod,
            self.cache.snapshot,
            enabled=self._enabled_preds,
            affinity_index=self._aff_index,
            affinity_extra=self._aff_extra,
        )

    def _pod_extenders(self, pod: Pod) -> List:
        """Extenders interested in this pod (IsInterested,
        core/extender.go:450)."""
        return [e for e in self.extenders if e.is_interested(pod)]

    def _intra_batch_conflict(
        self,
        pod: Pod,
        node_name: str,
        index: "_BatchConflictIndex",
        prior: Optional[List["_BatchConflictIndex"]] = None,
    ) -> bool:
        """Can an earlier commit of THIS batch invalidate pod→node_name?
        The cheap replacement for the full oracle pass (which is O(cluster)
        per pod): the device mask already validated everything against the
        pre-batch snapshot bit-for-bit, so only batch commits can break a
        LIGHT-level pod — host-port collisions on the node (commits are
        assumed into the live NodeInfo) and required anti-affinity in
        either direction (satisfiesExistingPodsAntiAffinity semantics,
        predicates.go:1284: both nodes must carry the topology key with
        equal values). Commits are indexed by (topology key, value), so
        each check touches only same-topology candidates instead of every
        commit × term."""
        ni = self.cache.snapshot.get(node_name)
        if ni is None:
            return True
        if pod.host_ports() and ni.host_port_conflict(pod):
            return True
        if index.anti_conflict(pod, ni.node):
            return True
        # prior batches' commit indices (consumed speculative entries carry
        # them): the device solved this batch before those commits existed
        for ix in prior or ():
            if ix.anti_conflict(pod, ni.node):
                return True
        return False

    def _oracle_place(
        self, pod: Pod, score_row: np.ndarray, meta, state: Optional[CycleState] = None
    ) -> Optional[str]:
        """Scalar fallback placement: oracle-feasible nodes against the live
        snapshot (including this batch's assumed pods), best device score
        first. Nodes with nominated pods additionally pass the two-pass
        nominated check (generic_scheduler.go:612-697). Host framework
        plugins run here: Filter as an extra per-node predicate, PostFilter
        over the feasible set, Score as an addend on the device score row
        (findNodesThatFit :457 → RunPostFilterPlugins :208 →
        PrioritizeNodes/RunScorePlugins :794)."""
        fw = self.framework
        state = state if state is not None else CycleState()
        run_filter = fw.run_filter if fw.has_plugins("filter") else None
        feasible: List[str] = []
        # zone-interleaved iteration (NodeTree semantics): first-max-wins
        # tie-breaks below spread across zones like the reference's
        # node_tree.go:162 round-robin
        for cand in self.cache.node_order():
            ni = self.cache.snapshot.get(cand)
            if ni is None or not pod_fits_on_node(pod, ni, meta=meta)[0]:
                continue
            if self.volume_checker is not None and not self.volume_checker(pod, ni)[0]:
                continue
            if run_filter is not None and not run_filter(state, pod, ni).is_success():
                continue
            nominees = preemption_mod.eligible_nominees(
                pod, cand, self.queue.nominated_pods_for_node
            )
            if nominees and not fits_with_nominees(
                pod, cand, self.cache.snapshot, nominees, enabled=self._enabled_preds
            ):
                continue
            feasible.append(cand)
        if not feasible:
            return None
        if fw.has_plugins("post_filter"):
            if not fw.run_post_filter(state, pod, feasible, {}).is_success():
                return None
        # HTTP extenders: Filter narrows (findNodesThatFit :531-557),
        # Prioritize adds weighted scores (PrioritizeNodes :813). Ignorable
        # extenders' wire failures are skipped; others fail the pod.
        ext_scores: Dict[str, int] = {}
        for e in self._pod_extenders(pod):
            snap_nodes = [self.cache.snapshot.node_infos[n].node for n in feasible]
            if e.supports_filter():
                try:
                    names, _failed = e.filter(pod, snap_nodes)
                except Exception as err:
                    if e.is_ignorable():
                        names = feasible
                    else:
                        raise ExtenderError(str(err)) from err
                keep = set(names)
                feasible = [n for n in feasible if n in keep]
                if not feasible:
                    return None
                snap_nodes = [self.cache.snapshot.node_infos[n].node for n in feasible]
            if e.supports_prioritize():
                try:
                    for n, s in e.prioritize(pod, snap_nodes).items():
                        ext_scores[n] = ext_scores.get(n, 0) + s
                except Exception as err:
                    if not e.is_ignorable():
                        raise ExtenderError(str(err)) from err
        plugin_scores: Dict[str, int] = {}
        if fw.has_plugins("score"):
            plugin_scores = fw.run_scores(state, pod, feasible)
        best = None
        best_score = None
        for cand in feasible:
            row = self.mirror.row_of.get(cand)
            s = int(score_row[row]) if row is not None and row < len(score_row) else 0
            s += plugin_scores.get(cand, 0) + ext_scores.get(cand, 0)
            if best_score is None or s > best_score:
                best, best_score = cand, s
        return best

    # -- commit path ---------------------------------------------------------

    def _prepare_commit(
        self, info: PodInfo, node_name: str, cycle: int, state: CycleState
    ) -> Optional[Pod]:
        """First half of the commit: volume-assume → reserve → cache-assume.
        Returns the assumed pod, or None after _fail. Gang groups prepare
        every member before any bind is submitted, so an incomplete group
        can roll back cleanly (_rollback_prepared)."""
        pod = info.pod
        if self.volume_binder is not None:
            # AssumePodVolumes (scheduler.go:643): tentatively match unbound
            # claims (zone-checked against the chosen node) before
            # reserve/assume so concurrent pods can't double-claim a PV
            ok = self.volume_binder.assume_pod_volumes(
                pod, node_name, self.cache.snapshot.get(node_name)
            )
            if not ok:
                self._fail(info, cycle, "volume assume failed: no bindable PV")
                return None
        st = self.framework.run_reserve(state, pod, node_name)
        if not st.is_success():
            if self.volume_binder is not None:
                self.volume_binder.forget_pod_volumes(pod)
            self._fail(info, cycle, f"reserve: {st.message}")
            return None
        assumed = pod.with_node(node_name)
        try:
            self.cache.assume_pod(assumed)
        except ValueError:
            if self.volume_binder is not None:
                self.volume_binder.forget_pod_volumes(pod)
            self.framework.run_unreserve(state, pod, node_name)
            self._fail(info, cycle, "already assumed")
            return None
        # the pod is no longer a pending nominee anywhere — drop it from the
        # queue's nominated index (DeleteNominatedPodIfExists at assume time,
        # scheduler.go:529) so it isn't double-counted on its node
        self.queue.clear_nomination(pod.key())
        return assumed

    def _rollback_prepared(
        self, info: PodInfo, assumed: Pod, node_name: str, state: CycleState, cycle: int, msg: str
    ) -> None:
        """Undo _prepare_commit for a gang member whose group fell apart."""
        self.cache.forget_pod(assumed)
        if self.volume_binder is not None:
            self.volume_binder.forget_pod_volumes(info.pod)
        self.framework.run_unreserve(state, info.pod, node_name)
        self._fail(info, cycle, msg)

    def _finalize_commit(
        self, info: PodInfo, assumed: Pod, node_name: str, cycle: int,
        state: CycleState, defer: Optional[List] = None, lean: bool = False,
    ) -> None:
        """Second half: submit the async permit → prebind → bind → postbind
        pipeline (scheduler.go:631-743). With `defer`, the pipeline closure
        is appended there instead of submitted — the caller batches
        closures into chunked pool submissions (a ThreadPoolExecutor
        submit costs ~100µs of Future/Event bookkeeping; one per POD was
        ~10%% of the whole commit loop). `lean` (batch-constant, computed
        by schedule_batch): no volume binder, no permit/prebind/bind/
        postbind plugins, no bind extender — the pipeline reduces to
        bind+finish, so defer a plain tuple and let _lean_bind_chunk run
        the whole chunk without per-pod closures."""
        pod = info.pod
        t_decided = time.perf_counter()
        if lean and defer is not None:
            defer.append((info, assumed, node_name, state, t_decided))
            return

        # ktpu: thread-entry(bind) submitted to the bind pool (directly
        # or via a deferred chunk) — never runs on the driver
        def bind_async():
            if self.volume_binder is not None:
                # bindVolumes first in the async path (scheduler.go:676)
                try:
                    self.volume_binder.bind_pod_volumes(pod)
                except Exception as e:
                    self._unbind(info, assumed, node_name, state, cycle, f"bindVolumes: {e}", reason="volumes")
                    return
            st = self.framework.run_permit(state, pod, node_name)
            if not st.is_success():
                self._unbind(info, assumed, node_name, state, cycle, f"permit: {st.message}", reason="permit")
                return
            st = self.framework.run_pre_bind(state, pod, node_name)
            if not st.is_success():
                self._unbind(info, assumed, node_name, state, cycle, f"prebind: {st.message}", reason="prebind")
                return
            ext_b = next(
                (
                    e
                    for e in self.extenders
                    if e.supports_bind() and e.is_interested(pod)
                ),
                None,
            )
            t_bind = time.perf_counter()
            try:
                fp = self._fault_plan
                if fp is not None:  # injection site: one attribute read
                    fp.raise_if("bind-error")
                if ext_b is not None:
                    # extender-delegated binding (scheduler_interface.go:53,
                    # scheduler.go:557-571 via extendersBinding)
                    ext_b.bind(pod, node_name)
                else:
                    st = self.framework.run_bind(state, pod, node_name)
                    if st.code != 0 and st.code != 4:  # not SUCCESS, not SKIP
                        raise RuntimeError(st.message)
                    self.binder.bind(pod, node_name)
            except Exception as e:  # bind RPC failed → forget + requeue
                self._unbind(info, assumed, node_name, state, cycle, f"bind: {e}", reason="rpc")
                return
            now = time.perf_counter()
            M.binding_duration.observe(now - t_bind)
            # e2e for this attempt: decision → bound (metrics.go
            # E2eSchedulingLatency = algorithm + binding)
            M.e2e_scheduling_duration.observe(now - t_decided)
            M.pod_scheduling_attempts.observe(info.attempts)
            # queue-add → bound (PodSchedulingDuration), measured on the
            # queue's own clock (it is injectable in tests)
            M.pod_scheduling_duration.observe(max(self.queue.age(info), 0.0))
            M.scheduling_attempt_duration.observe(
                self.queue.attempt_age(info), "scheduled"
            )
            self.cache.finish_binding(assumed)
            self.framework.run_post_bind(state, pod, node_name)
            self.event_fn(pod, "Scheduled", f"bound to {node_name}")

        if defer is not None:
            defer.append(bind_async)
        else:
            self._bind_pool.submit(bind_async)

    # ktpu: thread-entry(bind)
    def _lean_bind_chunk(self, items: List[Tuple], cycle: int) -> None:
        """Plugin-free bind pipeline for a whole chunk: the per-pod
        bind_async closure + four individually-locked histogram observes
        were a measurable slice of commit wall at 4096-pod batches (and the
        closures contend for the GIL with the NEXT batch's commit loop).
        Semantics identical to bind_async when lean conditions hold: no
        volume binder, permit/prebind success by vacuity, framework bind
        SKIP → default binder."""
        fp = self._fault_plan
        bind = self.binder.bind
        if fp is not None:
            _real_bind = bind

            def bind(pod, node):  # injection shim: bind-error site
                fp.raise_if("bind-error")
                _real_bind(pod, node)

        age = self.queue.age
        attempt_age = self.queue.attempt_age
        events = self.event_fn
        t_chunk = time.perf_counter()
        binds: List[float] = []
        e2es: List[float] = []
        attempts: List[int] = []
        ages: List[float] = []
        attempt_ages: List[float] = []
        finished: List[Pod] = []
        for info, assumed, node_name, state, t_decided in items:
            pod = info.pod
            bound = False
            try:
                if fp is not None:
                    # kill-point: between two binds of one chunk — the
                    # earlier items' POSTs landed, this one and the rest
                    # never happen (the restart's idempotent re-bind /
                    # relist confirm covers both halves)
                    fp.crash_if("mid-bind-chunk")
                t_bind = time.perf_counter()
                try:
                    bind(pod, node_name)
                except Exception as e:  # bind RPC failed → forget + requeue
                    self._unbind(info, assumed, node_name, state, cycle, f"bind: {e}", reason="rpc")
                    continue
                bound = True
                if fp is not None:
                    # kill-point: the POST landed, the confirm/finish
                    # bookkeeping never runs — the canonical benign-409
                    # replay window
                    fp.crash_if("post-bind")
                now = time.perf_counter()
                binds.append(now - t_bind)
                e2es.append(now - t_decided)
                attempts.append(info.attempts)
                ages.append(max(age(info), 0.0))
                attempt_ages.append(attempt_age(info))
                finished.append(assumed)
                events(pod, "Scheduled", f"bound to {node_name}")
            except Exception:
                # one pod's failure must not strand the rest of the chunk
                # assumed-but-never-bound — the per-pod closures had this
                # isolation. Post-bind bookkeeping failures leave the pod
                # BOUND (never unbind a pod the apiserver accepted — the
                # old bind_async swallowed those too); only a failure on
                # the unbound side forgets + requeues.
                if not bound:
                    try:
                        self._unbind(info, assumed, node_name, state, cycle, "bind pipeline error")
                    except Exception:
                        pass
        self.cache.finish_bindings(finished)
        M.binding_duration.observe_many(binds)
        M.e2e_scheduling_duration.observe_many(e2es)
        M.pod_scheduling_attempts.observe_many(attempts)
        M.pod_scheduling_duration.observe_many(ages)
        # per-pod attempt attribution (pop → bound), bulk-observed — with
        # queue_incoming_wait this decomposes pod_scheduling_duration
        M.scheduling_attempt_duration.observe_many(attempt_ages, "scheduled")
        M.scheduling_stage_duration.observe(
            time.perf_counter() - t_chunk, "bind"
        )
        OBS.record("bind", t_chunk, pods=len(items), bound=len(finished))

    def _commit(
        self, info: PodInfo, node_name: str, cycle: int,
        state: Optional[CycleState] = None, defer: Optional[List] = None,
        lean: bool = False,
    ) -> bool:
        """reserve → assume → async(permit → prebind → bind → postbind).
        `state` is the pod's CycleState carried from PreFilter onward, so
        plugins share per-cycle data across extension points
        (cycle_state.go)."""
        state = state if state is not None else CycleState()
        assumed = self._prepare_commit(info, node_name, cycle, state)
        if assumed is None:
            return False
        self._finalize_commit(info, assumed, node_name, cycle, state, defer=defer, lean=lean)
        return True

    def _unbind(
        self, info: PodInfo, assumed: Pod, node_name: str, state, cycle: int,
        msg: str, reason: str = "pipeline",
    ) -> None:
        """Bind-pipeline failure: forget the assume and re-queue through
        the BACKOFF tier with per-pod exponential backoff (the kube 1s→10s
        DefaultPodBackoff shape) — the old path re-added immediately via
        the unschedulable map, which either hot-looped a broken binder or
        parked the pod behind a cluster event that may never come.
        Counted by scheduler_bind_failures_total{reason}."""
        self.cache.forget_pod(assumed)
        if self.volume_binder is not None:
            self.volume_binder.forget_pod_volumes(info.pod)
        self.framework.run_unreserve(state, info.pod, node_name)
        M.bind_failures.inc(reason)
        self.event_fn(info.pod, "FailedScheduling", msg)
        M.scheduling_attempt_duration.observe(
            self.queue.attempt_age(info), "unschedulable"
        )
        self.queue.requeue_backoff(info)

    def _fail(self, info: PodInfo, cycle: int, msg: str) -> None:
        self.event_fn(info.pod, "FailedScheduling", msg)
        # attempt attribution for the failure result (pop → terminal):
        # observed BEFORE the re-queue resets the entry's clocks
        M.scheduling_attempt_duration.observe(
            self.queue.attempt_age(info), "unschedulable"
        )
        self.queue.add_unschedulable(info, cycle)

    def _try_preempt(self, info: PodInfo) -> bool:
        """scheduler.go:612 preempt: nominate a node, delete victims, clear
        obsolete lower-priority nominations. Runs BEFORE the failed pod is
        re-queued so the queue's nominated index sees the nomination."""
        pod = info.pod
        M.preemption_attempts.inc()
        t0 = time.perf_counter()
        node, victims, clear = preemption_mod.preempt(
            pod,
            self.cache.snapshot,
            pdbs=self.pdb_lister(),
            nominated_fn=self.queue.nominated_pods_for_node,
            # never evict a pod whose bind is still in flight: removing it
            # locally while the async bind completes would desync the cache
            # from the node's real occupancy
            can_disrupt=lambda p: not self.cache.is_assumed(p.key()),
            enabled=self._enabled_preds,
            # evictions can't cure volume conflicts — candidate nodes must
            # pass the volume predicates for the preemptor too
            extra_fit=(
                (lambda p, ni: self.volume_checker(p, ni)[0])
                if self.volume_checker is not None
                else None
            ),
        )
        M.preemption_evaluation_duration.observe(time.perf_counter() - t0)
        OBS.record("preempt", t0, pod=pod.key(), found=node is not None)
        if node is None:
            return False
        # extenders with a preemption verb get to veto/trim the victim set
        # (processPreemptionWithExtenders, core/generic_scheduler.go:323-345;
        # simplification: consulted on the chosen candidate rather than the
        # full candidate map — a veto fails this preemption attempt)
        preempt_exts = [
            e
            for e in self.extenders
            if e.supports_preemption() and e.is_interested(pod)
        ]
        if preempt_exts:
            from ..extender.types import Victims as WireVictims

            for e in preempt_exts:
                try:
                    result = e.process_preemption(
                        pod, {node: WireVictims(pods=list(victims))}
                    )
                except Exception:
                    if e.is_ignorable():
                        continue
                    return False
                mv = result.get(node)
                if mv is None:
                    return False  # extender vetoed the candidate node
                keep = set(mv.pod_uids)
                victims = [v for v in victims if v.uid in keep]
        self._apply_preemption(pod, node, victims, clear)
        return True

    def _apply_preemption(self, pod: Pod, node: str, victims: List[Pod], clear) -> None:
        """Victim deletes + nomination bookkeeping (the API-write tail of
        Preempt, scheduler.go:436-470) — shared by the per-pod scalar path
        and the device-batched path."""
        M.preemption_victims.observe(len(victims))
        fp = self._fault_plan
        for v in victims:
            if self.delete_fn is not None:
                # API delete: the informer's delete event removes it from the
                # cache (and graceful termination is the kubelet's business)
                self.delete_fn(v)
            else:
                self.cache.remove_pod(v)
            self.event_fn(v, "Preempted", f"by {pod.key()}")
        if fp is not None:
            # kill-point: process dies with victims evicted but the
            # preemptor's nomination never written — the restart must
            # NOT re-evict (the freed capacity is real; the relisted
            # pending preemptor simply re-solves into it)
            fp.crash_if("mid-preemption")
        for key in clear:
            self.queue.clear_nomination(key)
        pod.nominated_node_name = node
        if self.nominate_fn is not None:
            # persist status.nominatedNodeName (the wire half — the
            # informer's MODIFIED echo is what every OTHER scheduler
            # process, and a restarted this-one, reconstructs from)
            try:
                self.nominate_fn(pod, node)
            except Exception as e:
                # a failed status write degrades to local-only nomination
                # (exactly the reference's behavior: SetNominatedNodeName
                # errors are logged, the in-memory nomination stands)
                self.event_fn(pod, "FailedNomination", f"{e}")
        self.event_fn(pod, "Nominated", node)

    def _preempt_deferred(self, fails: List[PodInfo], cycle: int, res: ScheduleResult) -> None:
        """Batched preemption for the bulk-commit fast path's -1 pods: ONE
        device dispatch evaluates every preemptor x every candidate node
        (ops/preempt.preempt_batch — the vectorized selectNodesForPreemption,
        SURVEY §7 stage 7), with pop order preserved by the kernel's
        sequential carry. Evaluated at end-of-batch state (this batch's
        commits already assumed) — the batched analogue of preempt-after-
        failed-cycle. Every device plan is re-VERIFIED against the live
        snapshot on its chosen node before applying (exactness gate:
        bit-equal victim set or the pod falls back to the scalar oracle);
        ineligible batches (affinity/ports/volume seams, extender preemption
        verbs, restricted predicate sets) take the scalar path wholesale."""
        t0 = time.perf_counter()

        def can_disrupt(p: Pod) -> bool:
            return not self.cache.is_assumed(p.key())

        pdbs = self.pdb_lister()
        plans = None
        if (
            self.volume_checker is None
            and self._enabled_preds is None
            and not any(e.supports_preemption() for e in self.extenders)
        ):
            try:
                self._p_bucket = max(self._p_bucket, _bucket(len(fails), 8))
                plans = preemption_mod.batch_preempt_device(
                    [i.pod for i in fails],
                    self.cache.snapshot,
                    pdbs=pdbs,
                    can_disrupt=can_disrupt,
                    # outstanding nominations reserve their nodes in the
                    # kernel's fit checks (podFitsOnNode pass-1 semantics)
                    nominated=self.queue.nomination_extras(
                        {i.pod.key() for i in fails}
                    ),
                    # monotone preemptor/victim buckets + plan routing: one
                    # kernel signature per cluster shape, not per count
                    pod_bucket=self._p_bucket,
                    victim_bucket=self._pv_bucket or None,
                    plan=self.compile_plan,
                )
            except Exception:
                plans = None  # kernel trouble: scalar path answers instead
            # the victim axis GROWS mid-drain (nodes accumulate pods as
            # batches commit): background-warm one victim rung ahead so
            # the next preemption round lands on a hot kernel instead of
            # an inline compile — the same headroom discipline the solve's
            # growth hook applies
            if self._aot_enabled and self._warm_svc is not None:
                from dataclasses import replace as _replace

                from ..compile.ladder import next_rung

                p_spec = self._preempt_spec()
                self._warm_svc.warm_async(
                    [_replace(p_spec, v=next_rung(p_spec.v, 8))]
                )
        M.preemption_evaluation_duration.observe(time.perf_counter() - t0)
        any_preempted = False
        any_fits_free = False
        for k, info in enumerate(fails):
            pod = info.pod
            applied = False
            # _try_preempt counts its own attempt; only the pure device
            # paths (applied plan / fits_free / no-candidates) count here
            if plans is None:
                applied = self._try_preempt(info)
            else:
                node_name, victims, fits_free = plans[k]
                if fits_free:
                    # a stale speculative -1: the pod fits somewhere live
                    # without eviction — requeue, never evict for it
                    any_fits_free = True
                if node_name is None:
                    M.preemption_attempts.inc()
                if node_name is not None:
                    from ..oracle.nodeinfo import accumulated_request

                    noms = [
                        p
                        for p in self.queue.nominated_pods_for_node(node_name)
                        if p.key() != pod.key()
                    ]
                    charge = None
                    if noms:
                        total: Dict[str, int] = {}
                        for npod in noms:
                            for rn, v in accumulated_request(npod).items():
                                if rn != "pods":
                                    total[rn] = total.get(rn, 0) + v
                        charge = (total, len(noms))
                    live = preemption_mod._select_victims_fast(
                        pod, self.cache.snapshot.get(node_name), pdbs, can_disrupt,
                        nominee_charge=charge,
                    )
                    if live is not None and [p.key() for p in live.pods] == [
                        p.key() for p in victims
                    ]:
                        clear = [
                            p.key()
                            for p in self.queue.nominated_pods_for_node(node_name)
                            if p.get_priority() < pod.get_priority()
                        ]
                        M.preemption_attempts.inc()
                        self._apply_preemption(pod, node_name, victims, clear)
                        applied = True
                    else:
                        applied = self._try_preempt(info)
            if applied:
                res.preempted += 1
                any_preempted = True
                self._aff_index = None
            res.unschedulable += 1
            self._fail(info, cycle, "no fit")
        if any_preempted or any_fits_free:
            # victim deletions are cluster events — and fits_free pods must
            # retry promptly rather than age out of unschedulableQ
            # (eventhandlers.go:127 -> MoveAllToActiveQueue)
            self.queue.move_all_to_active()

    # -- commit plane --------------------------------------------------------

    def _commit_plane_statics_ok(self) -> bool:
        """Deployment-static preconditions for arbiter verdicts to ever be
        USABLE: any host plugin, extender, or volume seam forces the
        legacy loop, so a scheduler configured with one must not pay the
        device verdict scan at all. Shared by the dispatch gate (skip the
        arbitrate() dispatch + verdict fetch entirely) and
        _arbiter_covers (per-batch decision)."""
        if (
            self.extenders
            or self.volume_binder is not None
            or self.volume_checker is not None
        ):
            return False
        fw = self.framework
        for point in (
            "reserve", "filter", "pre_filter", "score", "post_filter",
            "permit", "pre_bind", "bind", "post_bind",
        ):
            if fw.has_plugins(point):
                return False
        return True

    def _arbiter_covers(self, out: SolveOutput, infos, prior_ix) -> bool:
        """Can this batch commit straight from the device arbiter's
        verdicts? True when nothing host-side can change or veto a pick
        beyond what the arbiter tracked: no host plugins/extenders/volume
        seams, every present term kind arbiter-covered, no encoding
        overflow, no outstanding nominations (their two-pass host check
        covers more than the mask's resource fold), and no speculative
        hard-spread staleness (a stale domain minimum can PASS a pod the
        sequential walk would veto — anti/ports staleness, by contrast, is
        patched exactly against the prior batches' conflict indices)."""
        if out.verdicts is None or out.gang_ok is not None:
            return False
        if not self._commit_plane_statics_ok():
            return False
        if out.existing_overflow or bool(out.fallback[: len(infos)].any()):
            return False
        if not kinds_covered(out.present_kinds):
            return False
        if (
            self.queue.has_nominations()
            or out.nom_adds != self.queue.nomination_adds
        ):
            return False
        if (out.speculative or prior_ix) and "spread_hard" in out.present_kinds:
            return False
        # -1 rows would need the oracle fallback when node rows are excluded
        if out.node_fallback_any and bool((out.assign[: len(infos)] < 0).any()):
            return False
        return True

    def _commit_arbitrated(
        self, infos: List[PodInfo], out: SolveOutput, res: ScheduleResult,
        cycle: int, prior_ix: List,
    ) -> Tuple[Optional[LazyConflictIndex], bool]:
        """Commit a covered batch from the arbiter's verdicts: V_PLACE pods
        bulk-apply (columnar assume + chunked lean binds) on the commit
        pipeline's worker, V_DEFER pods re-queue for the next batch (no
        backoff — they conflicted with their own batch, they are not
        unschedulable), V_NOFIT pods take the batched-preemption /
        unschedulable path exactly like the bulk fast path. Returns
        (prior_record, dirty): the lazy conflict index speculative-chain
        entries need when placed pods carried anti/ports, and whether the
        chain must poison (defers or escalations made the solver's carry
        diverge from what actually committed)."""
        n = len(infos)
        verdicts = out.verdicts
        assign = out.assign
        name_of = self.mirror.name_of_row
        # RAW (non-resolving) snapshot reads: this loop needs node
        # EXISTENCE and the Node object only — never the pod-derived
        # aggregates — so it must not materialize lazy NodeInfo views on
        # the commit path (perf_smoke's columnar mode pins zero
        # materializations); the one pod-derived read below (speculative
        # host-port staleness) consults the hot port COLUMNS instead.
        snap_infos = self.cache.snapshot.node_infos
        cache_cols = self.cache._columns
        raw_get = dict.get
        place: List[Tuple[PodInfo, str]] = []
        defers: List[Tuple[int, PodInfo]] = []
        escalate: List[Tuple[int, PodInfo]] = []
        preempt_fails: List[PodInfo] = []
        pairs: List[Tuple[Pod, object]] = []
        fold_pairs: List[Tuple[Pod, int]] = []
        any_anti_port = False
        nofit = 0
        known_rejects = 0
        speculative = out.speculative
        for i in range(n):
            info = infos[i]
            v = int(verdicts[i])
            row = int(assign[i])
            pod = info.pod
            if v == V_PLACE and row >= 0:
                node_name = name_of[row] if 0 <= row < len(name_of) else None
                ni = raw_get(snap_infos, node_name) if node_name is not None else None
                if ni is None:
                    defers.append((i, info))  # node vanished under the solve
                    continue
                # cross-batch staleness patch: the speculated mask predates
                # the commits recorded in prior_ix (anti, memoized per
                # spec) and, for ported pods, the live node occupancy
                if prior_ix and any(
                    ix.anti_conflict(pod, ni.node) for ix in prior_ix
                ):
                    defers.append((i, info))
                    continue
                if speculative and pod.host_ports() and (
                    cache_cols.host_port_conflict(node_name, pod)
                    if cache_cols is not None
                    else ni.host_port_conflict(pod)
                ):
                    defers.append((i, info))
                    continue
                place.append((info, node_name))
                pairs.append((pod, ni.node))
                fold_pairs.append((pod, row))
                if bool(out.has_anti[i]) or pod.host_ports():
                    any_anti_port = True
            elif v == V_DEFER:
                defers.append((i, info))
            elif row < 0 and self.enable_preemption:
                preempt_fails.append(info)
            else:
                nofit += 1
                res.unschedulable += 1
                self._fail(info, cycle, "no fit")
        # defer escalation: a pod deferred _defer_escalate times in a row
        # routes through the legacy oracle re-place instead — the progress
        # guarantee against pathological repeat conflicts
        kept_defers: List[PodInfo] = []
        for i, info in defers:
            k = info.pod.key()
            c = self._defer_counts.get(k, 0) + 1
            self._defer_counts[k] = c
            if c >= self._defer_escalate:
                # escalation CONSUMES the budget: whatever the oracle
                # decides below, the slate is clean (a recreated pod with
                # the same key must not inherit a stale count)
                self._defer_counts.pop(k, None)
                escalate.append((i, info))
            else:
                kept_defers.append(info)
        if self._defer_counts and place:
            for info, _node in place:
                self._defer_counts.pop(info.pod.key(), None)
        # bounded heuristic state: pods placed via OTHER paths (scalar,
        # bulk), deleted, or parked unschedulable never clear their entry —
        # reset wholesale rather than leak under pod churn (a reset merely
        # restores a pod's defer budget, which is always safe)
        if len(self._defer_counts) > max(1024, 4 * self.batch_size):
            self._defer_counts.clear()
        # re-queue BEFORE the apply is even submitted: the pods must be in
        # the queue no matter what happens to this batch downstream
        if kept_defers:
            self.queue.requeue(kept_defers)
            res.deferred += len(kept_defers)
        # exact accounting parity with the bulk fast path: a key the cache
        # already tracks would be REJECTED by the worker's assume_pods —
        # fail it NOW (synchronously) so res never reports it scheduled.
        # One lock for the whole batch; the worker's reject handling stays
        # as defense for the (informer-race) window after this check.
        if place:
            known = self.cache.known_keys([i.pod.key() for i, _ in place])
            if known:
                known_rejects = len(known)
                kept: List[Tuple[PodInfo, str]] = []
                for info, node_name in place:
                    if info.pod.key() in known:
                        res.unschedulable += 1
                        self._fail(info, cycle, "already assumed")
                    else:
                        kept.append((info, node_name))
                place = kept
                pairs = [
                    (pod, node) for pod, node in pairs
                    if pod.key() not in known
                ]
                fold_pairs = [
                    (pod, row) for pod, row in fold_pairs
                    if pod.key() not in known
                ]
        res.scheduled += len(place)
        assignments = res.assignments
        for info, node_name in place:
            assignments[info.pod.key()] = node_name
        # columnar apply + lean binds on the pipeline worker: overlaps the
        # next batch's solve fetch; drained before anything reads the
        # cache/queue/mirror (schedule_batch head, preemption below)
        lazy = LazyConflictIndex(pairs) if any_anti_port else None
        if place:
            # RESIDENT-STATE FOLD: the placed set's deltas land in the
            # device banks now (donated scatter-adds), the worker's bulk
            # assume is tagged `folded`, and the mirror skips re-shipping
            # those rows — a covered batch's solve inputs never cross the
            # wire. Late assume rejects (informer race) are corrected by
            # the worker via note_failed_fold (host-wins row re-ship).
            folded = self._dispatch_fold(fold_pairs)
            self._submit_columnar(place, cycle, lazy, folded=folded)
        self.stats["arbiter_batches"] = self.stats.get("arbiter_batches", 0) + 1
        self.stats["arbiter_place"] = self.stats.get("arbiter_place", 0) + len(place)
        self.stats["arbiter_defer"] = self.stats.get("arbiter_defer", 0) + len(defers)
        M.commit_plane_batches.inc("arbiter")
        M.commit_arbiter_verdicts.inc("place", by=len(place))
        if defers:
            M.commit_arbiter_verdicts.inc("defer", by=len(defers))
        if nofit > 0:
            M.commit_arbiter_verdicts.inc("nofit", by=nofit)
        if escalate or preempt_fails:
            # both read post-apply cluster state (oracle snapshot walks /
            # end-of-batch preemption) — settle the bulk apply first
            self._drain_commit()
        for i, info in escalate:
            self.stats["arbiter_escalated"] = (
                self.stats.get("arbiter_escalated", 0) + 1
            )
            pod = info.pod
            state = CycleState()
            try:
                self.stats["oracle_places"] += 1
                meta = self._pod_meta(pod)
                node_name = self._oracle_place(pod, out.score[i], meta, state)
            except Exception:
                node_name = None
            if node_name is not None and self._commit(info, node_name, cycle, state):
                res.scheduled += 1
                assignments[pod.key()] = node_name
            else:
                if node_name is None:
                    if self.enable_preemption and self._try_preempt(info):
                        res.preempted += 1
                        self._aff_index = None
                        self.queue.move_all_to_active()
                    self._fail(info, cycle, "no fit")
                res.unschedulable += 1
        if preempt_fails:
            self._preempt_deferred(preempt_fails, cycle, res)
        dirty = bool(kept_defers or escalate or known_rejects)
        return lazy, dirty

    def _submit_columnar(
        self, place: List[Tuple[PodInfo, str]], cycle: int,
        lazy: Optional[LazyConflictIndex], folded: bool = False,
    ) -> None:
        """Hand a batch's bulk apply to the commit-pipeline worker: one
        cache assume + nomination clears + chunked lean-bind submissions.
        The closure owns its failure handling (rejected keys fail their
        pods individually); the prior conflict index materializes here,
        off the critical path, before any chain entry can read it (the
        consume side drains the pipeline first)."""
        columnar = self._columnar
        bind_pool = self._bind_pool
        workers = self._bind_workers
        pipe = self._commit_pipe  # the closure's stat sink (worker side)

        # ktpu: thread-entry(commit-apply) the pipelined bulk apply —
        # runs on the CommitPipeline worker, overlapped with the next
        # batch's solve fetch
        def apply_batch() -> None:
            # runs on the commit-pipeline worker: the "apply" span lands
            # in that thread's ring, so the timeline shows the overlap
            # with the driver's next solve fetch
            t_apply = time.perf_counter()
            try:
                fp = self._fault_plan
                if fp is not None:  # injection site: one attribute read
                    fp.raise_if("device-raise", "apply")
                result = columnar.apply(place, folded=folded)
                if fp is not None:
                    # kill-point: commit worker dies mid-apply — assumes
                    # landed in the (now dead) cache, zero binds issued;
                    # the API server still holds every pod pending
                    fp.crash_if("mid-apply")
            except Exception as e:
                # commit-worker fault: nothing has been bound yet — undo
                # whatever DID get assumed (forget_pods skips unknown
                # keys, so a partial assume unwinds exactly), correct any
                # phantom fold lanes host-wins, and re-queue every pod
                # through the backoff tier. Zero lost, zero
                # double-scheduled; the breaker routes later batches to
                # the scalar loop once tripped.
                self._report_fault("commit", type(e).__name__)
                try:
                    self.cache.forget_pods(
                        [info.pod.with_node(node) for info, node in place]
                    )
                except Exception:
                    pass  # forget is best-effort cleanup here
                if folded:
                    for _info, node in place:
                        self.mirror.note_failed_fold(node)
                for info, _node in place:
                    self.queue.requeue_backoff(info)
                return
            OBS.record("apply", t_apply, pods=len(place))
            M.commit_apply_duration.observe(result.seconds)
            M.scheduling_stage_duration.observe(result.seconds, "apply")
            # stats handoff: this closure runs on the PIPELINE WORKER —
            # contributions land in the pipe's locked sink and the
            # driver merges them at drain (Scheduler.stats stays
            # single-writer; KTPU006 caught the direct write)
            pipe.note_stat("apply_s", result.seconds)
            t_decided = time.perf_counter()
            state = CycleState()  # shared: the lean pipeline never reads it
            items = [
                (info, assumed, node, state, t_decided)
                for info, assumed, node in result.placed
            ]
            if items:
                step = max(1, -(-len(items) // workers))
                for i in range(0, len(items), step):
                    bind_pool.submit(
                        self._lean_bind_chunk, items[i : i + step], cycle
                    )
            for info, node in result.rejected:
                # a pod key already in the cache means a double-schedule
                # upstream; count loudly and fail it like assume_pod's
                # ValueError path (the chain's mutation-count equality
                # check self-corrects for the uncounted assume)
                pipe.note_stat("apply_rejects", 1)
                if folded:
                    # its fold lane landed on device with no host delta to
                    # match: queue the row for a host-wins re-ship (the
                    # driver drains this worker before its next sync)
                    self.mirror.note_failed_fold(node)
                self._fail(info, cycle, "already assumed")
            if lazy is not None:
                lazy.materialize()

        self._commit_pipe.submit(apply_batch)

    @property
    def _spec_pending(self) -> Optional[Dict]:
        """Head of the speculative chain (None when empty) — kept for
        introspection/tests; the driver itself walks _spec_chain."""
        return self._spec_chain[0] if self._spec_chain else None

    def _speculative_dispatch(self, max_pods: Optional[int], carry) -> Optional[Dict]:
        """Pop the next batch and (when it is speculation-safe) dispatch its
        solve against `carry` (the chain predecessor's device residuals).
        Returns the pending entry, or None when the queue is empty.
        disp=None means the pods are popped but must be solved fresh at
        consume time."""
        infos_next = self.queue.pop_batch(max_pods or self.batch_size)
        if not infos_next:
            return None
        # acc accumulates the driver's own commits between dispatch and
        # consume; the entry is consumable as-speculated only if
        # dispatch_gen + acc == cache.mutation_count at consume time (any
        # foreign mutation — informer event, failed bind — breaks equality)
        # gang completeness at DISPATCH time: queued members of any group
        # present join the speculated batch (pop_all_in_groups), exactly as
        # the fresh path does at batch assembly — members created later are
        # protected by the min-available guard at commit
        groups = {g for g in (pod_group_name(i.pod) for i in infos_next) if g}
        if groups:
            infos_next.extend(
                self.queue.pop_all_in_groups(groups, pod_group_name)
            )
        entry: Dict = {
            "infos": infos_next,
            "disp": None,
            "acc": 0,
            "rebuild_count": -1,
            "dispatch_gen": self.cache.mutation_count,
        }
        try:
            disp = self._dispatch_solve(
                infos_next, carry=carry, allow_rebuild=False
            )
        except Exception:
            return entry  # encode trouble (e.g. overflow): solve fresh next cycle
        # start the device→host copy NOW: on a remote-attached TPU the
        # ~100ms result round-trip otherwise serializes after this batch's
        # commit loop; enqueued behind the solve, it rides the tunnel while
        # the host commits, so consume-time device_get finds the bytes local
        try:
            disp["assign_dev"].copy_to_host_async()
            if disp["gang_dev"] is not None:
                disp["gang_dev"].copy_to_host_async()
            if disp.get("verdict_dev") is not None:
                disp["verdict_dev"].copy_to_host_async()
        except AttributeError:
            pass  # non-jax array (tests with stub arrays)
        entry["disp"] = disp
        entry["rebuild_count"] = self.mirror.rebuild_count
        return entry

    # -- main loop -----------------------------------------------------------

    # ktpu: thread-entry(driver)
    def schedule_batch(self, max_pods: Optional[int] = None) -> ScheduleResult:
        """One batch cycle, wrapped in the flight recorder's cycle span
        and black-box accounting: an exception escaping the cycle (a
        driver bug, not a per-pod failure — those are handled inside)
        dumps the last N cycle records before propagating, turning the
        invisible-mid-drain class of bug into a log artifact."""
        register_thread_role("driver")
        if not OBS.enabled:
            return self._schedule_batch(max_pods)
        t0 = time.perf_counter()
        try:
            with OBS.span("cycle"):
                res = self._schedule_batch(max_pods)
        except Exception:
            self.obs.dump_blackbox("driver-exception")
            raise
        self._bb_record(
            res, self.queue.scheduling_cycle(),
            res.scheduled + res.unschedulable + res.errors + res.deferred,
            time.perf_counter() - t0,
        )
        return res

    def _schedule_batch(self, max_pods: Optional[int] = None) -> ScheduleResult:
        res = ScheduleResult()
        pending = self._spec_chain.pop(0) if self._spec_chain else None
        if pending is not None:
            infos = pending["infos"]
        else:
            infos = self.queue.pop_batch(max_pods or self.batch_size)
        if not infos:
            # an apply may still be in flight (a reject re-queues its pod):
            # settle it before reporting the queue drained, then re-pop once
            self._drain_commit()
            infos = self.queue.pop_batch(max_pods or self.batch_size)
            if not infos:
                return res
        cycle = self.queue.scheduling_cycle()
        self.stats["batches"] += 1
        trace = Trace("schedule_batch", pods=len(infos), cycle=cycle)
        # COMMIT PIPELINING overlap window: the speculated solve's result
        # fetch is a device/tunnel wait needing no host CPU — start it
        # BEFORE draining the previous batch's in-flight columnar apply so
        # the two run concurrently (commit/pipeline.py double buffering).
        # If the entry turns out non-consumable below, the fetch was the
        # copy_to_host_async bytes already in flight — nothing wasted.
        out_pre: Optional[SolveOutput] = None
        if pending is not None and pending["disp"] is not None:
            out_pre = self._finish_solve(pending["disp"])
        self._drain_commit()
        trace.step("commit-pipeline drain")
        t_sync = time.perf_counter()
        self.mirror.sync()
        dt_sync = time.perf_counter() - t_sync
        self.stats["sync_s"] += dt_sync
        M.tensor_sync_duration.observe(dt_sync)
        M.scheduling_stage_duration.observe(dt_sync, "sync")
        OBS.record("sync", t_sync)
        trace.step("tensor mirror sync")
        # steady-state health plane: the post-sync, pipeline-drained
        # moment is the monitor's designated safe point — the driver
        # publishes the mirror census (driver-confined state never
        # crosses to the monitor thread) and executes any due sampled
        # shadow audit here, where device_bank_divergence is already
        # the resident-state plane's designed sync point
        if self.health is not None:
            self.health.driver_sync_hook()
            trace.step("health sync hook")
        # fault plane: recoveries + audit-gated probe resolution at the
        # same safe point (one attribute read while everything is closed)
        if not self.faults.quiet:
            self._fault_service()
            trace.step("fault service")
        fault_plan = self._fault_plan
        if fault_plan is not None and fault_plan.fire("bank-skew"):
            # chaos harness: corrupt a device bank array so the next
            # shadow audit MUST report divergence (and escalate: trip +
            # resync + black box) — the forced-skew sensitivity probe as
            # a fault. Settle the banks FIRST (ship pending/stale state)
            # and audit at THIS safe point: a pending full re-upload
            # (e.g. a fold fault's resync) would otherwise legitimately
            # heal the skew before any audit saw it, silently voiding
            # the escalation-path coverage the injection exists for.
            from ..faults.inject import apply_bank_skew

            if self.mirror._dev_nodes is not None:
                self.mirror.device_arrays()
            apply_bank_skew(self.mirror)
            if self.health is not None:
                self.health.request_audit()
                self.health.driver_sync_hook()
        # the snapshot moved (sync) — rebuild the oracle metadata index
        # lazily if this batch needs it
        self._aff_index = None
        self._aff_extra = []
        # a speculated solve is consumable only if nothing it could not have
        # accounted for happened since dispatch: no cache mutations beyond
        # the previous batch's own commits, and no bank rebuild (row remap)
        use_pending = (
            pending is not None
            and pending["disp"] is not None
            and pending["dispatch_gen"] + pending["acc"] == self.cache.mutation_count
            and pending["rebuild_count"] == self.mirror.rebuild_count
        )
        # gang completeness: every QUEUED member of any group present in the
        # batch joins it, so all-or-nothing is decided over the whole group.
        # Entries consumed exactly as speculated completed their groups at
        # dispatch time — extending those would add pods the device never
        # solved. Any entry that will NOT be consumed as-speculated (no
        # dispatch, poisoned chain, or a consume-time validity miss about to
        # re-solve fresh) reunifies like any fresh batch.
        batch_groups = [pod_group_name(i.pod) for i in infos]
        groups_in_batch = {g for g in batch_groups if g}
        if groups_in_batch and not use_pending:
            extra = self.queue.pop_all_in_groups(groups_in_batch, pod_group_name)
            infos.extend(extra)
            batch_groups.extend(pod_group_name(i.pod) for i in extra)
        M.batch_size.observe(len(infos))
        # conflict indices of batches committed between this entry's
        # dispatch and now (tracked chains survive anti/port commits; the
        # stale device mask is patched by checking these host-side)
        prior_ix: List[_BatchConflictIndex] = (
            pending.get("prior") or []
        ) if use_pending else []
        try:
            t_solve = time.perf_counter()
            if use_pending:
                self.stats["spec_hits"] = self.stats.get("spec_hits", 0) + 1
                out = out_pre if out_pre is not None else self._finish_solve(
                    pending["disp"]
                )
                self._last_carry = pending["disp"]["carry_dev"]
            else:
                if pending is not None:
                    self.stats["spec_misses"] = self.stats.get("spec_misses", 0) + 1
                    # a miss means THIS batch re-solves fresh — every entry
                    # still in the chain was solved against this entry's
                    # never-materialized speculative placements, and any
                    # entry appended later would chain on that same dead
                    # carry. Poison them all (their pods re-solve fresh at
                    # consume; the chain refills behind the fresh carry).
                    for e in self._spec_chain:
                        e["disp"] = None
                disp = self._dispatch_solve(infos)
                out = self._finish_solve(disp)
                self._last_carry = disp["carry_dev"]
            dt_solve = time.perf_counter() - t_solve
            M.device_solve_duration.observe(dt_solve)
            # the mask and score stages are ONE fused program — both series
            # observe the same dispatch (split is meaningless under fusion)
            M.predicate_evaluation_duration.observe(dt_solve)
            M.priority_evaluation_duration.observe(dt_solve)
            trace.step("device solve (mask+score+assign)")
        except Exception as e:
            # a solve/fetch error is an ERROR, not unschedulability: the
            # pods retry through the backoff tier (1s→10s per pod — the
            # MakeDefaultErrorFunc shape) instead of parking in
            # unschedulableQ behind a cluster event that may never come
            for info in infos:
                res.errors += 1
                if self.error_fn:
                    self.error_fn(info.pod, e)
                self.event_fn(info.pod, "FailedScheduling", f"solve error: {e}")
                M.scheduling_attempt_duration.observe(
                    self.queue.attempt_age(info), "unschedulable"
                )
                self.queue.requeue_backoff(info)
            M.schedule_attempts.inc(M.ERROR, by=len(infos))
            return res
        if fault_plan is not None:
            # kill-point: solve result in hand, nothing committed — the
            # popped pods die with the process and only the API server's
            # pending copies survive (the restart relist re-queues them)
            fault_plan.crash_if("post-solve")
        # SPECULATIVE PIPELINING (the reference's assume-then-async-bind
        # discipline applied to the solve, SURVEY §2.3), depth spec_depth:
        # pop and dispatch the next batches chained on each other's device
        # residual carries BEFORE committing this one — the device solves
        # k+1..k+D while the host commits k, and finished results stream
        # back via copy_to_host_async. Dispatches are optimistic; the
        # commit loop's outcome accumulates into every chained entry, and
        # consumption re-validates against cache mutations / bank rebuilds.
        if self.speculate and self._last_carry is not None:
            if self._spec_backoff > 0:
                self._spec_backoff -= 1
            else:
                while len(self._spec_chain) < self.spec_depth:
                    if self._spec_chain:
                        tail_disp = self._spec_chain[-1]["disp"]
                        if tail_disp is None:
                            break  # cannot chain past a fresh-solve entry
                        tail_carry = tail_disp["carry_dev"]
                    else:
                        tail_carry = self._last_carry
                    # entries join the chain from this moment: if the commit
                    # loop below raises, the popped pods survive (consumed
                    # with sentinel validity, i.e. solved fresh)
                    entry = self._speculative_dispatch(max_pods, tail_carry)
                    if entry is None:
                        break  # queue drained
                    self._spec_chain.append(entry)
                    if entry["disp"] is None:
                        break

        fw = self.framework
        # plugin-free bind pipeline? (batch-constant; see _lean_bind_chunk)
        lean_bind = (
            self.volume_binder is None
            and not fw.has_plugins("permit")
            and not fw.has_plugins("pre_bind")
            and not fw.has_plugins("bind")
            and not fw.has_plugins("post_bind")
            and not any(e.supports_bind() for e in self.extenders)
        )
        # nominated-pods lookups take the queue lock per POD; skip them for
        # the whole batch when the nominated index is empty (the common
        # case) — preemption inside the loop re-arms the real lookup
        nominated_fn = self.queue.nominated_pods_for_node
        if not self.queue.has_nominations():
            nominated_fn = _no_nominations
        # host framework plugins (framework.go): Filter narrows the mask,
        # PostFilter sees the feasible set, Score adds to the ranking — any
        # of them forces the host commit path (the device mask/score can't
        # know what host Python plugins will say)
        host_filter = fw.has_plugins("filter")
        host_pre_filter = fw.has_plugins("pre_filter")
        # Score/PostFilter participate in SELECTION, not just validation —
        # the device's argmax pick must be re-ranked host-side
        force_host_rank = fw.has_plugins("score") or fw.has_plugins("post_filter")
        if force_host_rank:
            # EVERY pod will take the host-rank path: one bulk gather instead
            # of a ~100ms device round-trip per pod
            out.score.prefetch(range(len(infos)))
        # once a pod carrying required anti-affinity commits, its terms can
        # invalidate ANY later pod's device placement (the mask predates the
        # batch) — later pods get the cheap intra-batch check against this
        # topology-value index instead of an O(cluster) oracle pass
        # (reference: the sequential loop sees it via
        # satisfiesExistingPodsAntiAffinity, predicates.go:1284)
        conflict_index = _BatchConflictIndex()
        # maintaining the commit index costs ~10us/pod in label-dict walks;
        # a batch of pure RECHECK_NONE pods (no gang, no host plugins, no
        # extenders) never reads it — neither the LIGHT/_minus_one paths
        # (no such pods) nor the oracle metadata extras (commits carry no
        # affinity terms, so their contribution is empty)
        index_needed = (
            out.gang_ok is not None
            or host_filter
            or bool(self.extenders)
            or out.levels is None
            or bool((out.levels[: len(infos)] != RECHECK_NONE).any())
        )
        # once ANY pod commits to a different node than the solver chose (an
        # oracle re-placement), the scan carry's residuals are stale for the
        # rest of the batch — later device picks need a resource validation
        residuals_diverged = False
        # gang groups: members are PREPARED (reserve+assume) as decided but
        # their binds are submitted only once the whole group has landed;
        # one failing member rolls back the group (all-or-nothing) through
        # a SINGLE rollback record per group (commit/apply.py): one bulk
        # cache forget plus the per-member plugin bookkeeping
        gang_staged: Dict[str, GangRollbackRecord] = {}
        gang_failed: set = set()

        def rollback_group(g: str) -> None:
            nonlocal residuals_diverged
            gang_failed.add(g)
            # rolled-back assumes leave the snapshot: the extras no longer
            # mirror it — drop the cache (rebuilt lazily from live state)
            self._aff_index = None
            rec = gang_staged.pop(g, None)
            if rec is None or not len(rec):
                return
            n = rec.rollback(
                self.cache, self.framework, self.volume_binder,
                self._fail, cycle, "gang incomplete",
                # the rolled-back members no longer occupy any node: prune
                # them so later LIGHT pods don't see phantom conflicts and
                # escalate to the O(cluster) oracle path
                on_member=lambda info: conflict_index.remove(info.pod),
            )
            res.unschedulable += n
            residuals_diverged = True  # staged capacity released

        t_commit = time.perf_counter()
        bind_jobs: List = []  # deferred bind pipelines, chunk-submitted below

        # BULK COMMIT fast path: when nothing host-side can change or veto
        # the device's picks — plugin-free lean pipeline, every pod
        # RECHECK_NONE (index_needed False covers gang/extenders/levels),
        # no nominations, no volume seam, no stale prior indices, no
        # encoding overflow — the per-pod commit shell (CycleState, RLock
        # round-trip, recheck dispatch) collapses to: clone → one bulk
        # cache assume → deferred lean binds. Pop-order semantics are
        # vacuous here: with no topology/anti/port coupling and resources
        # already sequentialized by the solver's carry, earlier commits
        # cannot invalidate later ones.
        fast_bulk = (
            lean_bind
            and not index_needed
            and not host_pre_filter
            and not force_host_rank
            # nominations either don't exist, or every outstanding one was
            # folded into this solve's mask at dispatch and none appeared
            # since — the pass-1 accounting is already in the device pick,
            # and pass 2 (without nominees) is vacuous for RECHECK_NONE
            # pods (resources only)
            and (
                nominated_fn is _no_nominations
                or out.nom_adds == self.queue.nomination_adds
            )
            and self.volume_binder is None
            and self.volume_checker is None
            and not fw.has_plugins("reserve")
            and not prior_ix
            and not out.existing_overflow
            and not bool(out.fallback[: len(infos)].any())
        )
        if fast_bulk:
            assign_l = out.assign[: len(infos)].tolist()
            if any(r < 0 for r in assign_l) and out.node_fallback_any:
                fast_bulk = False  # -1s need the oracle fallback: scalar loop
        preempt_fails: List[PodInfo] = []
        if fast_bulk:
            name_of = self.mirror.name_of_row
            assumed_meta: List[Tuple[PodInfo, Pod, str]] = []
            fold_rows: List[int] = []
            fail = self._fail
            perf = time.perf_counter
            for i, row in enumerate(assign_l):
                info = infos[i]
                node_name = name_of[row] if row >= 0 else None
                if node_name is None:
                    if row < 0 and self.enable_preemption:
                        # deferred: one device-batched preemption round after
                        # the commits (pop order preserved by the kernel)
                        preempt_fails.append(info)
                        continue
                    res.unschedulable += 1
                    if row >= 0:
                        residuals_diverged = True  # charged a vanished node
                    fail(info, cycle, "no fit")
                    continue
                assumed_meta.append((info, info.pod.with_node(node_name), node_name))
                fold_rows.append(row)
            # RESIDENT-STATE FOLD: this batch's usage/signature deltas go
            # straight into the device banks (donated scatter-adds) — the
            # matching assumes below are tagged `folded` so the mirror
            # never re-ships their rows. Dispatched BEFORE the assume so
            # any reject (informer race) is corrected by a host-wins
            # re-ship of its row, never by a device state we can't undo.
            folded = bool(assumed_meta) and self._dispatch_fold(
                [(m[0].pod, r) for m, r in zip(assumed_meta, fold_rows)]
            )
            rejected = set(
                self.cache.assume_pods(
                    [m[1] for m in assumed_meta], folded=folded
                )
            )
            if fault_plan is not None:
                # kill-point: the bulk apply landed (assumes in the
                # dying cache) but no bind was submitted — same window
                # the commit-worker mid-apply site covers on the
                # arbitrated path
                fault_plan.crash_if("mid-apply")
            if folded:
                for j in rejected:
                    self.mirror.note_failed_fold(assumed_meta[j][2])
            if self.queue.has_nominations():
                # DeleteNominatedPodIfExists at assume time (scheduler.go:
                # 529), batched — committed pods stop reserving their
                # nominated nodes
                self.queue.clear_nominations(
                    [m[0].pod.key() for j, m in enumerate(assumed_meta) if j not in rejected]
                )
            state = CycleState()  # shared: the lean pipeline never reads it
            append = bind_jobs.append
            assignments = res.assignments
            for j, (info, assumed, node_name) in enumerate(assumed_meta):
                if j in rejected:
                    res.unschedulable += 1
                    residuals_diverged = True
                    self._fail(info, cycle, "already assumed")
                    continue
                append((info, assumed, node_name, state, perf()))
                assignments[info.pod.key()] = node_name
            res.scheduled += len(assumed_meta) - len(rejected)
            if preempt_fails:
                self._preempt_deferred(preempt_fails, cycle, res)
            M.commit_plane_batches.inc("bulk")
            infos = []  # the scalar loop below sees an empty batch

        # DEVICE-ARBITRATED COMMIT (commit plane, kubernetes_tpu/commit):
        # term-carrying batches the bulk path had to refuse — required
        # anti-affinity, host ports, DoNotSchedule spread — commit straight
        # from the arbiter's sequential-equivalent verdicts: V_PLACE pods
        # columnar-apply on the pipeline worker, V_DEFER pods retry next
        # batch against the committed state, V_NOFIT pods take the batched
        # preemption path. The per-pod scalar loop below becomes the
        # fallback for what the arbiter does not cover (plugins, extenders,
        # volumes, required affinity, nominations, gangs).
        arb_prior: Optional[LazyConflictIndex] = None
        if infos and self._arbiter_covers(out, infos, prior_ix):
            arb_prior, arb_dirty = self._commit_arbitrated(
                infos, out, res, cycle, prior_ix
            )
            if arb_dirty:
                residuals_diverged = True
            trace.step("commit plane (device-arbitrated)")
            infos = []
        elif infos:
            M.commit_plane_batches.inc("scalar")

        # commit in pop order so oracle re-checks see earlier assumes,
        # reproducing sequential semantics. pop_batch pops the activeQ heap,
        # so `infos` already arrives in comparator order — (priority desc,
        # seq asc) by default, or the QueueSort plugin's Less — and that
        # order, not a hardcoded priority sort, is authoritative
        # (scheduling_queue.go:120 activeQComp).
        for i in range(len(infos)):
            info = infos[i]
            pod = info.pod
            group = None
            # disposition marker: True once this pod has been finally handled
            # (committed, staged into its gang, or _fail-ed) — the exception
            # guard below must not dispose a pod twice (double _fail inflates
            # backoff; _fail after a queued bind double-schedules)
            disposed = False
            try:
                state = CycleState()
                group = batch_groups[i]
                if group and group in gang_failed:
                    res.unschedulable += 1
                    disposed = True
                    self._fail(info, cycle, "gang incomplete")
                    continue
                if group and out.gang_ok is not None and not out.gang_ok[i]:
                    # the device solver dropped the whole group in pass 2
                    rollback_group(group)
                    res.unschedulable += 1
                    disposed = True
                    self._fail(info, cycle, "gang does not fit")
                    continue
                row = int(out.assign[i])
                node_name = self.mirror.node_name_of_row(row) if row >= 0 else None
                device_choice = node_name
                if host_pre_filter:
                    st = fw.run_pre_filter(state, pod)
                    if not st.is_success():
                        res.unschedulable += 1
                        if device_choice is not None:
                            # the solver charged this pod's request to a node it
                            # will never occupy
                            residuals_diverged = True
                        disposed = True
                        self._fail(info, cycle, f"prefilter: {st.message}")
                        continue
                level = int(out.levels[i]) if out.levels is not None else _recheck_level(pod)
                needs_full = (
                    out.fallback[i]
                    or out.existing_overflow
                    or host_filter
                    or level == RECHECK_FULL
                    # speculative solve without device tracking: topology/
                    # port counts are one batch stale — LIGHT pods escalate
                    # to the live-snapshot check. With tracking, the prior
                    # conflict indices + live-snapshot ports cover exactly
                    # the staleness (needs_light below).
                    or (out.speculative and level == RECHECK_LIGHT
                        and not out.inbatch_tracked)
                    or (
                        self.volume_checker is not None
                        and bool(scheduling_relevant_volumes(pod))
                    )
                )
                # the device sequentialized anti/ports within this batch:
                # LIGHT rechecks are redundant while commits follow the
                # device's picks (divergence re-arms them) and the solve was
                # not speculative (cross-batch staleness keeps the FULL
                # escalation above)
                tracked_ok = out.inbatch_tracked and not residuals_diverged
                needs_light = (
                    (level == RECHECK_LIGHT or conflict_index.any_anti)
                    and not tracked_ok
                ) or bool(prior_ix)
                pod_host_rank = force_host_rank or (
                    bool(self.extenders)
                    and any(
                        e.supports_filter() or e.supports_prioritize()
                        for e in self._pod_extenders(pod)
                    )
                )
                placed_attempted = False  # _oracle_place already ran for this pod
                try:
                    if node_name is not None and pod_host_rank:
                        # Score/PostFilter plugins and HTTP extenders participate
                        # in selection — skip validating the device pick and
                        # re-rank host-side directly
                        self.stats["oracle_places"] += 1
                        meta = self._pod_meta(pod)
                        node_name = self._oracle_place(pod, out.score[i], meta, state)
                        placed_attempted = True
                    elif node_name is not None and (needs_full or nominated_fn(node_name)):
                        self.stats["oracle_rechecks"] += 1
                        meta = self._pod_meta(pod)
                        ok = self.cache.snapshot.get(node_name) is not None and fits_considering_nominated(
                            pod, node_name, self.cache.snapshot, nominated_fn, meta=meta
                        )
                        if ok and self.volume_checker is not None:
                            ni = self.cache.snapshot.get(node_name)
                            ok = self.volume_checker(pod, ni)[0]
                        if ok and host_filter:
                            ni = self.cache.snapshot.get(node_name)
                            ok = fw.run_filter(state, pod, ni).is_success()
                        if not ok:
                            # invalidated by an earlier commit in this batch (the
                            # solver carry tracks only resources) — re-place via
                            # the oracle against the CURRENT snapshot, ranking
                            # candidates by the device score row
                            # (sequential-equivalent filter, batch-stale scores)
                            node_name = self._oracle_place(pod, out.score[i], meta, state)
                            placed_attempted = True
                    elif node_name is not None and needs_light:
                        # cheap intra-batch validation: only this batch's commits
                        # can invalidate a LIGHT pod's device placement
                        self.stats["light_rechecks"] += 1
                        ok = not self._intra_batch_conflict(
                            pod, node_name, conflict_index, prior=prior_ix
                        )
                        if ok and residuals_diverged:
                            ni = self.cache.snapshot.get(node_name)
                            ok = ni is not None and pod_fits_resources(pod, ni)
                        if not ok:
                            self.stats["oracle_places"] += 1
                            meta = self._pod_meta(pod)
                            node_name = self._oracle_place(pod, out.score[i], meta, state)
                            placed_attempted = True
                    elif node_name is not None and residuals_diverged:
                        # constraint-free pod, but an earlier re-placement moved
                        # capacity the solver didn't account for: cheap scalar
                        # resource check against the LIVE snapshot; full oracle
                        # re-place only if it fails
                        ni = self.cache.snapshot.get(node_name)
                        if ni is None or not pod_fits_resources(pod, ni):
                            meta = self._pod_meta(pod)
                            node_name = self._oracle_place(pod, out.score[i], meta, state)
                            placed_attempted = True
                    if (
                        node_name is None
                        and not placed_attempted
                        and (
                            out.fallback[i]
                            or out.existing_overflow
                            or out.node_fallback_any
                            or residuals_diverged
                            # speculative solve: the topology/affinity counts
                            # are one batch stale, so a FULL pod's -1 may
                            # reflect a feasible set the PREVIOUS batch's
                            # commits have since widened (anchor landed,
                            # spread minimum rose). The stale-ASSIGNMENT case
                            # gets the LIGHT→FULL escalation above; this is
                            # the stale--1 counterpart.
                            or (out.speculative and level == RECHECK_FULL)
                            or _minus_one_could_fit(
                                pod, conflict_index, res.preempted > 0, level
                            )
                        )
                    ):
                        # the device mask may be conservatively wrong (encoding
                        # overflow / excluded node rows / capacity the carry
                        # charged to a node an earlier pod vacated / a topology
                        # constraint SATISFIED by an earlier in-batch commit,
                        # e.g. a required pod-affinity anchor arriving in the
                        # same batch) — full scalar fallback before declaring the
                        # pod unschedulable
                        self.stats["oracle_places"] += 1
                        meta = self._pod_meta(pod)
                        node_name = self._oracle_place(pod, out.score[i], meta, state)
                except ExtenderError as ee:
                    # wire failure, not a FitError: error path, never preemption
                    # (MakeDefaultErrorFunc re-queue, factory.go:646)
                    res.errors += 1
                    if device_choice is not None:
                        residuals_diverged = True
                    if self.error_fn:
                        self.error_fn(pod, ee)
                    disposed = True
                    self._fail(info, cycle, f"extender error: {ee}")
                    continue
                if node_name is None:
                    if device_choice is not None:
                        # the solver charged this pod's request to a node it never
                        # occupied — later device picks may be too conservative
                        residuals_diverged = True
                    if group:
                        # one member without a home sinks the whole group; no
                        # preemption on behalf of gang members (keep the
                        # all-or-nothing contract simple and deterministic)
                        rollback_group(group)
                        res.unschedulable += 1
                        disposed = True
                        self._fail(info, cycle, "gang member: no fit")
                        continue
                    preempted_now = self.enable_preemption and self._try_preempt(info)
                    if preempted_now:
                        res.preempted += 1
                        # victim deletions changed the snapshot under the index
                        self._aff_index = None
                        # the preempted pod is about to be re-queued with a
                        # nomination: later pods must see the real index
                        nominated_fn = self.queue.nominated_pods_for_node
                    res.unschedulable += 1
                    disposed = True
                    self._fail(info, cycle, "no fit")
                    if preempted_now:
                        # victim deletions are cluster events: wake the queue
                        # (eventhandlers.go:127 → MoveAllToActiveQueue); the pod
                        # retries after its backoff expires
                        self.queue.move_all_to_active()
                    continue
                if group:
                    assumed = self._prepare_commit(info, node_name, cycle, state)
                    if assumed is None:
                        rollback_group(group)
                        res.unschedulable += 1
                        disposed = True
                        continue
                    # from here the pod's disposition belongs to the group:
                    # the guard's rollback_group fails staged members
                    gang_staged.setdefault(
                        group, GangRollbackRecord(group)
                    ).stage(info, assumed, node_name, state)
                    disposed = True
                    c_node = self.cache.snapshot.get(node_name) if index_needed else None
                    if c_node is not None:
                        conflict_index.add_commit(pod, c_node.node)
                        self._aff_extra.append((assumed, c_node.node.labels))
                        if out.has_anti[i]:
                            conflict_index.add_anti(pod, c_node.node)
                    if node_name != device_choice:
                        residuals_diverged = True
                elif self._commit(
                    info, node_name, cycle, state, defer=bind_jobs, lean=lean_bind
                ):
                    res.scheduled += 1
                    res.assignments[pod.key()] = node_name
                    disposed = True  # bind pipeline queued: never _fail past this
                    c_node = self.cache.snapshot.get(node_name) if index_needed else None
                    if c_node is not None:
                        conflict_index.add_commit(pod, c_node.node)
                        self._aff_extra.append((pod.with_node(node_name), c_node.node.labels))
                        if out.has_anti[i]:
                            conflict_index.add_anti(pod, c_node.node)
                    if node_name != device_choice:
                        residuals_diverged = True
                else:
                    res.unschedulable += 1
                    disposed = True  # _commit failed the pod internally
                    if device_choice is not None:
                        residuals_diverged = True
            except Exception as e:
                # PER-POD EXCEPTION GUARD: a bug or bad object on one pod's
                # commit path must fail THAT pod (error-requeue, factory.go:646
                # MakeDefaultErrorFunc semantics), never abort the batch and
                # strand its uncommitted tail (round-2 verdict, weak #1)
                residuals_diverged = True
                # a mid-preemption exception may have deleted victims before
                # raising — the snapshot moved under the affinity index
                self._aff_index = None
                if group:
                    # fails staged members (including this pod, if staged)
                    rollback_group(group)
                if not disposed:
                    res.errors += 1
                    if self.error_fn:
                        # error-requeue contract (factory.go:646) — only for
                        # pods not already bound/staged/failed
                        self.error_fn(pod, e)
                    self._fail(info, cycle, f"commit error: {e!r}")
                continue
        # complete groups: submit every member's bind pipeline — unless the
        # declared min-available says part of the group hasn't even been
        # created yet, in which case binding this slice would break
        # all-or-nothing across batches
        for g, rec in list(gang_staged.items()):
            members = rec.members
            need = max((pod_group_min_available(m[0].pod) for m in members), default=0)
            if need and len(members) < need:
                rollback_group(g)
                continue
            for s_info, s_assumed, s_node, s_state in members:
                self._finalize_commit(
                    s_info, s_assumed, s_node, cycle, s_state, defer=bind_jobs,
                    lean=lean_bind,
                )
                res.scheduled += 1
                res.assignments[s_info.pod.key()] = s_node
        # chunked submission: ceil(len/workers) pipelines per pool task
        # keeps the ~100µs-per-submit overhead off the commit loop while
        # still spreading the chunks across every worker (IO-bound binders
        # keep their concurrency). Permit plugins can WAIT on other pods'
        # allow() (framework/interface.py waiting pods) — sequentializing
        # those would deadlock a chunk, so they keep per-pod submission.
        if bind_jobs:
            if lean_bind:
                step = max(1, -(-len(bind_jobs) // self._bind_workers))
                for i in range(0, len(bind_jobs), step):
                    self._bind_pool.submit(
                        self._lean_bind_chunk, bind_jobs[i : i + step], cycle
                    )
            elif self.framework.has_plugins("permit"):
                for f in bind_jobs:
                    # ktpu: thread-entry(bind) per-pod bind_async closures
                    self._bind_pool.submit(f)
            else:

                # ktpu: thread-entry(bind)
                def _run_chunk(chunk):
                    for f in chunk:
                        try:
                            f()
                        except Exception:  # one failed bind must not
                            pass  # abort the rest (each f fails its own pod)

                step = max(1, -(-len(bind_jobs) // self._bind_workers))
                for i in range(0, len(bind_jobs), step):
                    self._bind_pool.submit(_run_chunk, bind_jobs[i : i + step])
        dt_commit = time.perf_counter() - t_commit
        self.stats["commit_s"] += dt_commit
        M.scheduling_stage_duration.observe(dt_commit, "commit")
        OBS.record("commit", t_commit, pods=len(infos) or res.scheduled)
        if self._spec_chain:
            # keep the speculated solves only if this batch went exactly the
            # way the device predicted: every commit on the device's node
            # (residual carry exact), no preemption/error side effects, and
            # no new required-anti pattern the speculated masks missed. One
            # dirty batch poisons the WHOLE chain (each entry is chained on
            # the previous solve's residuals).
            if (
                residuals_diverged
                or res.errors
                or res.preempted
                # without device tracking, anti commits invalidate the
                # speculated masks wholesale; with it, the carried conflict
                # index patches them at consume time (needs_light)
                or (conflict_index.any_anti and not out.inbatch_tracked)
            ):
                for e in self._spec_chain:
                    e["disp"] = None
                self._spec_backoff = 4
            else:
                self._spec_backoff = 0
                # every in-flight entry expected this batch's commits (one
                # assume each); anything else — foreign pods, async bind
                # failures, informer events — lands on top and fails the
                # equality check at consume time
                for e in self._spec_chain:
                    e["acc"] += res.scheduled
                if conflict_index.any_anti or conflict_index.any_ports:
                    for e in self._spec_chain:
                        e.setdefault("prior", []).append(conflict_index)
                elif arb_prior is not None:
                    # arbiter-committed anti/port pods: chained entries get
                    # the lazy index (materialized on the pipeline worker)
                    for e in self._spec_chain:
                        e.setdefault("prior", []).append(arb_prior)
        trace.step("commit loop")
        M.scheduling_algorithm_duration.observe(trace.total_seconds())
        M.schedule_attempts.inc(M.SCHEDULED, by=res.scheduled)
        M.schedule_attempts.inc(M.UNSCHEDULABLE, by=res.unschedulable)
        active, backoff, unsched = self.queue.counts()
        M.pending_pods.set(active, "active")
        M.pending_pods.set(backoff, "backoff")
        M.pending_pods.set(unsched, "unschedulable")
        # oldest-pending age on the queue's own clock, observed OUTSIDE
        # the queue lock (oldest_pending_age releases it before
        # returning) — the starvation gauge next to the depth split.
        # THROTTLED: the min-timestamp walk is O(pending) under the
        # queue lock, so unlike the O(1) depth gauges it refreshes at
        # most twice a second, not per batch (the health monitor's
        # refresh exports it on its own cadence too).
        now_pc = time.perf_counter()
        if now_pc - getattr(self, "_oldest_age_obs_ts", 0.0) >= 0.5:
            self._oldest_age_obs_ts = now_pc
            M.queue_oldest_pending_age.set(self.queue.oldest_pending_age())
        # the reference's 100ms slow-cycle contract (LogIfLong,
        # generic_scheduler.go:175-176) — per batch here
        trace.log_if_long()
        return res

    def run_until_empty(self, max_cycles: int = 1000) -> ScheduleResult:
        total = ScheduleResult()
        for _ in range(max_cycles):
            r = self.schedule_batch()
            total.scheduled += r.scheduled
            total.unschedulable += r.unschedulable
            total.errors += r.errors
            total.preempted += r.preempted
            total.deferred += r.deferred
            total.assignments.update(r.assignments)
            if (
                r.scheduled == 0 and r.unschedulable == 0 and r.errors == 0
                and r.deferred == 0
            ):
                break
        return total

    def flush_speculative(self) -> int:
        """Return any pods parked by a speculative dispatch to the queue.
        Without this, pods popped by `_speculative_dispatch` but never
        consumed (caller stops invoking schedule_batch, shutdown between
        cycles) would be in neither the queue nor the unschedulable set —
        silently dropped. Returns the number of pods re-queued."""
        chain, self._spec_chain = self._spec_chain, []
        n = 0
        for pending in chain:
            for info in pending.get("infos") or []:
                self.queue.add(info.pod)
                n += 1
        return n

    # ktpu: thread-entry(driver) shutdown runs on the owning thread
    def close(self) -> None:
        """Orderly shutdown, in dependency order: re-queue speculatively
        parked pods, drain the commit pipeline (its worker SUBMITS bind
        chunks), retire the bind pool for good (no recreation — a closed
        scheduler must leak zero threads), stop the health monitor and
        both staged-bank uploaders with join timeouts (each bank flushes
        its dirty backlog synchronously first, so the device twins are
        host-true at the moment the workers die), retire the background
        compile-warmup worker (an XLA compile in flight at interpreter
        exit aborts the process — queued warms are dropped, the running
        one completes and the grown ladder persists), and emit a final
        census (`last_census`) as the shutdown flight record. Idempotent:
        a second close() returns immediately."""
        if getattr(self, "_closed", False):
            return
        self._closed = True
        try:
            self.flush_speculative()
            # drain-then-shutdown, not wait_for_binds: that helper
            # recreates the pool for callers that keep scheduling;
            # close must not
            self._drain_commit()
        finally:
            # a raising drain (a worker exception — or a SimulatedCrash
            # — re-raised on this thread) must still stop every worker:
            # the _closed latch above makes a retry a no-op, so this is
            # the only shot at not leaking threads
            self._bind_pool.shutdown(wait=True)
            self._commit_pipe.close()
            if self.health is not None:
                self.health.stop()
            if self.stage_bank is not None:
                self.stage_bank.close()
            if self.term_bank is not None:
                self.term_bank.close()
            if self._warm_svc is not None:
                self._warm_svc.stop()
                self._warm_svc.join()
                self.compile_plan.persist()
        # final census — every worker above is stopped, so this is the
        # one census guaranteed quiescent; kept on the instance (and
        # returned by obs/introspect.census consumers) as the shutdown
        # flight record
        try:
            from ..obs.introspect import census as _census

            self.last_census = _census(self)
        except Exception:
            self.last_census = None  # forensics, never load-bearing

    # ktpu: thread-entry(driver)
    def abort(self) -> None:
        """NON-graceful teardown for the crash-restart harness
        (kubernetes_tpu/restart): a dead process flushes nothing,
        persists nothing, emits nothing — this only stops the
        instance's threads so a supervised in-process "kill" doesn't
        leak them across incarnations. The commit worker is shut down
        WITHOUT draining (drain re-raises the captured crash), the bind
        pool without recreation, the bank uploaders without their
        backlog flush, and the warm worker without persisting the
        ladder (the previous warmup already persisted it — a crash
        after warmup loses nothing). Idempotent; close() after abort()
        is a no-op."""
        if getattr(self, "_closed", False):
            return
        self._closed = True
        try:
            self._bind_pool.shutdown(wait=True)
        except BaseException:
            pass
        try:
            self._commit_pipe._pool.shutdown(wait=True)
        except BaseException:
            pass
        if self.health is not None:
            self.health.stop()
        for bank in (self.stage_bank, self.term_bank):
            if bank is not None:
                bank._stop.set()
                bank._wake.set()
                w = bank._worker
                if w is not None and w.is_alive():
                    w.join(timeout=5)
        if self._warm_svc is not None:
            self._warm_svc.stop()
            self._warm_svc.join()

    def wait_for_binds(self) -> None:
        """Drain the bind pipeline (tests/benchmarks). The commit pipeline
        settles first — its worker is what SUBMITS the lean bind chunks.
        No-op after close() (the pool must stay retired)."""
        if getattr(self, "_closed", False):
            return
        self._drain_commit()
        self._bind_pool.shutdown(wait=True)
        self._bind_pool = ThreadPoolExecutor(
            max_workers=self._bind_workers, thread_name_prefix="bind",
            initializer=register_thread_role, initargs=("bind",),
        )
