"""Event handlers: informer callbacks → cache + queue.

Reference: pkg/scheduler/eventhandlers.go AddAllEventHandlers (:380):
  assigned pods   → cache add/update/remove (confirming assumed pods, :255)
  pending pods    → scheduling queue (:214), filtered by scheduler name
  nodes           → cache + MoveAllToActiveQueue wake-up (:92-130)
  PV/PVC/Service  → MoveAllToActiveQueue (cluster events can unblock pods)
plus skipPodUpdate (:336): resource-version-only updates don't requeue.
"""

from __future__ import annotations

from typing import Optional

from ..api.types import Node, Pod
from ..state.cache import SchedulerCache
from ..state.queue import PriorityQueue


def _assigned(pod: Pod) -> bool:
    return bool(pod.node_name)


def _responsible(pod: Pod, scheduler_name: str) -> bool:
    return pod.scheduler_name == scheduler_name


class EventHandlers:
    """Wire an informer-like event source into the scheduler state."""

    def __init__(
        self,
        cache: SchedulerCache,
        queue: PriorityQueue,
        scheduler_name: str = "default-scheduler",
    ):
        self.cache = cache
        self.queue = queue
        self.scheduler_name = scheduler_name

    # -- pods ---------------------------------------------------------------

    # ktpu: thread-entry(informer)
    def on_pod_add(self, pod: Pod) -> None:
        if _assigned(pod):
            self.cache.add_pod(pod)
            self.queue.move_all_to_active()  # assignedPodAdded (:451 via queue)
        elif _responsible(pod, self.scheduler_name):
            self.queue.add(pod)

    # ktpu: thread-entry(informer)
    def on_pod_update(self, old: Pod, new: Pod) -> None:
        """The reference registers TWO filtered informers (eventhandlers.go:
        380-430): assigned pods feed the cache, pending ones the queue. An
        unassigned→assigned transition (our own bind echo) therefore arrives
        at the cache side as an ADD — which is what confirms the assumed
        pod (cache.go AddPod) — and leaves the queue side as a delete.
        skipPodUpdate (:336) guards only the QUEUE path."""
        if _assigned(new):
            if _assigned(old):
                self.cache.update_pod(old, new)
            else:
                self.cache.add_pod(new)  # bind echo: confirm the assume
                self.queue.delete(new)
            self.queue.move_all_to_active()
        elif _responsible(new, self.scheduler_name):
            if self._skip_pod_update(old, new):
                return
            self.queue.update(old, new)

    # ktpu: thread-entry(informer)
    def on_pod_delete(self, pod: Pod) -> None:
        if _assigned(pod):
            self.cache.remove_pod(pod)
            self.queue.move_all_to_active()
        else:
            self.queue.delete(pod)

    def _skip_pod_update(self, old: Pod, new: Pod) -> bool:
        """skipPodUpdate (eventhandlers.go:336): skip only when (1) the pod
        is ASSUMED in the cache (the update is likely the echo of our own
        bind), and (2) the objects are identical once ResourceVersion,
        Spec.NodeName and Annotations — the fields the scheduler/API server
        write — are stripped. Any real spec change must requeue."""
        if not self.cache.is_assumed(new.key()):
            return False
        import dataclasses

        strip = dict(resource_version="", node_name="", annotations={})
        return dataclasses.replace(old, **strip) == dataclasses.replace(new, **strip)

    # -- nodes --------------------------------------------------------------

    # ktpu: thread-entry(informer)
    def on_node_add(self, node: Node) -> None:
        self.cache.add_node(node)
        self.queue.move_all_to_active()

    # ktpu: thread-entry(informer)
    def on_node_update(self, old: Optional[Node], new: Node) -> None:
        self.cache.update_node(new)
        self.queue.move_all_to_active()

    # ktpu: thread-entry(informer)
    def on_node_delete(self, node: Node) -> None:
        self.cache.remove_node(node.name)

    # -- other cluster events (PV/PVC/Service/StorageClass) ------------------

    # ktpu: thread-entry(informer)
    def on_cluster_event(self) -> None:
        self.queue.move_all_to_active()
