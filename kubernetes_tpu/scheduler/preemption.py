"""Preemption: victim selection when no node fits.

Reference: core/generic_scheduler.go Preempt (:313),
selectNodesForPreemption (:1007), selectVictimsOnNode (:1104),
filterPodsWithPDBViolation (:1055), pickOneNodeForPreemption (:878),
nodesWherePreemptionMightHelp (:1218), podFitsOnNode's nominated-pods
two-pass rule (:612-697).

Host-side implementation over the oracle (preemption runs only for pods
that already failed the fast path — inherently rare, so scalar cost is
acceptable; vectorized victim search is a planned optimization).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..api.selectors import match_label_selector
from ..api.types import Pod, PodDisruptionBudget
from ..oracle.nodeinfo import DEFAULT_BIND_ALL_HOST_IP, NodeInfo, Snapshot
from ..oracle.predicates import (
    check_node_unschedulable,
    compute_predicate_metadata,
    get_pod_affinity_terms,
    get_pod_anti_affinity_terms,
    pod_fits_host,
    pod_fits_on_node,
    pod_match_node_selector,
    pod_tolerates_node_taints,
)

NominatedFn = Callable[[str], List[Pod]]


@dataclass
class Victims:
    pods: List[Pod]
    num_pdb_violations: int = 0


def _shadow_one(snapshot: Snapshot, node_name: str) -> Snapshot:
    """Copy-on-write snapshot that clones ONLY node_name's pod list (the one
    thing victim search / nominee simulation mutates); every other NodeInfo
    is shared with the source — O(nodes) references, not O(pods) copies."""
    shadow = Snapshot()
    for n, info in snapshot.node_infos.items():
        if n == node_name:
            si = shadow.add_node(info.node)
            si.set_pods(info.pods)
        else:
            shadow.node_infos[n] = info
    return shadow


def eligible_nominees(pod: Pod, node_name: str, nominated_fn: Optional[NominatedFn]) -> List[Pod]:
    """Nominated pods the two-pass rule must count for `pod` on this node:
    someone else's nomination with equal-or-higher priority
    (generic_scheduler.go:620-630)."""
    if nominated_fn is None:
        return []
    prio = pod.get_priority()
    return [
        p
        for p in nominated_fn(node_name)
        if p.key() != pod.key() and p.get_priority() >= prio
    ]


def fits_considering_nominated(
    pod: Pod,
    node_name: str,
    snapshot: Snapshot,
    nominated_fn: Optional[NominatedFn],
    meta=None,
) -> bool:
    """podFitsOnNode's two-pass rule (generic_scheduler.go:612-697): when
    the node has nominated pods of priority >= the incoming pod's, predicates
    must pass BOTH with those pods' resources/affinity counted AND without
    (nominated pods may never arrive, and their absence can break the
    incoming pod's required pod affinity)."""
    ni = snapshot.get(node_name)
    if ni is None:
        return False
    nominees = eligible_nominees(pod, node_name, nominated_fn)
    if meta is None:
        meta = compute_predicate_metadata(pod, snapshot)
    if not pod_fits_on_node(pod, ni, meta=meta)[0]:
        return False
    if not nominees:
        return True
    return fits_with_nominees(pod, node_name, snapshot, nominees, enabled=meta.enabled)


def fits_with_nominees(
    pod: Pod,
    node_name: str,
    snapshot: Snapshot,
    nominees: Sequence[Pod],
    enabled: Optional[frozenset] = None,
) -> bool:
    """The with-nominated-pods pass alone (callers have already verified the
    plain pass)."""
    shadow = _shadow_one(snapshot, node_name)
    sni = shadow.get(node_name)
    for p in nominees:
        sni.add_pod(p.with_node(node_name))
    meta2 = compute_predicate_metadata(pod, shadow, enabled=enabled)
    return pod_fits_on_node(pod, sni, meta=meta2)[0]


def pod_eligible_to_preempt_others(pod: Pod, snapshot: Snapshot) -> bool:
    """podEligibleToPreemptOthers (:847): a pod that already nominated a node
    where a lower-priority pod is terminating must wait."""
    if pod.nominated_node_name:
        ni = snapshot.get(pod.nominated_node_name)
        if ni is not None:
            for p in ni.pods:
                if p.deletion_timestamp is not None and p.get_priority() < pod.get_priority():
                    return False
    return True


def nodes_where_preemption_might_help(pod: Pod, snapshot: Snapshot) -> List[str]:
    """:1218 — skip nodes whose failure cannot be resolved by removing pods
    (node selector, taints, unschedulable, name pinning are unresolvable)."""
    out = []
    for name, ni in snapshot.node_infos.items():
        if not check_node_unschedulable(pod, ni):
            continue
        if not pod_fits_host(pod, ni):
            continue
        if not pod_match_node_selector(pod, ni):
            continue
        if not pod_tolerates_node_taints(pod, ni):
            continue
        out.append(name)
    return out


def _pods_violating_pdbs(
    pods: Sequence[Pod], pdbs: Sequence[PodDisruptionBudget]
) -> Tuple[List[Pod], List[Pod]]:
    """filterPodsWithPDBViolation (:1055): a pod 'violates' when it matches a
    PDB (same namespace, selector) whose disruptionsAllowed is exhausted."""
    violating, non_violating = [], []
    for p in pods:
        hit = False
        for pdb in pdbs:
            if pdb.namespace != p.namespace or pdb.selector is None:
                continue
            # an EMPTY selector matches nothing here (the reference does
            # `if selector.Empty() || !selector.Matches(...) { continue }`,
            # generic_scheduler.go:1069) — the opposite of the usual
            # empty-selector-matches-all label semantics
            if not pdb.selector.match_labels and not pdb.selector.match_expressions:
                continue
            if match_label_selector(pdb.selector, p.labels):
                if pdb.disruptions_allowed <= 0:
                    hit = True
        (violating if hit else non_violating).append(p)
    return violating, non_violating


def _importance(p: Pod) -> Tuple[int, float]:
    """util.MoreImportantPod sort key: higher priority first, then earlier
    start (approximated by creation timestamp)."""
    return (-p.get_priority(), p.creation_timestamp)


def select_victims_on_node(
    pod: Pod,
    node_name: str,
    snapshot: Snapshot,
    pdbs: Sequence[PodDisruptionBudget] = (),
    can_disrupt: Optional[Callable[[Pod], bool]] = None,
    extra_fit: Optional[Callable[[Pod, object], bool]] = None,
    enabled: Optional[frozenset] = None,
    static_meta=None,
) -> Optional[Victims]:
    """selectVictimsOnNode (:1104): remove ALL lower-priority pods; if the
    pod then fits, reprieve candidates most-important-first — PDB-protected
    pods get reprieved first; any that cannot be reprieved count as PDB
    violations for the tie-break.

    can_disrupt: extra victim eligibility (the driver excludes ASSUMED pods
    whose bind is still in flight — deleting those would corrupt the cache's
    capacity view; the reference tolerates this because victims die via API
    delete + informer echo)."""
    ni = snapshot.get(node_name)
    if ni is None:
        return None
    prio = pod.get_priority()
    potential = [
        p
        for p in ni.pods
        if p.get_priority() < prio and (can_disrupt is None or can_disrupt(p))
    ]
    if not potential:
        return None

    shadow = _shadow_one(snapshot, node_name)
    sni = shadow.get(node_name)
    victims_set = {id(p) for p in potential}
    sni.set_pods([p for p in sni.pods if id(p) not in victims_set])

    meta = static_meta if static_meta is not None else compute_predicate_metadata(
        pod, shadow, enabled=enabled
    )
    fits, _ = pod_fits_on_node(pod, sni, meta=meta)
    if fits and extra_fit is not None:
        # volume predicates etc.: evicting pods cannot cure a zone/volume
        # conflict, so the extra predicates must hold on the shadow node too
        fits = extra_fit(pod, sni)
    if not fits:
        return None

    violating, non_violating = _pods_violating_pdbs(potential, pdbs)
    victims: List[Pod] = []
    num_violations = 0

    def reprieve(p: Pod) -> bool:
        sni.add_pod(p)
        meta = static_meta if static_meta is not None else compute_predicate_metadata(
            pod, shadow, enabled=enabled
        )
        still_fits, _ = pod_fits_on_node(pod, sni, meta=meta)
        if still_fits and extra_fit is not None:
            still_fits = extra_fit(pod, sni)
        if not still_fits:
            sni.remove_pod(p)
            victims.append(p)
        return still_fits

    for p in sorted(violating, key=_importance):
        if not reprieve(p):
            num_violations += 1
    for p in sorted(non_violating, key=_importance):
        reprieve(p)
    if not victims:
        return None
    return Victims(pods=victims, num_pdb_violations=num_violations)


def _select_victims_fast(
    pod: Pod,
    ni: Optional[NodeInfo],
    pdbs: Sequence[PodDisruptionBudget],
    can_disrupt: Optional[Callable[[Pod], bool]],
    nominee_charge: Optional[Tuple[Dict[str, int], int]] = None,
) -> Optional[Victims]:
    """select_victims_on_node for the STATIC-metadata case (the affinity-free
    fast path in preempt()): with no (anti-)affinity or spread terms anywhere
    and the default predicate set, the only pod-dependent predicates are
    PodFitsResources and PodFitsHostPorts — the node-constant ones were
    already validated by nodes_where_preemption_might_help. So victim search
    needs NO shadow snapshot, NO NodeInfo mutation, and NO full predicate
    chain: just arithmetic over the node's incremental aggregates, mirroring
    pod_fits_resources' compare rules exactly (predicates.go:854 and :886-895
    semantics). This turns selectVictimsOnNode from ~100us+ into ~5us per
    candidate — the difference between 3 and 300 preemptions/s at 500 nodes.

    `nominee_charge` = (summed requests, pod count) of pods NOMINATED to
    this node (excluding the preemptor itself): the reference's victim-
    search fit check counts nominated pods (selectVictimsOnNode :1160 →
    podFitsOnNode pass 1) — without it a preemptor wave thrashes, each
    eviction's freed capacity making the next preemptor "fit". All
    nominees count regardless of priority (conservative vs the
    reference's >=-priority filter; matches ops/preempt's aggregate).

    Bit-identical to select_victims_on_node under the routing preconditions
    (enforced by test_preemption_fast_matches_oracle)."""
    if ni is None:
        return None
    prio = pod.get_priority()
    potential = [
        p
        for p in ni.pods
        if p.get_priority() < prio and (can_disrupt is None or can_disrupt(p))
    ]
    if not potential:
        return None
    from ..oracle.nodeinfo import accumulated_request
    from ..api.types import (
        RESOURCE_CPU,
        RESOURCE_EPHEMERAL_STORAGE,
        RESOURCE_MEMORY,
    )

    req = pod.resource_request()
    check_res = not all(v == 0 for k, v in req.items() if k != "pods")
    alloc = ni.node.allocatable_int()
    allowed = ni.allowed_pod_number()
    used = dict(ni.requested())
    count = len(ni.pods)
    if nominee_charge is not None:
        nreq, ncnt = nominee_charge
        for rname, val in nreq.items():
            used[rname] = used.get(rname, 0) + val
        count += ncnt
    pod_ports = pod.host_ports()
    for v in potential:
        for rname, val in accumulated_request(v).items():
            used[rname] = used.get(rname, 0) - val
    count -= len(potential)
    port_counts: Optional[Dict[Tuple[str, str, int], int]] = None
    if pod_ports:
        port_counts = {}
        victim_ids = {id(p) for p in potential}
        for p in ni.pods:
            if id(p) not in victim_ids:
                for t in p.host_ports():
                    port_counts[t] = port_counts.get(t, 0) + 1

    def fits() -> bool:
        # PodFitsResources (predicates.go:854): count always; cpu/mem/
        # ephemeral unconditionally when anything is requested; scalars
        # only when requested non-zero
        if count + 1 > allowed:
            return False
        if check_res:
            for name in (RESOURCE_CPU, RESOURCE_MEMORY, RESOURCE_EPHEMERAL_STORAGE):
                if alloc.get(name, 0) < req.get(name, 0) + used.get(name, 0):
                    return False
            for name, r in req.items():
                if name in (RESOURCE_CPU, RESOURCE_MEMORY, RESOURCE_EPHEMERAL_STORAGE, "pods"):
                    continue
                if r != 0 and alloc.get(name, 0) < r + used.get(name, 0):
                    return False
        if port_counts is not None:
            # HostPortInfo.CheckConflict: 0.0.0.0 conflicts with every IP
            # for the same (protocol, port)
            live = [t for t, c in port_counts.items() if c > 0]
            for proto, ip, port in pod_ports:
                if port <= 0:
                    continue
                if ip == DEFAULT_BIND_ALL_HOST_IP:
                    if any(up == port and upr == proto for upr, _, up in live):
                        return False
                else:
                    for upr, uip, up in live:
                        if up == port and upr == proto and uip in (DEFAULT_BIND_ALL_HOST_IP, ip):
                            return False
        return True

    if not fits():
        return None

    violating, non_violating = _pods_violating_pdbs(potential, pdbs)
    victims: List[Pod] = []
    num_violations = 0

    def reprieve(p: Pod) -> bool:
        nonlocal count
        for rname, val in accumulated_request(p).items():
            used[rname] = used.get(rname, 0) + val
        count += 1
        if port_counts is not None:
            for t in p.host_ports():
                port_counts[t] = port_counts.get(t, 0) + 1
        if fits():
            return True
        for rname, val in accumulated_request(p).items():
            used[rname] = used.get(rname, 0) - val
        count -= 1
        if port_counts is not None:
            for t in p.host_ports():
                port_counts[t] -= 1
        victims.append(p)
        return False

    for p in sorted(violating, key=_importance):
        if not reprieve(p):
            num_violations += 1
    for p in sorted(non_violating, key=_importance):
        reprieve(p)
    if not victims:
        return None
    return Victims(pods=victims, num_pdb_violations=num_violations)


def pick_one_node_for_preemption(candidates: Dict[str, Victims]) -> Optional[str]:
    """pickOneNodeForPreemption (:878) tie-break chain:
    1. fewest PDB violations  2. lowest highest-victim-priority
    3. smallest priority sum  4. fewest victims
    5. latest start time of the highest-priority victim  6. first."""
    if not candidates:
        return None
    names = list(candidates)

    def keep_min(names: List[str], keyfn) -> List[str]:
        vals = {n: keyfn(candidates[n]) for n in names}
        m = min(vals.values())
        return [n for n in names if vals[n] == m]

    names = keep_min(names, lambda v: v.num_pdb_violations)
    if len(names) == 1:
        return names[0]
    names = keep_min(names, lambda v: max(p.get_priority() for p in v.pods))
    if len(names) == 1:
        return names[0]
    names = keep_min(names, lambda v: sum(p.get_priority() for p in v.pods))
    if len(names) == 1:
        return names[0]
    names = keep_min(names, lambda v: len(v.pods))
    if len(names) == 1:
        return names[0]
    # latest (max) start time among each node's highest-priority victim
    names = keep_min(
        names,
        lambda v: -max(
            p.creation_timestamp
            for p in v.pods
            if p.get_priority() == max(q.get_priority() for q in v.pods)
        ),
    )
    return names[0]


def batch_preempt_device(
    pods: Sequence[Pod],
    snapshot: Snapshot,
    pdbs: Sequence[PodDisruptionBudget] = (),
    can_disrupt: Optional[Callable[[Pod], bool]] = None,
    nominated: Sequence[Tuple[str, Pod]] = (),
    max_victim_slots: int = 64,
    max_bytes: int = 64 << 20,
    pod_bucket: Optional[int] = None,
    victim_bucket: Optional[int] = None,
    plan=None,
):
    """Vectorized victim search for a whole batch of failed pods on DEVICE
    (ops/preempt.preempt_batch): one dispatch evaluates every preemptor
    against every candidate node sequentially-consistently (earlier
    preemptors' victims vanish from later steps' state), replacing
    O(preemptors x nodes x victims) host Python with a scan.

    Returns a list aligned with `pods` of (node_name or None, [victim Pod
    objects in reprieve order], fits_free) — fits_free means the pod fits a
    candidate node WITHOUT eviction at its step's state (a stale -1; the
    caller should retry it instead of failing it cold) — or None when the
    batch/cluster is outside
    the kernel's exact domain (any (anti-)affinity or spread terms in play,
    a ported preemptor, or victim-slot/memory overflow), in which case the
    caller walks the scalar path. The caller MUST re-verify each plan
    against its live snapshot before applying (the driver does — see
    Scheduler._preempt_deferred) since this function takes no locks.
    """
    # eligibility: the kernel models resources + pod count only (the static
    # case — same preconditions as preempt()'s fast path). Any required
    # anti-affinity on existing pods, or terms/ports on a preemptor, falls
    # back to the scalar oracle.
    for ni in snapshot.node_infos.values():
        for ep in ni.pods_with_affinity():
            if get_pod_anti_affinity_terms(ep.affinity):
                return None
    for p in pods:
        if (
            get_pod_affinity_terms(p.affinity)
            or get_pod_anti_affinity_terms(p.affinity)
            or p.topology_spread_constraints
            or p.host_ports()
            or not pod_eligible_to_preempt_others(p, snapshot)
        ):
            return None

    import numpy as np

    names = list(snapshot.node_infos)
    n = len(names)
    if n == 0:
        return None
    # local resource-slot map (cpu/mem/ephemeral fixed; scalars as seen) —
    # self-contained, independent of the mirror's vocab/rows
    slots: Dict[str, int] = {}

    def slot_of(rname: str) -> int:
        s = slots.get(rname)
        if s is None:
            s = len(slots)
            slots[rname] = s
        return s

    from ..api.types import (
        RESOURCE_CPU,
        RESOURCE_EPHEMERAL_STORAGE,
        RESOURCE_MEMORY,
        RESOURCE_PODS,
    )
    from ..oracle.nodeinfo import accumulated_request

    for rn in (RESOURCE_CPU, RESOURCE_MEMORY, RESOURCE_EPHEMERAL_STORAGE):
        slot_of(rn)
    reqs = []
    for p in pods:
        reqs.append({k: v for k, v in p.resource_request().items() if k != RESOURCE_PODS})
        for rn in reqs[-1]:
            slot_of(rn)
    for _, npod in nominated:
        for rn in accumulated_request(npod):
            if rn != RESOURCE_PODS:
                slot_of(rn)
    victims_by_node: List[List[Pod]] = []
    vio_by_node: List[set] = []
    vict_reqs: List[List[Dict[str, int]]] = []
    v_max = 1
    for name in names:
        ni = snapshot.node_infos[name]
        pool = [p for p in ni.pods if can_disrupt is None or can_disrupt(p)]
        violating, non_violating = _pods_violating_pdbs(pool, pdbs)
        vio_by_node.append({id(p) for p in violating})
        ordered = sorted(violating, key=_importance) + sorted(non_violating, key=_importance)
        victims_by_node.append(ordered)
        rr = []
        for p in ordered:
            d = {k: v for k, v in accumulated_request(p).items() if k != RESOURCE_PODS}
            for rn in d:
                slot_of(rn)
            rr.append(d)
        vict_reqs.append(rr)
        v_max = max(v_max, len(ordered))
    if v_max > max_victim_slots:
        return None
    from ..state.tensors import _bucket, _node_bucket

    r_cap = _bucket(len(slots), 8)
    v_cap = max(victim_bucket or 0, _bucket(v_max, 8))
    n_pad_guard = _node_bucket(n)
    # guard the PADDED allocation (the victim tensors are built at the
    # node-axis rung, up to ~2x the raw node count)
    if n_pad_guard * v_cap * r_cap * 8 > max_bytes:
        return None

    b = len(pods)
    # ladder-padded axes (one XLA signature per cluster shape, not per
    # fails-count): preemptors to the caller's monotone bucket, nodes to
    # the node-axis rung. Padded rows are inert — p_valid False kills
    # their scan step's pick; node_valid/cand False keep phantom nodes
    # out of every fit check.
    b_pad = max(pod_bucket or 0, _bucket(b, 8))
    n_pad = n_pad_guard
    p_req = np.zeros((b_pad, r_cap), np.int64)
    p_req_any = np.zeros(b_pad, bool)
    p_prio = np.zeros(b_pad, np.int32)
    p_valid = np.zeros(b_pad, bool)
    p_valid[:b] = True
    for k, d in enumerate(reqs):
        for rn, val in d.items():
            p_req[k, slots[rn]] = val
        p_req_any[k] = any(v != 0 for v in d.values())
        p_prio[k] = pods[k].get_priority()
    vict_req = np.zeros((n_pad, v_cap, r_cap), np.int64)
    vict_prio = np.zeros((n_pad, v_cap), np.int32)
    vict_ts = np.zeros((n_pad, v_cap), np.int64)
    vict_pdb = np.zeros((n_pad, v_cap), bool)
    vict_valid = np.zeros((n_pad, v_cap), bool)
    free0 = np.zeros((n_pad, r_cap), np.int64)
    count_free0 = np.zeros(n_pad, np.int32)
    node_valid = np.zeros(n_pad, bool)
    node_valid[:n] = True
    # out-of-batch nominee reservations (the queue's nominated index minus
    # this batch): charged into the fit checks, exactly as podFitsOnNode's
    # pass 1 counts nominated pods
    nom_extra0 = np.zeros((n_pad, r_cap), np.int64)
    nom_cnt0 = np.zeros(n_pad, np.int32)
    row_of_name = {name: i for i, name in enumerate(names)}
    for node, npod in nominated:
        row = row_of_name.get(node)
        if row is None:
            continue
        for rn, val in accumulated_request(npod).items():
            if rn != RESOURCE_PODS:
                nom_extra0[row, slots[rn]] += val
        nom_cnt0[row] += 1
    for i, name in enumerate(names):
        ni = snapshot.node_infos[name]
        alloc = ni.node.allocatable_int()
        used = ni.requested()
        for rn, s in slots.items():
            free0[i, s] = alloc.get(rn, 0) - used.get(rn, 0)
        count_free0[i] = ni.allowed_pod_number() - len(ni.pods)
        pool = victims_by_node[i]
        vio_set = vio_by_node[i]
        for j, p in enumerate(pool):
            vict_valid[i, j] = True
            vict_prio[i, j] = p.get_priority()
            vict_ts[i, j] = int(p.creation_timestamp * 1e6)
            vict_pdb[i, j] = id(p) in vio_set
            for rn, val in vict_reqs[i][j].items():
                vict_req[i, j, slots[rn]] = val
    # candidate mask: the four unresolvable predicates, once per UNIQUE
    # spec (replicas share the row) — nodesWherePreemptionMightHelp :1218
    from ..state.tensors import spec_key

    cand = np.zeros((b_pad, n_pad), bool)
    mask_of: Dict[object, np.ndarray] = {}
    for k, p in enumerate(pods):
        key = spec_key(p)
        m = mask_of.get(key)
        if m is None:
            m = np.array(
                [
                    check_node_unschedulable(p, snapshot.node_infos[nm])
                    and pod_fits_host(p, snapshot.node_infos[nm])
                    and pod_match_node_selector(p, snapshot.node_infos[nm])
                    and pod_tolerates_node_taints(p, snapshot.node_infos[nm])
                    for nm in names
                ],
                bool,
            )
            mask_of[key] = m
        cand[k, :n] = m

    import time as _time

    import jax
    import jax.numpy as jnp

    from ..ops.preempt import preempt_batch

    # route through the compile plan (when the caller has one): the kernel
    # signature is (b_pad, n_pad, v_cap, r_cap) — padded axes make it one
    # spec per cluster shape, which warmup pre-compiles
    spec = None
    spec_known = True
    if plan is not None:
        from ..compile.ladder import KIND_PREEMPT, SolveSpec

        spec = SolveSpec(kind=KIND_PREEMPT, b=b_pad, n=n_pad, v=v_cap, r=r_cap)
        spec_known = plan.admit(spec)
    t_disp = _time.perf_counter()
    nodes_out, victims_out, fits_free_out = preempt_batch(
        jnp.asarray(cand),
        jnp.asarray(p_req),
        jnp.asarray(p_req_any),
        jnp.asarray(p_prio),
        jnp.asarray(p_valid),
        jnp.asarray(vict_req),
        jnp.asarray(vict_prio),
        jnp.asarray(vict_ts),
        jnp.asarray(vict_pdb),
        jnp.asarray(vict_valid),
        jnp.asarray(free0),
        jnp.asarray(count_free0),
        jnp.asarray(node_valid),
        jnp.asarray(nom_extra0),
        jnp.asarray(nom_cnt0),
    )
    nodes_out, victims_out, fits_free_out = jax.device_get(
        (nodes_out, victims_out, fits_free_out)
    )
    if plan is not None and not spec_known:
        # dispatch+fetch wall as the compile-stall upper bound (device_get
        # blocks on execution; a hot kernel makes this milliseconds)
        from ..compile.plan import SOURCE_INLINE

        plan.note_compiled(
            spec, _time.perf_counter() - t_disp,
            SOURCE_INLINE if plan.warmed else "warmup",
        )
    plans = []
    for k in range(b):
        row = int(nodes_out[k])
        if row < 0:
            # fits_free: no eviction NEEDED (the pod fits somewhere as-is —
            # a stale -1); plain None: no eviction POSSIBLE
            plans.append((None, [], bool(fits_free_out[k])))
            continue
        mask = victims_out[k]
        plans.append(
            (
                names[row],
                [p for j, p in enumerate(victims_by_node[row]) if mask[j]],
                False,
            )
        )
    return plans


def preempt(
    pod: Pod,
    snapshot: Snapshot,
    pdbs: Sequence[PodDisruptionBudget] = (),
    nominated_fn: Optional[NominatedFn] = None,
    can_disrupt: Optional[Callable[[Pod], bool]] = None,
    extra_fit: Optional[Callable[[Pod, object], bool]] = None,
    enabled: Optional[frozenset] = None,
) -> Tuple[Optional[str], List[Pod], List[str]]:
    """Preempt (:313): returns (node, victims, nominated pod keys to clear).
    The third element lists LOWER-priority pods nominated to the chosen node
    (from the scheduling queue's nominated index, :346-360) whose nomination
    should be cleared — their node is about to be consumed by this pod."""
    if not pod_eligible_to_preempt_others(pod, snapshot):
        return None, [], []
    potential = nodes_where_preemption_might_help(pod, snapshot)
    if not potential:
        return None, [], []
    # AFFINITY-FREE FAST PATH: when the preemptor carries no (anti-)affinity
    # terms and no spread constraints, AND no existing pod carries a
    # REQUIRED ANTI-affinity term (the only existing-pod terms the
    # predicate metadata reads — preferred/positive terms never enter the
    # pair maps), the metadata is identical for every candidate shadow
    # (victim removal cannot change empty pair maps) — compute it once
    # instead of once per node per reprieve. This is what makes preemption
    # O(candidates x victims) instead of O(candidates x victims x cluster)
    # on plain-resource and preferred-only workloads.
    static_meta = None
    if (
        not get_pod_affinity_terms(pod.affinity)
        and not get_pod_anti_affinity_terms(pod.affinity)
        and not pod.topology_spread_constraints
        and not any(
            get_pod_anti_affinity_terms(ep.affinity)
            for ni in snapshot.node_infos.values()
            for ep in ni.pods_with_affinity()
        )
    ):
        static_meta = compute_predicate_metadata(pod, snapshot, enabled=enabled)
    # with static metadata, no volume seam, and the default predicate set,
    # the shadow-snapshot machinery is pure overhead — victim search is
    # exact arithmetic over each node's incremental aggregates
    use_fast = static_meta is not None and extra_fit is None and enabled is None
    candidates: Dict[str, Victims] = {}
    for name in potential:
        if use_fast:
            v = _select_victims_fast(pod, snapshot.get(name), pdbs, can_disrupt)
        else:
            v = select_victims_on_node(
                pod, name, snapshot, pdbs=pdbs, can_disrupt=can_disrupt,
                extra_fit=extra_fit, enabled=enabled, static_meta=static_meta,
            )
        if v is not None:
            candidates[name] = v
    chosen = pick_one_node_for_preemption(candidates)
    if chosen is None:
        return None, [], []
    # lower-priority pending pods nominated to the chosen node lose their
    # nomination (getLowerPriorityNominatedPods :1240)
    clear: List[str] = []
    prio = pod.get_priority()
    if nominated_fn is not None:
        for p in nominated_fn(chosen):
            if p.get_priority() < prio:
                clear.append(p.key())
    return chosen, candidates[chosen].pods, clear
