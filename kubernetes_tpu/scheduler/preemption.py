"""Preemption: victim selection when no node fits.

Reference: core/generic_scheduler.go Preempt (:313),
selectNodesForPreemption (:1007), selectVictimsOnNode (:1104),
pickOneNodeForPreemption (:878), nodesWherePreemptionMightHelp (:1218).

Host-side implementation over the oracle (preemption runs only for pods
that already failed the fast path — inherently rare, so scalar cost is
acceptable; vectorized victim search is a planned optimization).
"""

from __future__ import annotations

import copy
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ..api.types import Pod
from ..oracle.nodeinfo import NodeInfo, Snapshot
from ..oracle.predicates import (
    check_node_unschedulable,
    compute_predicate_metadata,
    pod_fits_host,
    pod_fits_on_node,
    pod_match_node_selector,
    pod_tolerates_node_taints,
)


@dataclass
class Victims:
    pods: List[Pod]
    num_pdb_violations: int = 0


def pod_eligible_to_preempt_others(pod: Pod, snapshot: Snapshot) -> bool:
    """podEligibleToPreemptOthers (:847): a pod that already nominated a node
    where a lower-priority pod is terminating must wait."""
    if pod.nominated_node_name:
        ni = snapshot.get(pod.nominated_node_name)
        if ni is not None:
            for p in ni.pods:
                if p.deletion_timestamp is not None and p.get_priority() < pod.get_priority():
                    return False
    return True


def nodes_where_preemption_might_help(pod: Pod, snapshot: Snapshot) -> List[str]:
    """:1218 — skip nodes whose failure cannot be resolved by removing pods
    (node selector, taints, unschedulable, name pinning are unresolvable)."""
    out = []
    for name, ni in snapshot.node_infos.items():
        if not check_node_unschedulable(pod, ni):
            continue
        if not pod_fits_host(pod, ni):
            continue
        if not pod_match_node_selector(pod, ni):
            continue
        if not pod_tolerates_node_taints(pod, ni):
            continue
        out.append(name)
    return out


def select_victims_on_node(pod: Pod, node_name: str, snapshot: Snapshot) -> Optional[Victims]:
    """selectVictimsOnNode (:1104): remove ALL lower-priority pods; if the
    pod then fits, reprieve victims (highest priority first) keeping every
    one whose re-addition still lets the pod fit."""
    ni = snapshot.get(node_name)
    if ni is None:
        return None
    prio = pod.get_priority()
    potential = [p for p in ni.pods if p.get_priority() < prio]
    if not potential:
        return None

    # shadow snapshot: same objects, shallow per-node pod lists
    shadow = Snapshot()
    for n, info in snapshot.node_infos.items():
        si = shadow.add_node(info.node)
        si.pods = list(info.pods)
    sni = shadow.get(node_name)
    sni.pods = [p for p in sni.pods if p.get_priority() >= prio]

    meta = compute_predicate_metadata(pod, shadow)
    fits, _ = pod_fits_on_node(pod, sni, meta=meta)
    if not fits:
        return None

    victims: List[Pod] = []
    # reprieve in descending priority (then earlier start first — approximated
    # by creation timestamp, util.MoreImportantPod)
    for p in sorted(potential, key=lambda x: (-x.get_priority(), x.creation_timestamp)):
        sni.pods.append(p)
        meta = compute_predicate_metadata(pod, shadow)
        still_fits, _ = pod_fits_on_node(pod, sni, meta=meta)
        if not still_fits:
            sni.pods.remove(p)
            victims.append(p)
    if not victims:
        return None
    return Victims(pods=victims)


def pick_one_node_for_preemption(candidates: Dict[str, Victims]) -> Optional[str]:
    """pickOneNodeForPreemption (:878) tie-break chain:
    1. fewest PDB violations  2. lowest highest-victim-priority
    3. smallest priority sum  4. fewest victims
    5. latest start time of the highest-priority victim  6. first."""
    if not candidates:
        return None
    names = list(candidates)

    def keep_min(names: List[str], keyfn) -> List[str]:
        vals = {n: keyfn(candidates[n]) for n in names}
        m = min(vals.values())
        return [n for n in names if vals[n] == m]

    names = keep_min(names, lambda v: v.num_pdb_violations)
    if len(names) == 1:
        return names[0]
    names = keep_min(names, lambda v: max(p.get_priority() for p in v.pods))
    if len(names) == 1:
        return names[0]
    names = keep_min(names, lambda v: sum(p.get_priority() for p in v.pods))
    if len(names) == 1:
        return names[0]
    names = keep_min(names, lambda v: len(v.pods))
    if len(names) == 1:
        return names[0]
    # latest (max) start time among each node's highest-priority victim
    names = keep_min(
        names,
        lambda v: -max(
            p.creation_timestamp
            for p in v.pods
            if p.get_priority() == max(q.get_priority() for q in v.pods)
        ),
    )
    return names[0]


def preempt(pod: Pod, snapshot: Snapshot) -> Tuple[Optional[str], List[Pod], List[str]]:
    """Preempt (:313): returns (node, victims, nominated pod keys to clear).
    The third element lists LOWER-priority pods nominated to the chosen node
    whose nomination should be cleared (:346-360)."""
    if not pod_eligible_to_preempt_others(pod, snapshot):
        return None, [], []
    potential = nodes_where_preemption_might_help(pod, snapshot)
    candidates: Dict[str, Victims] = {}
    for name in potential:
        v = select_victims_on_node(pod, name, snapshot)
        if v is not None:
            candidates[name] = v
    chosen = pick_one_node_for_preemption(candidates)
    if chosen is None:
        return None, [], []
    # lower-priority nominated pods on the chosen node lose their nomination
    clear: List[str] = []
    ni = snapshot.get(chosen)
    prio = pod.get_priority()
    if ni is not None:
        for p in ni.pods:
            if p.nominated_node_name == chosen and p.get_priority() < prio:
                clear.append(p.key())
    return chosen, candidates[chosen].pods, clear
