"""Preemption: victim selection when no node fits.

Reference: core/generic_scheduler.go Preempt (:313),
selectNodesForPreemption (:1007), selectVictimsOnNode (:1104),
filterPodsWithPDBViolation (:1055), pickOneNodeForPreemption (:878),
nodesWherePreemptionMightHelp (:1218), podFitsOnNode's nominated-pods
two-pass rule (:612-697).

Host-side implementation over the oracle (preemption runs only for pods
that already failed the fast path — inherently rare, so scalar cost is
acceptable; vectorized victim search is a planned optimization).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..api.selectors import match_label_selector
from ..api.types import Pod, PodDisruptionBudget
from ..oracle.nodeinfo import NodeInfo, Snapshot
from ..oracle.predicates import (
    check_node_unschedulable,
    compute_predicate_metadata,
    get_pod_affinity_terms,
    get_pod_anti_affinity_terms,
    pod_fits_host,
    pod_fits_on_node,
    pod_match_node_selector,
    pod_tolerates_node_taints,
)

NominatedFn = Callable[[str], List[Pod]]


@dataclass
class Victims:
    pods: List[Pod]
    num_pdb_violations: int = 0


def _shadow_one(snapshot: Snapshot, node_name: str) -> Snapshot:
    """Copy-on-write snapshot that clones ONLY node_name's pod list (the one
    thing victim search / nominee simulation mutates); every other NodeInfo
    is shared with the source — O(nodes) references, not O(pods) copies."""
    shadow = Snapshot()
    for n, info in snapshot.node_infos.items():
        if n == node_name:
            si = shadow.add_node(info.node)
            si.set_pods(info.pods)
        else:
            shadow.node_infos[n] = info
    return shadow


def eligible_nominees(pod: Pod, node_name: str, nominated_fn: Optional[NominatedFn]) -> List[Pod]:
    """Nominated pods the two-pass rule must count for `pod` on this node:
    someone else's nomination with equal-or-higher priority
    (generic_scheduler.go:620-630)."""
    if nominated_fn is None:
        return []
    prio = pod.get_priority()
    return [
        p
        for p in nominated_fn(node_name)
        if p.key() != pod.key() and p.get_priority() >= prio
    ]


def fits_considering_nominated(
    pod: Pod,
    node_name: str,
    snapshot: Snapshot,
    nominated_fn: Optional[NominatedFn],
    meta=None,
) -> bool:
    """podFitsOnNode's two-pass rule (generic_scheduler.go:612-697): when
    the node has nominated pods of priority >= the incoming pod's, predicates
    must pass BOTH with those pods' resources/affinity counted AND without
    (nominated pods may never arrive, and their absence can break the
    incoming pod's required pod affinity)."""
    ni = snapshot.get(node_name)
    if ni is None:
        return False
    nominees = eligible_nominees(pod, node_name, nominated_fn)
    if meta is None:
        meta = compute_predicate_metadata(pod, snapshot)
    if not pod_fits_on_node(pod, ni, meta=meta)[0]:
        return False
    if not nominees:
        return True
    return fits_with_nominees(pod, node_name, snapshot, nominees, enabled=meta.enabled)


def fits_with_nominees(
    pod: Pod,
    node_name: str,
    snapshot: Snapshot,
    nominees: Sequence[Pod],
    enabled: Optional[frozenset] = None,
) -> bool:
    """The with-nominated-pods pass alone (callers have already verified the
    plain pass)."""
    shadow = _shadow_one(snapshot, node_name)
    sni = shadow.get(node_name)
    for p in nominees:
        sni.add_pod(p.with_node(node_name))
    meta2 = compute_predicate_metadata(pod, shadow, enabled=enabled)
    return pod_fits_on_node(pod, sni, meta=meta2)[0]


def pod_eligible_to_preempt_others(pod: Pod, snapshot: Snapshot) -> bool:
    """podEligibleToPreemptOthers (:847): a pod that already nominated a node
    where a lower-priority pod is terminating must wait."""
    if pod.nominated_node_name:
        ni = snapshot.get(pod.nominated_node_name)
        if ni is not None:
            for p in ni.pods:
                if p.deletion_timestamp is not None and p.get_priority() < pod.get_priority():
                    return False
    return True


def nodes_where_preemption_might_help(pod: Pod, snapshot: Snapshot) -> List[str]:
    """:1218 — skip nodes whose failure cannot be resolved by removing pods
    (node selector, taints, unschedulable, name pinning are unresolvable)."""
    out = []
    for name, ni in snapshot.node_infos.items():
        if not check_node_unschedulable(pod, ni):
            continue
        if not pod_fits_host(pod, ni):
            continue
        if not pod_match_node_selector(pod, ni):
            continue
        if not pod_tolerates_node_taints(pod, ni):
            continue
        out.append(name)
    return out


def _pods_violating_pdbs(
    pods: Sequence[Pod], pdbs: Sequence[PodDisruptionBudget]
) -> Tuple[List[Pod], List[Pod]]:
    """filterPodsWithPDBViolation (:1055): a pod 'violates' when it matches a
    PDB (same namespace, selector) whose disruptionsAllowed is exhausted."""
    violating, non_violating = [], []
    for p in pods:
        hit = False
        for pdb in pdbs:
            if pdb.namespace != p.namespace or pdb.selector is None:
                continue
            # an EMPTY selector matches nothing here (the reference does
            # `if selector.Empty() || !selector.Matches(...) { continue }`,
            # generic_scheduler.go:1069) — the opposite of the usual
            # empty-selector-matches-all label semantics
            if not pdb.selector.match_labels and not pdb.selector.match_expressions:
                continue
            if match_label_selector(pdb.selector, p.labels):
                if pdb.disruptions_allowed <= 0:
                    hit = True
        (violating if hit else non_violating).append(p)
    return violating, non_violating


def _importance(p: Pod) -> Tuple[int, float]:
    """util.MoreImportantPod sort key: higher priority first, then earlier
    start (approximated by creation timestamp)."""
    return (-p.get_priority(), p.creation_timestamp)


def select_victims_on_node(
    pod: Pod,
    node_name: str,
    snapshot: Snapshot,
    pdbs: Sequence[PodDisruptionBudget] = (),
    can_disrupt: Optional[Callable[[Pod], bool]] = None,
    extra_fit: Optional[Callable[[Pod, object], bool]] = None,
    enabled: Optional[frozenset] = None,
    static_meta=None,
) -> Optional[Victims]:
    """selectVictimsOnNode (:1104): remove ALL lower-priority pods; if the
    pod then fits, reprieve candidates most-important-first — PDB-protected
    pods get reprieved first; any that cannot be reprieved count as PDB
    violations for the tie-break.

    can_disrupt: extra victim eligibility (the driver excludes ASSUMED pods
    whose bind is still in flight — deleting those would corrupt the cache's
    capacity view; the reference tolerates this because victims die via API
    delete + informer echo)."""
    ni = snapshot.get(node_name)
    if ni is None:
        return None
    prio = pod.get_priority()
    potential = [
        p
        for p in ni.pods
        if p.get_priority() < prio and (can_disrupt is None or can_disrupt(p))
    ]
    if not potential:
        return None

    shadow = _shadow_one(snapshot, node_name)
    sni = shadow.get(node_name)
    victims_set = {id(p) for p in potential}
    sni.set_pods([p for p in sni.pods if id(p) not in victims_set])

    meta = static_meta if static_meta is not None else compute_predicate_metadata(
        pod, shadow, enabled=enabled
    )
    fits, _ = pod_fits_on_node(pod, sni, meta=meta)
    if fits and extra_fit is not None:
        # volume predicates etc.: evicting pods cannot cure a zone/volume
        # conflict, so the extra predicates must hold on the shadow node too
        fits = extra_fit(pod, sni)
    if not fits:
        return None

    violating, non_violating = _pods_violating_pdbs(potential, pdbs)
    victims: List[Pod] = []
    num_violations = 0

    def reprieve(p: Pod) -> bool:
        sni.add_pod(p)
        meta = static_meta if static_meta is not None else compute_predicate_metadata(
            pod, shadow, enabled=enabled
        )
        still_fits, _ = pod_fits_on_node(pod, sni, meta=meta)
        if still_fits and extra_fit is not None:
            still_fits = extra_fit(pod, sni)
        if not still_fits:
            sni.remove_pod(p)
            victims.append(p)
        return still_fits

    for p in sorted(violating, key=_importance):
        if not reprieve(p):
            num_violations += 1
    for p in sorted(non_violating, key=_importance):
        reprieve(p)
    if not victims:
        return None
    return Victims(pods=victims, num_pdb_violations=num_violations)


def pick_one_node_for_preemption(candidates: Dict[str, Victims]) -> Optional[str]:
    """pickOneNodeForPreemption (:878) tie-break chain:
    1. fewest PDB violations  2. lowest highest-victim-priority
    3. smallest priority sum  4. fewest victims
    5. latest start time of the highest-priority victim  6. first."""
    if not candidates:
        return None
    names = list(candidates)

    def keep_min(names: List[str], keyfn) -> List[str]:
        vals = {n: keyfn(candidates[n]) for n in names}
        m = min(vals.values())
        return [n for n in names if vals[n] == m]

    names = keep_min(names, lambda v: v.num_pdb_violations)
    if len(names) == 1:
        return names[0]
    names = keep_min(names, lambda v: max(p.get_priority() for p in v.pods))
    if len(names) == 1:
        return names[0]
    names = keep_min(names, lambda v: sum(p.get_priority() for p in v.pods))
    if len(names) == 1:
        return names[0]
    names = keep_min(names, lambda v: len(v.pods))
    if len(names) == 1:
        return names[0]
    # latest (max) start time among each node's highest-priority victim
    names = keep_min(
        names,
        lambda v: -max(
            p.creation_timestamp
            for p in v.pods
            if p.get_priority() == max(q.get_priority() for q in v.pods)
        ),
    )
    return names[0]


def preempt(
    pod: Pod,
    snapshot: Snapshot,
    pdbs: Sequence[PodDisruptionBudget] = (),
    nominated_fn: Optional[NominatedFn] = None,
    can_disrupt: Optional[Callable[[Pod], bool]] = None,
    extra_fit: Optional[Callable[[Pod, object], bool]] = None,
    enabled: Optional[frozenset] = None,
) -> Tuple[Optional[str], List[Pod], List[str]]:
    """Preempt (:313): returns (node, victims, nominated pod keys to clear).
    The third element lists LOWER-priority pods nominated to the chosen node
    (from the scheduling queue's nominated index, :346-360) whose nomination
    should be cleared — their node is about to be consumed by this pod."""
    if not pod_eligible_to_preempt_others(pod, snapshot):
        return None, [], []
    potential = nodes_where_preemption_might_help(pod, snapshot)
    if not potential:
        return None, [], []
    # AFFINITY-FREE FAST PATH: when the preemptor carries no (anti-)affinity
    # terms and no spread constraints, AND no existing pod carries a
    # REQUIRED ANTI-affinity term (the only existing-pod terms the
    # predicate metadata reads — preferred/positive terms never enter the
    # pair maps), the metadata is identical for every candidate shadow
    # (victim removal cannot change empty pair maps) — compute it once
    # instead of once per node per reprieve. This is what makes preemption
    # O(candidates x victims) instead of O(candidates x victims x cluster)
    # on plain-resource and preferred-only workloads.
    static_meta = None
    if (
        not get_pod_affinity_terms(pod.affinity)
        and not get_pod_anti_affinity_terms(pod.affinity)
        and not pod.topology_spread_constraints
        and not any(
            get_pod_anti_affinity_terms(ep.affinity)
            for ni in snapshot.node_infos.values()
            for ep in ni.pods_with_affinity()
        )
    ):
        static_meta = compute_predicate_metadata(pod, snapshot, enabled=enabled)
    candidates: Dict[str, Victims] = {}
    for name in potential:
        v = select_victims_on_node(
            pod, name, snapshot, pdbs=pdbs, can_disrupt=can_disrupt,
            extra_fit=extra_fit, enabled=enabled, static_meta=static_meta,
        )
        if v is not None:
            candidates[name] = v
    chosen = pick_one_node_for_preemption(candidates)
    if chosen is None:
        return None, [], []
    # lower-priority pending pods nominated to the chosen node lose their
    # nomination (getLowerPriorityNominatedPods :1240)
    clear: List[str] = []
    prio = pod.get_priority()
    if nominated_fn is not None:
        for p in nominated_fn(chosen):
            if p.get_priority() < prio:
                clear.append(p.key())
    return chosen, candidates[chosen].pods, clear
