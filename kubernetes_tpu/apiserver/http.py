"""HTTP transport for the fake apiserver: REST list+watch on k8s wire JSON.

The reference's integration seam is exactly this protocol — reflectors
LIST then WATCH a resource over HTTP (tools/cache/reflector.go:184), the
server streaming chunked watch events from its cacher
(storage/cacher/cacher.go:234), with 410 Gone forcing a relist after
compaction. Serving it makes the in-process store reachable by
out-of-process clients: a second scheduler replica, the debug CLI, or a
real kubectl-style tool.

Routes (apiVersion collapsed — kinds are top-level):
  GET    /api/v1/{kind}                          list → {"kind": "...List",
         "items": [...], "metadata": {"resourceVersion": "N"}}
  GET    /api/v1/{kind}?watch=1&resourceVersion=N   chunked watch stream of
         {"type": "ADDED|MODIFIED|DELETED", "object": {...}} lines
         (Transfer-Encoding: chunked, one JSON object per chunk — the k8s
         watch framing); HTTP 410 when N is compacted
  POST   /api/v1/{kind}                          create (JSON body)
  GET    /api/v1/{kind}/{ns}/{name}              get (cluster-scoped kinds
         — nodes — take /{name} alone)
  PUT    /api/v1/{kind}/{ns}/{name}              update (409 on stale
         resourceVersion when the body carries one)
  DELETE /api/v1/{kind}/{ns}/{name}              delete
  POST   /api/v1/pods/{ns}/{name}/binding        bind subresource
         ({"target": {"name": node}}, registry BindingREST semantics)

Namespaced paths (the reference's canonical shape for namespaced kinds):
  GET    /api/v1/namespaces/{ns}/{kind}              list restricted to {ns},
         authorized against {ns} (a namespaced RoleBinding suffices —
         bare /api/v1/{kind} list/watch stays cluster-scope authorized)
  GET    /api/v1/namespaces/{ns}/{kind}?watch=1      watch, events outside
         {ns} filtered out
  POST   /api/v1/namespaces/{ns}/{kind}              create in {ns} (body
         namespace defaults to the path; mismatch → 400)
  GET/PUT/DELETE /api/v1/namespaces/{ns}/{kind}/{name}   item verbs
  POST   /api/v1/namespaces/{ns}/pods/{name}/binding    bind subresource
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from ..api.types import (
    cronjob_from_k8s,
    cronjob_to_k8s,
    daemonset_from_k8s,
    daemonset_to_k8s,
    deployment_from_k8s,
    deployment_to_k8s,
    endpoints_from_k8s,
    endpoints_to_k8s,
    hpa_from_k8s,
    hpa_to_k8s,
    job_from_k8s,
    job_to_k8s,
    limitrange_from_k8s,
    limitrange_to_k8s,
    node_from_k8s,
    node_to_k8s,
    nodemetrics_from_k8s,
    nodemetrics_to_k8s,
    namespace_from_k8s,
    namespace_to_k8s,
    pdb_from_k8s,
    pdb_to_k8s,
    pod_from_k8s,
    pod_to_k8s,
    podmetrics_from_k8s,
    podmetrics_to_k8s,
    priorityclass_from_k8s,
    priorityclass_to_k8s,
    replicaset_from_k8s,
    replicaset_to_k8s,
    replicationcontroller_from_k8s,
    replicationcontroller_to_k8s,
    resourcequota_from_k8s,
    resourcequota_to_k8s,
    service_from_k8s,
    service_to_k8s,
    serviceaccount_from_k8s,
    serviceaccount_to_k8s,
    statefulset_from_k8s,
    statefulset_to_k8s,
    clusterrole_from_k8s,
    clusterrole_to_k8s,
    clusterrolebinding_from_k8s,
    clusterrolebinding_to_k8s,
    role_from_k8s,
    role_to_k8s,
    rolebinding_from_k8s,
    rolebinding_to_k8s,
)
from ..utils.events import event_from_k8s, event_to_k8s
from .admission import AdmissionError
from .store import ConflictError, FakeAPIServer, GoneError, NotFoundError


def _lease_to_k8s(rec) -> dict:
    """coordination/v1 Lease wire shape for LeaderElectionRecord — enough
    for an out-of-process replica to contend for the lock over HTTP."""
    return {
        "apiVersion": "coordination.k8s.io/v1",
        "kind": "Lease",
        "metadata": {"name": rec.name, "resourceVersion": rec.resource_version or ""},
        "spec": {
            "holderIdentity": rec.holder_identity,
            "leaseDurationSeconds": rec.lease_duration_s,
            "acquireTime": rec.acquire_time,
            "renewTime": rec.renew_time,
            "leaseTransitions": rec.leader_transitions,
        },
    }


def _lease_from_k8s(d: dict):
    from ..utils.leaderelection import LeaderElectionRecord

    meta = d.get("metadata") or {}
    spec = d.get("spec") or {}
    return LeaderElectionRecord(
        holder_identity=spec.get("holderIdentity", ""),
        lease_duration_s=float(spec.get("leaseDurationSeconds", 15.0)),
        acquire_time=float(spec.get("acquireTime", 0.0)),
        renew_time=float(spec.get("renewTime", 0.0)),
        leader_transitions=int(spec.get("leaseTransitions", 0)),
        name=meta.get("name", "kube-scheduler"),
        resource_version=str(meta.get("resourceVersion", "")),
    )


# kind → (to_k8s, from_k8s, ListKind)
_CODECS: Dict[str, Tuple[Callable, Callable, str]] = {
    "pods": (pod_to_k8s, pod_from_k8s, "PodList"),
    "nodes": (node_to_k8s, node_from_k8s, "NodeList"),
    "replicasets": (replicaset_to_k8s, replicaset_from_k8s, "ReplicaSetList"),
    "deployments": (deployment_to_k8s, deployment_from_k8s, "DeploymentList"),
    "jobs": (job_to_k8s, job_from_k8s, "JobList"),
    "events": (event_to_k8s, event_from_k8s, "EventList"),
    "leases": (_lease_to_k8s, _lease_from_k8s, "LeaseList"),
    "priorityclasses": (priorityclass_to_k8s, priorityclass_from_k8s, "PriorityClassList"),
    "statefulsets": (statefulset_to_k8s, statefulset_from_k8s, "StatefulSetList"),
    "daemonsets": (daemonset_to_k8s, daemonset_from_k8s, "DaemonSetList"),
    "services": (service_to_k8s, service_from_k8s, "ServiceList"),
    "endpoints": (endpoints_to_k8s, endpoints_from_k8s, "EndpointsList"),
    "namespaces": (namespace_to_k8s, namespace_from_k8s, "NamespaceList"),
    "replicationcontrollers": (replicationcontroller_to_k8s, replicationcontroller_from_k8s, "ReplicationControllerList"),
    "cronjobs": (cronjob_to_k8s, cronjob_from_k8s, "CronJobList"),
    "poddisruptionbudgets": (pdb_to_k8s, pdb_from_k8s, "PodDisruptionBudgetList"),
    "serviceaccounts": (serviceaccount_to_k8s, serviceaccount_from_k8s, "ServiceAccountList"),
    "resourcequotas": (resourcequota_to_k8s, resourcequota_from_k8s, "ResourceQuotaList"),
    "limitranges": (limitrange_to_k8s, limitrange_from_k8s, "LimitRangeList"),
    "horizontalpodautoscalers": (hpa_to_k8s, hpa_from_k8s, "HorizontalPodAutoscalerList"),
    "podmetrics": (podmetrics_to_k8s, podmetrics_from_k8s, "PodMetricsList"),
    "nodemetrics": (nodemetrics_to_k8s, nodemetrics_from_k8s, "NodeMetricsList"),
    "roles": (role_to_k8s, role_from_k8s, "RoleList"),
    "clusterroles": (clusterrole_to_k8s, clusterrole_from_k8s, "ClusterRoleList"),
    "rolebindings": (rolebinding_to_k8s, rolebinding_from_k8s, "RoleBindingList"),
    "clusterrolebindings": (clusterrolebinding_to_k8s, clusterrolebinding_from_k8s,
                            "ClusterRoleBindingList"),
}

#: kinds keyed by bare name (store._key_of has no namespace for these)
_CLUSTER_SCOPED = {"nodes", "leases", "priorityclasses", "namespaces",
                   "nodemetrics", "clusterroles", "clusterrolebindings"}


def _parse_selector(vals) -> Optional[Dict[str, str]]:
    """k8s wire FIELD-selector syntax: "k1=v1,k2=v2" (equality only — the
    reference's field selectors are equality-based, fields/selector.go).
    Label selectors go through _parse_label_selector, which speaks the
    full set-based grammar."""
    if not vals or not vals[0]:
        return None
    out: Dict[str, str] = {}
    for part in vals[0].split(","):
        if "=" in part:
            k, _, v = part.partition("=")
            out[k.strip()] = v.strip()
    return out or None


def _parse_label_selector(vals):
    """Full k8s label-selector wire grammar (labels.Parse): equality,
    `in (a,b)` / `notin (a,b)` set ops, `k` / `!k` existence — parsed to
    a typed LabelSelector the store's matcher (and watch filtering)
    evaluates via the in-process match_label_selector."""
    from .store import parse_wire_label_selector

    if not vals:
        return None
    return parse_wire_label_selector(vals[0])


def _status(code: int, reason: str, message: str) -> bytes:
    return json.dumps({
        "kind": "Status", "apiVersion": "v1", "status": "Failure",
        "reason": reason, "message": message, "code": code,
    }).encode()


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    store: FakeAPIServer = None  # type: ignore  # set per-server subclass
    authenticator = None  # TokenAuthenticator | None (None = open server)
    authorizer = None  # RBACAuthorizer | None (None = authn only)

    def log_message(self, fmt, *args):  # quiet
        pass

    # -- helpers -------------------------------------------------------------

    def _auth(self, verb: str, resource: str, namespace: Optional[str]) -> bool:
        """authn → authz filter pair (DefaultBuildHandlerChain order,
        apiserver/pkg/server/config.go:539). True = proceed; False =
        response already sent (401 unauthenticated / 403 forbidden)."""
        if self.authenticator is None:
            return True
        user = self.authenticator.authenticate(self.headers.get("Authorization"))
        if user is None:
            body = _status(401, "Unauthorized", "invalid or missing bearer token")
            self.send_response(401)
            self.send_header("WWW-Authenticate", "Bearer")
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return False
        if self.authorizer is not None and not self.authorizer.authorize(
                user, verb, resource, namespace):
            self._send_json(403, _status(
                403, "Forbidden",
                f'user "{user.name}" cannot {verb} resource "{resource}"'
                + (f' in namespace "{namespace}"' if namespace else "")))
            return False
        return True

    @staticmethod
    def _ns_of(kind: str, rest) -> Optional[str]:
        if kind in _CLUSTER_SCOPED:
            return None
        return rest[0] if len(rest) >= 2 else None

    def _send_json(self, code: int, payload: Any) -> None:
        body = payload if isinstance(payload, bytes) else json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _read_body(self) -> dict:
        n = int(self.headers.get("Content-Length", 0) or 0)
        return json.loads(self.rfile.read(n) or b"{}")

    @staticmethod
    def _obj_key(kind: str, rest) -> Optional[str]:
        """Cluster-scoped kinds take key = name; everything else is
        namespace/name — mirroring store._key_of."""
        if kind in _CLUSTER_SCOPED:
            return rest[0] if len(rest) == 1 else None
        return f"{rest[0]}/{rest[1]}" if len(rest) == 2 else None

    def _route(self):
        u = urlparse(self.path)
        parts = [p for p in u.path.split("/") if p]
        # ["api", "v1", kind, ns?, name?, subresource?]
        if len(parts) < 3 or parts[0] != "api" or parts[1] != "v1":
            return None
        kind = parts[2]
        rest = parts[3:]
        # namespaced resource paths (the reference's canonical shape):
        #   /api/v1/namespaces/{ns}/{kind}            list/watch/create IN ns
        #   /api/v1/namespaces/{ns}/{kind}/{name}...  item verbs
        # Authorization runs against the REQUEST namespace (a namespaced
        # RoleBinding suffices), and list/watch results are restricted to
        # it. Distinguished from the namespaces kind's own item paths by
        # the second segment naming a known namespaced kind.
        ns_scope = None
        if (
            kind == "namespaces"
            and len(rest) >= 2
            and rest[1] in _CODECS
            and rest[1] not in _CLUSTER_SCOPED
        ):
            ns_scope = rest[0]
            kind = rest[1]
            rest = [ns_scope] + list(rest[2:])
        return kind, rest, parse_qs(u.query), ns_scope

    # -- verbs ---------------------------------------------------------------

    def do_GET(self):
        r = self._route()
        if r is None:
            return self._send_json(404, _status(404, "NotFound", self.path))
        kind, rest, q, ns_scope = r
        codec = _CODECS.get(kind)
        if codec is None:
            return self._send_json(404, _status(404, "NotFound", f"unknown kind {kind}"))
        to_k8s, _, list_kind = codec
        collection = not rest or (ns_scope is not None and len(rest) == 1)
        if not collection:
            if not self._auth("get", kind, self._ns_of(kind, rest)):
                return
            key = self._obj_key(kind, rest)
            if key is None:
                return self._send_json(404, _status(404, "NotFound", self.path))
            obj = None
            try:
                obj = self.store.get(kind, key)
            except KeyError:
                pass
            if obj is None:
                return self._send_json(404, _status(404, "NotFound", self.path))
            return self._send_json(200, to_k8s(obj))
        # list/watch: namespaced paths authorize against the REQUEST
        # namespace (a user with only a namespaced RoleBinding can list
        # their own namespace) and see only that namespace's objects;
        # bare /api/v1/{kind} stays cluster-scoped authorization
        if q.get("watch", ["0"])[0] in ("1", "true"):
            if not self._auth("watch", kind, ns_scope):
                return
            return self._serve_watch(kind, to_k8s, q, ns=ns_scope)
        if not self._auth("list", kind, ns_scope):
            return
        try:
            sel = _parse_label_selector(q.get("labelSelector"))
        except ValueError as e:
            return self._send_json(400, _status(400, "BadRequest", str(e)))
        items, rv = self.store.list(
            kind,
            label_selector=sel,
            field_selector=_parse_selector(q.get("fieldSelector")),
        )
        if ns_scope is not None:
            items = [
                o for o in items
                if getattr(o, "namespace", None) == ns_scope
            ]
        return self._send_json(200, {
            "kind": list_kind,
            "apiVersion": "v1",
            "metadata": {"resourceVersion": str(rv)},
            "items": [to_k8s(o) for o in items],
        })

    def _serve_watch(self, kind: str, to_k8s, q, ns: Optional[str] = None) -> None:
        try:
            since = int((q.get("resourceVersion") or ["0"])[0] or 0)
            timeout = float((q.get("timeoutSeconds") or ["300"])[0])
            sel = _parse_label_selector(q.get("labelSelector"))
        except ValueError as e:
            return self._send_json(400, _status(400, "BadRequest", str(e)))
        try:
            watcher = self.store.watch(
                kind, since,
                label_selector=sel,
                field_selector=_parse_selector(q.get("fieldSelector")),
            )
        except GoneError as e:
            return self._send_json(410, _status(410, "Expired", str(e)))
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()

        def chunk(data: bytes) -> None:
            self.wfile.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
            self.wfile.flush()

        import time as _time

        deadline = _time.monotonic() + timeout
        last_write = _time.monotonic()
        try:
            while _time.monotonic() < deadline:
                ev = watcher.next(timeout=0.5)
                if ev is not None and ns is not None and getattr(
                        ev.obj, "namespace", None) != ns:
                    # namespaced watch: events outside the authorized
                    # namespace never reach the client
                    continue
                if ev is None:
                    if watcher.closed:
                        break  # store closed the stream (restart simulation)
                    if _time.monotonic() - last_write > 2.0:
                        # blank-line heartbeat (clients skip empty lines):
                        # detects a dropped client during idle stretches
                        # instead of pinning this thread + Watcher for the
                        # full timeoutSeconds
                        chunk(b"\n")
                        last_write = _time.monotonic()
                    continue
                d = to_k8s(ev.obj)
                d["metadata"] = {**d.get("metadata", {}), "resourceVersion": str(ev.rv)}
                chunk(json.dumps({"type": ev.type, "object": d}).encode() + b"\n")
                last_write = _time.monotonic()
        except (BrokenPipeError, ConnectionResetError):
            pass
        finally:
            watcher.close()
            try:
                chunk(b"")  # terminating chunk
            except Exception:
                pass

    def do_POST(self):
        r = self._route()
        if r is None:
            return self._send_json(404, _status(404, "NotFound", self.path))
        kind, rest, _, ns_scope = r
        # bind subresource (both /api/v1/pods/{ns}/{name}/binding and the
        # namespaced form /api/v1/namespaces/{ns}/pods/{name}/binding —
        # _route remaps the latter onto the same rest shape)
        if kind == "pods" and len(rest) == 3 and rest[2] == "binding":
            if not self._auth("create", "pods/binding", rest[0]):
                return
            body = self._read_body()
            node = ((body.get("target") or {}).get("name")) or ""
            try:
                self.store.bind(rest[0], rest[1], node)
            except NotFoundError as e:
                return self._send_json(404, _status(404, "NotFound", str(e)))
            except ConflictError as e:
                return self._send_json(409, _status(409, "Conflict", str(e)))
            return self._send_json(201, {"kind": "Status", "status": "Success"})
        codec = _CODECS.get(kind)
        in_ns_collection = ns_scope is not None and len(rest) == 1
        if codec is None or (rest and not in_ns_collection):
            return self._send_json(404, _status(404, "NotFound", self.path))
        _, from_k8s, _ = codec
        try:
            body = self._read_body()
            obj = from_k8s(body)
        except Exception as e:  # malformed JSON/object → 400, not a dropped conn
            return self._send_json(400, _status(400, "BadRequest", str(e)))
        if in_ns_collection:
            # the URL namespace is the authorization subject AND the write
            # scope: a body without an EXPLICIT namespace inherits it (the
            # codec's "default" fill is not user intent), a conflicting one
            # is a 400 (rest.BeforeCreate namespace validation)
            body_ns = ((body.get("metadata") or {}).get("namespace")) or ""
            if body_ns and body_ns != ns_scope:
                return self._send_json(400, _status(
                    400, "BadRequest",
                    f"namespace in body ({body_ns}) must match URL path "
                    f"({ns_scope})"))
            if hasattr(obj, "namespace"):
                obj.namespace = ns_scope
        ns = None if kind in _CLUSTER_SCOPED else getattr(obj, "namespace", None)
        if not self._auth("create", kind, ns):
            return
        try:
            created = self.store.create(kind, obj)
        except ConflictError as e:
            return self._send_json(409, _status(409, "AlreadyExists", str(e)))
        except AdmissionError as e:
            return self._send_json(422, _status(422, "Invalid", str(e)))
        return self._send_json(201, codec[0](created))

    def do_PUT(self):
        r = self._route()
        if r is None:
            return self._send_json(404, _status(404, "NotFound", self.path))
        kind, rest, _, _ns_scope = r
        # pod status subresource (PUT .../pods/{ns}/{name}/status): the
        # scheduler's preemption nomination write. Status-only — the
        # store patches nominatedNodeName and nothing else, so it can
        # never clobber a concurrent bind's spec.nodeName.
        if kind == "pods" and len(rest) == 3 and rest[2] == "status":
            if not self._auth("update", "pods/status", rest[0]):
                return
            try:
                body = self._read_body()
                nominated = (body.get("status") or {}).get("nominatedNodeName")
            except Exception as e:
                return self._send_json(400, _status(400, "BadRequest", str(e)))
            try:
                updated = self.store.update_pod_status(
                    rest[0], rest[1], nominated_node_name=nominated,
                )
            except NotFoundError as e:
                return self._send_json(404, _status(404, "NotFound", str(e)))
            return self._send_json(200, pod_to_k8s(updated))
        codec = _CODECS.get(kind)
        if codec is None or self._obj_key(kind, rest) is None:
            return self._send_json(404, _status(404, "NotFound", self.path))
        if not self._auth("update", kind, self._ns_of(kind, rest)):
            return
        to_k8s, from_k8s, _ = codec
        try:
            body = self._read_body()
            obj = from_k8s(body)
        except Exception as e:  # malformed JSON/object → 400, not a dropped conn
            return self._send_json(400, _status(400, "BadRequest", str(e)))
        # The URL path is the authorization subject AND the write key: a
        # body claiming a different namespace/name would be authorized
        # against the path namespace but stored under the body's key — an
        # RBAC bypass (a user bound in "dev" overwriting "prod" objects).
        # The reference apiserver rejects path/body mismatches with 400
        # (rest.BeforeUpdate name/namespace validation); empty body fields
        # inherit the path (the reference's defaulting).
        path_name = rest[0] if kind in _CLUSTER_SCOPED else rest[1]
        body_name = getattr(obj, "name", "") or ""
        if body_name and body_name != path_name:
            return self._send_json(400, _status(
                400, "BadRequest",
                f"name in body ({body_name}) must match URL path ({path_name})"))
        if body_name != path_name and hasattr(obj, "name"):
            obj.name = path_name
        if kind not in _CLUSTER_SCOPED:
            path_ns = rest[0]
            body_ns = getattr(obj, "namespace", "") or ""
            if body_ns and body_ns != path_ns:
                return self._send_json(400, _status(
                    400, "BadRequest",
                    f"namespace in body ({body_ns}) must match URL path ({path_ns})"))
            if body_ns != path_ns and hasattr(obj, "namespace"):
                obj.namespace = path_ns
        check_rv = bool(((body.get("metadata") or {}).get("resourceVersion")))
        try:
            updated = self.store.update(kind, obj, check_rv=check_rv)
        except ConflictError as e:
            return self._send_json(409, _status(409, "Conflict", str(e)))
        except AdmissionError as e:
            return self._send_json(422, _status(422, "Invalid", str(e)))
        except KeyError:
            return self._send_json(404, _status(404, "NotFound", self.path))
        return self._send_json(200, to_k8s(updated))

    def do_DELETE(self):
        r = self._route()
        if r is None:
            return self._send_json(404, _status(404, "NotFound", self.path))
        kind, rest, _, _ns_scope = r
        key = self._obj_key(kind, rest)
        if key is None:
            return self._send_json(404, _status(404, "NotFound", self.path))
        if not self._auth("delete", kind, self._ns_of(kind, rest)):
            return
        try:
            self.store.delete(kind, key)
        except KeyError:
            return self._send_json(404, _status(404, "NotFound", self.path))
        return self._send_json(200, {"kind": "Status", "status": "Success"})


class APIServerHTTP:
    """Serve a FakeAPIServer store over HTTP (daemon threads).

    Pass `authenticator` (apiserver.auth.TokenAuthenticator) to require
    bearer tokens (401 otherwise), and `authorizer`
    (apiserver.auth.RBACAuthorizer) to enforce RBAC (403 on deny).
    Both None (the default) keeps the open-server behavior for
    local/simulation use."""

    def __init__(self, store: FakeAPIServer, host: str = "127.0.0.1", port: int = 0,
                 authenticator=None, authorizer=None):
        self.store = store
        handler = type("BoundHandler", (_Handler,), {
            "store": store,
            "authenticator": authenticator,
            "authorizer": authorizer,
        })
        self._srv = ThreadingHTTPServer((host, port), handler)
        self._srv.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        h, p = self._srv.server_address[:2]
        return f"http://{h}:{p}"

    def start(self) -> "APIServerHTTP":
        # ktpu: thread-entry(apiserver-serve) stdlib mux: handlers run
        # on socketserver threads the call graph cannot follow
        self._thread = threading.Thread(
            target=self._srv.serve_forever, name="apiserver-http", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()
