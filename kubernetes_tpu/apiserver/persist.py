"""Durable persistence for the apiserver store: WAL + snapshot.

The reference's storage layer is etcd — raft-replicated WAL + periodic
snapshots, with the apiserver stateless above it
(staging/src/k8s.io/apiserver/pkg/storage/etcd3/store.go:239 writes are
revision-CAS transactions; "etcd IS the checkpoint", SURVEY §5). This is
the single-node analogue with the same observable contract:

* every accepted write appends one JSON line {op, kind, key, rv, obj} to
  the log BEFORE the in-memory apply returns;
* on startup the store replays snapshot + log, and resourceVersion
  continues from the highest persisted revision — clients' stored RVs
  stay meaningful across a restart (watch HISTORY is not persisted:
  reconnecting watchers get 410 Gone and relist, exactly the
  Reflector.ListAndWatch recovery path, reflector.go:184);
* when the log exceeds `compact_every` entries, the store is checkpointed
  to <path>.snap (atomic tmp+rename) and the log truncated — bounded
  recovery time, like etcd's snapshot+compaction cycle.

Objects serialize through the same k8s wire codecs the HTTP transport
uses (one canonical encoding, apiserver/http._CODECS); kinds without a
codec fall back to a tagged pickle payload (test-only object shapes).

Durability level: lines are flushed to the OS on every append; pass
fsync=True to force fsync per write (etcd's default) at the obvious
throughput cost.
"""

from __future__ import annotations

import base64
import json
import os
import pickle
from typing import Any, Dict, Tuple

from ..analysis.lockorder import audited_lock


def _codecs():
    from .http import _CODECS

    return _CODECS


def _encode(kind: str, obj: Any) -> dict:
    codec = _codecs().get(kind)
    if codec is not None:
        try:
            return {"w": codec[0](obj)}
        except Exception:
            pass
    return {"p": base64.b64encode(pickle.dumps(obj)).decode()}


def _decode(kind: str, payload: dict) -> Any:
    if "w" in payload:
        return _codecs()[kind][1](payload["w"])
    return pickle.loads(base64.b64decode(payload["p"]))


class WAL:
    def __init__(self, path: str, compact_every: int = 10000, fsync: bool = False):
        self.path = path
        self.snap_path = path + ".snap"
        self.compact_every = compact_every
        self.fsync = fsync
        self._lock = audited_lock("apiserver-persist")
        self._f = None
        self._entries_since_snap = 0

    # -- recovery -------------------------------------------------------------

    def replay(self) -> Tuple[Dict[str, Dict[str, Any]], int]:
        """(objects by kind by key, highest revision seen)."""
        objects: Dict[str, Dict[str, Any]] = {}
        rv = 0
        if os.path.exists(self.snap_path):
            with open(self.snap_path) as f:
                snap = json.load(f)
            rv = int(snap.get("rv", 0))
            for kind, items in snap.get("kinds", {}).items():
                objects[kind] = {
                    key: _decode(kind, payload) for key, payload in items.items()
                }
        if os.path.exists(self.path):
            torn_at = None
            with open(self.path, "rb") as f:
                offset = 0
                for raw in f:
                    line = raw.strip()
                    if not line:
                        offset += len(raw)
                        continue
                    try:
                        e = json.loads(line)
                    except ValueError:
                        # torn tail write (crash mid-append): stop here AND
                        # truncate below — appending after the fragment
                        # would make every later entry unreadable on the
                        # NEXT replay (silent loss of post-crash writes)
                        torn_at = offset
                        break
                    offset += len(raw)
                    rv = max(rv, int(e.get("rv", 0)))
                    kind, key = e["kind"], e["key"]
                    if e["op"] == "DELETE":
                        objects.get(kind, {}).pop(key, None)
                    else:
                        objects.setdefault(kind, {})[key] = _decode(kind, e["obj"])
            if torn_at is not None:
                with open(self.path, "r+b") as f:
                    f.truncate(torn_at)
        return objects, rv

    # -- appends --------------------------------------------------------------

    def _file(self):
        if self._f is None:
            self._f = open(self.path, "a")
        return self._f

    def append(self, op: str, kind: str, key: str, rv: int, obj: Any = None) -> None:
        entry: Dict[str, Any] = {"op": op, "kind": kind, "key": key, "rv": rv}
        if obj is not None:
            entry["obj"] = _encode(kind, obj)
        with self._lock:
            f = self._file()
            f.write(json.dumps(entry) + "\n")
            f.flush()
            if self.fsync:
                os.fsync(f.fileno())
            self._entries_since_snap += 1

    def maybe_compact(self, objects: Dict[str, Dict[str, Any]], rv: int) -> bool:
        """Checkpoint + truncate when the log has grown past the bound.
        Caller holds the store lock (the object maps must not move)."""
        with self._lock:
            if self._entries_since_snap < self.compact_every:
                return False
            snap = {
                "rv": rv,
                "kinds": {
                    kind: {key: _encode(kind, o) for key, o in items.items()}
                    for kind, items in objects.items()
                },
            }
            tmp = self.snap_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(snap, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.snap_path)
            if self._f is not None:
                self._f.close()
                self._f = None
            open(self.path, "w").close()  # truncate: snapshot covers it
            self._entries_since_snap = 0
            return True

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None
