"""Simulated API server: resourceVersion-ordered store + watch fan-out.

Plays the role the real control plane plays for the scheduler (SURVEY §3.3
/ §3.4): an ObjectTracker-style store (client-go testing.ObjectTracker is
what the reference's fake clientset is backed by) with

* a single monotonically-increasing resourceVersion (etcd revision
  semantics: one global sequence, etcd3/store.go:239 CAS txns),
* watch streams per kind with a bounded replay window — watchers starting
  below the window get 410 Gone and must relist, exactly the
  Reflector.ListAndWatch contract (reflector.go:184, relist-on-410),
* the pods/binding subresource (what the scheduler's bind POSTs,
  factory.go:718) and pod status patches,
* deep copies on every write AND read: shared-object mutation by a client
  is the bug class client-go's mutation detector exists for
  (cache/mutation_detector.go) — copying at the boundary makes it
  impossible by construction.

In-process only: the transport is a queue, not HTTP — the wire format is
the typed api.types objects (their JSON round-trip lives with them).
"""

from __future__ import annotations

import copy
import itertools
import queue
from collections import deque
from dataclasses import dataclass
from typing import Any, Deque, Dict, List, Optional, Tuple

from ..analysis.lockorder import audited_lock

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"

HISTORY_WINDOW = 2048  # events kept per kind before compaction → 410


class GoneError(Exception):
    """HTTP 410: requested resourceVersion compacted away — relist."""


class ConflictError(Exception):
    """HTTP 409: resourceVersion precondition failed."""


class NotFoundError(KeyError):
    pass


@dataclass
class WatchEvent:
    type: str
    obj: Any
    rv: int


def _key_of(obj: Any) -> str:
    k = getattr(obj, "key", None)
    if callable(k):
        return k()
    return obj.name


class Watcher:
    """One watch stream: a queue of WatchEvents; close() ends it. An
    attached (label, field) selector pair filters server-side — the store
    only pushes matching events (per-node pod watches don't fan the whole
    cluster)."""

    def __init__(self, label_selector=None, field_selector=None):
        self._q: "queue.Queue[Optional[WatchEvent]]" = queue.Queue()
        self.label_selector = label_selector
        self.field_selector = field_selector
        self.closed = False

    def next(self, timeout: Optional[float] = None) -> Optional[WatchEvent]:
        try:
            ev = self._q.get(timeout=timeout)
        except queue.Empty:
            return None
        return ev

    def _push(self, ev: Optional[WatchEvent]) -> None:
        self._q.put(ev)

    def close(self) -> None:
        if not self.closed:
            self.closed = True
            self._q.put(None)


def _field_of(obj: Any, path: str) -> str:
    """Resolve a field-selector path against the typed objects
    (apimachinery/pkg/fields: the supported paths are per-kind; these
    cover the scheduling-relevant set — notably pods-by-nodeName, which is
    how kubelets watch only their own pods)."""
    if path == "metadata.name":
        return getattr(obj, "name", "")
    if path == "metadata.namespace":
        return getattr(obj, "namespace", "")
    if path == "spec.nodeName":
        return getattr(obj, "node_name", "")
    if path == "status.phase":
        return getattr(obj, "phase", "")
    return ""


import re as _re

#: `k in (a,b)` / `k notin (a,b)` — whitespace after the op is optional
#: ("env in(prod)" is legal k8s; the lexer tokenizes '(' separately)
_SET_REQ_RE = _re.compile(r"^(\S+?)\s+(in|notin)\s*\((.*)\)$")
#: a plausible label key (qualified-name characters only) — guards every
#: branch against swallowing unsupported syntax like `k>v` as a literal
#: never-matching key
_KEY_RE = _re.compile(r"^[A-Za-z0-9]([A-Za-z0-9._/-]*[A-Za-z0-9])?$")


def _is_key(s: str) -> bool:
    return bool(s) and _KEY_RE.match(s) is not None


def parse_wire_label_selector(text: Optional[str]):
    """k8s wire label-selector syntax (labels.Parse,
    staging/src/k8s.io/apimachinery/pkg/labels/selector.go) → a typed
    LabelSelector evaluated by the in-process matcher
    (api.selectors.match_label_selector — it already implements every op;
    only this parser was missing). Full grammar:

        k=v | k==v | k!=v | k in (a,b) | k notin (a,b) | k | !k

    comma-separated, ANDed. `!=`/`notin` match when the key is ABSENT or
    the value differs (labels.Requirement NotIn semantics). Returns None
    for an empty/missing selector (no filtering); a requirement this
    grammar cannot parse (Gt/Lt's `k>v`, typo'd set syntax) raises
    ValueError — the HTTP layer turns that into 400 BadRequest, exactly
    like the reference apiserver. Silently skipping would over-match
    (no filter where the client asked for one); silently keeping the
    raw token as an Exists key would under-match. Both are worse than
    an error."""
    if not text or not text.strip():
        return None
    from ..api.types import LabelSelector, LabelSelectorRequirement

    # split on top-level commas only: set values live inside parentheses
    parts, depth, cur = [], 0, []
    for ch in text:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth = max(depth - 1, 0)
        if ch == "," and depth == 0:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    parts.append("".join(cur))
    match_labels: Dict[str, str] = {}
    exprs = []
    for part in parts:
        part = part.strip()
        if not part:
            continue
        m = _SET_REQ_RE.match(part)
        if m:
            values = [v.strip() for v in m.group(3).split(",") if v.strip()]
            if not _is_key(m.group(1)) or not values:
                raise ValueError(
                    f"unparseable label-selector requirement {part!r}"
                )
            exprs.append(LabelSelectorRequirement(
                key=m.group(1),
                operator="In" if m.group(2) == "in" else "NotIn",
                values=values,
            ))
        elif "!=" in part:
            k, _, v = part.partition("!=")
            if not _is_key(k.strip()):
                raise ValueError(
                    f"unparseable label-selector requirement {part!r}"
                )
            exprs.append(LabelSelectorRequirement(
                key=k.strip(), operator="NotIn", values=[v.strip()]
            ))
        elif "=" in part:
            k, _, v = part.partition("==" if "==" in part else "=")
            if not _is_key(k.strip()):
                raise ValueError(
                    f"unparseable label-selector requirement {part!r}"
                )
            match_labels[k.strip()] = v.strip()
        elif part.startswith("!") and _is_key(part[1:].strip()):
            exprs.append(LabelSelectorRequirement(
                key=part[1:].strip(), operator="DoesNotExist"
            ))
        elif _is_key(part):
            exprs.append(LabelSelectorRequirement(key=part, operator="Exists"))
        else:
            raise ValueError(f"unparseable label-selector requirement {part!r}")
    if not match_labels and not exprs:
        return None
    return LabelSelector(match_labels=match_labels, match_expressions=exprs)


def _matches(obj: Any, label_selector,
             field_selector: Optional[Dict[str, str]]) -> bool:
    """Label matching accepts BOTH selector shapes: the in-process
    informers' equality dict (labels.Set.AsSelector) and a typed
    LabelSelector from the wire parser above (set-based ops included).
    Field selectors stay equality-only — the reference's are too."""
    if label_selector:
        labels = getattr(obj, "labels", None) or {}
        if isinstance(label_selector, dict):
            for k, v in label_selector.items():
                if labels.get(k) != v:
                    return False
        else:
            from ..api.selectors import match_label_selector

            if not match_label_selector(label_selector, labels):
                return False
    if field_selector:
        for path, v in field_selector.items():
            if _field_of(obj, path) != v:
                return False
    return True


class FakeAPIServer:
    def __init__(self, history_window: int = HISTORY_WINDOW, admission=None,
                 wal=None):
        self._lock = audited_lock("apiserver-store")
        self._objects: Dict[str, Dict[str, Any]] = {}
        self._history: Dict[str, Deque[WatchEvent]] = {}
        self._watchers: Dict[str, List[Watcher]] = {}
        self._history_window = history_window
        self._current_rv = 0
        # admission chain (apiserver/admission.py): runs on create/update
        # BEFORE the store lock (plugins read the store — PriorityClass
        # lookups); raises AdmissionError to reject, may mutate the object
        self._admission = admission
        # durable persistence (apiserver/persist.WAL or a path): every
        # accepted write is logged before it returns; on startup the store
        # replays snapshot+log and resourceVersion CONTINUES from the
        # highest persisted revision ("etcd IS the checkpoint", SURVEY §5).
        # Watch history is not persisted — reconnecting watchers relist.
        if isinstance(wal, str):
            from .persist import WAL

            wal = WAL(wal)
        self._wal = wal
        start_rv = 0
        if wal is not None:
            self._objects, start_rv = wal.replay()
            self._current_rv = start_rv
        self._rv = itertools.count(start_rv + 1)

    # -- internals -----------------------------------------------------------

    def _bump(self) -> int:
        self._current_rv = next(self._rv)
        return self._current_rv

    def _emit(self, kind: str, type_: str, obj: Any, rv: int, old: Any = None) -> None:
        ev = WatchEvent(type_, obj, rv)
        hist = self._history.setdefault(kind, deque(maxlen=self._history_window))
        hist.append(ev)
        # prune watchers closed by their consumers (reflector restarts would
        # otherwise leak one dead Watcher per relist)
        live = [w for w in self._watchers.get(kind, []) if not w.closed]
        self._watchers[kind] = live
        for w in live:
            if _matches(obj, w.label_selector, w.field_selector):
                w._push(WatchEvent(type_, copy.deepcopy(obj), rv))
            elif old is not None and _matches(old, w.label_selector, w.field_selector):
                # the object LEFT this watcher's selector: synthesize
                # DELETED so filtered informer caches don't go stale (the
                # reference watch cache does the same, cacher.go
                # sendWatchCacheEvent's match-transition handling)
                w._push(WatchEvent(DELETED, copy.deepcopy(obj), rv))

    # -- REST surface ---------------------------------------------------------

    def create(self, kind: str, obj: Any) -> Any:
        undo: List[Any] = []
        if self._admission is not None:
            obj = self._admission.admit(self, kind, "CREATE", copy.deepcopy(obj),
                                        undo=undo)
        try:
            return self._create_admitted(kind, obj)
        except Exception:
            # admission ran (and e.g. charged quota) for a write the store
            # did not accept — duplicate-name ConflictError (the CronJob
            # Replace/dedupe path), a WAL write failure, anything. Run the
            # plugins' rollbacks OUTSIDE the lock (they re-enter the
            # store) so the usage doesn't strand until the quota
            # controller's resync.
            for fn in reversed(undo):
                try:
                    fn()
                except Exception:
                    pass  # rollback is best-effort; the controller resyncs
            raise

    def _create_admitted(self, kind: str, obj: Any) -> Any:
        with self._lock:
            objs = self._objects.setdefault(kind, {})
            key = _key_of(obj)
            if key in objs:
                raise ConflictError(f"{kind} {key} already exists")
            stored = copy.deepcopy(obj)
            stored.resource_version = str(self._bump())
            objs[key] = stored
            if self._wal is not None:
                try:
                    self._wal.append("PUT", kind, key, self._current_rv, stored)
                    self._wal.maybe_compact(self._objects, self._current_rv)
                except Exception:
                    # a create that raises must leave no object behind —
                    # create()'s admission rollback (quota uncharge) relies
                    # on failure meaning the write didn't happen
                    del objs[key]
                    raise
            self._emit(kind, ADDED, copy.deepcopy(stored), self._current_rv)
            return copy.deepcopy(stored)

    def update(self, kind: str, obj: Any, check_rv: bool = False) -> Any:
        if self._admission is not None:
            obj = self._admission.admit(self, kind, "UPDATE", copy.deepcopy(obj))
        with self._lock:
            objs = self._objects.setdefault(kind, {})
            key = _key_of(obj)
            if key not in objs:
                raise NotFoundError(key)
            if check_rv and obj.resource_version != objs[key].resource_version:
                raise ConflictError(f"{kind} {key}: resourceVersion mismatch")
            prev = objs[key]
            stored = copy.deepcopy(obj)
            stored.resource_version = str(self._bump())
            objs[key] = stored
            if self._wal is not None:
                self._wal.append("PUT", kind, key, self._current_rv, stored)
                self._wal.maybe_compact(self._objects, self._current_rv)
            self._emit(kind, MODIFIED, copy.deepcopy(stored), self._current_rv, old=prev)
            return copy.deepcopy(stored)

    def delete(self, kind: str, key: str) -> None:
        with self._lock:
            objs = self._objects.setdefault(kind, {})
            if key not in objs:
                raise NotFoundError(key)
            obj = objs.pop(key)
            rv = self._bump()
            if self._wal is not None:
                self._wal.append("DELETE", kind, key, rv)
                self._wal.maybe_compact(self._objects, self._current_rv)
            self._emit(kind, DELETED, copy.deepcopy(obj), rv)

    def get(self, kind: str, key: str) -> Any:
        with self._lock:
            obj = self._objects.get(kind, {}).get(key)
            if obj is None:
                raise NotFoundError(key)
            return copy.deepcopy(obj)

    def list(self, kind: str, label_selector: Optional[Dict[str, str]] = None,
             field_selector: Optional[Dict[str, str]] = None) -> Tuple[List[Any], int]:
        """→ (deep-copied items, list resourceVersion); selectors filter
        server-side (labels.Selector / fields.Selector on the list verb)."""
        with self._lock:
            items = [
                copy.deepcopy(o)
                for o in self._objects.get(kind, {}).values()
                if _matches(o, label_selector, field_selector)
            ]
            return items, self._current_rv

    def watch(self, kind: str, since_rv: int,
              label_selector: Optional[Dict[str, str]] = None,
              field_selector: Optional[Dict[str, str]] = None) -> Watcher:
        """Watch from since_rv (exclusive). 410 when compacted below it.
        Selectors filter events server-side."""
        with self._lock:
            hist = self._history.setdefault(kind, deque(maxlen=self._history_window))
            if hist and since_rv < hist[0].rv - 1 and since_rv < self._oldest_live_rv(kind):
                raise GoneError(f"resourceVersion {since_rv} compacted")
            w = Watcher(label_selector, field_selector)
            for ev in hist:
                if ev.rv > since_rv and _matches(ev.obj, label_selector, field_selector):
                    w._push(WatchEvent(ev.type, copy.deepcopy(ev.obj), ev.rv))
            self._watchers.setdefault(kind, []).append(w)
            return w

    def _oldest_live_rv(self, kind: str) -> int:
        hist = self._history.get(kind)
        if not hist or len(hist) < self._history_window:
            return 0  # nothing compacted yet
        return hist[0].rv

    def close_watchers(self, kind: Optional[str] = None) -> None:
        """Drop watch connections (tests simulate apiserver restarts)."""
        with self._lock:
            kinds = [kind] if kind else list(self._watchers)
            for k in kinds:
                for w in self._watchers.get(k, []):
                    w.close()
                self._watchers[k] = []

    # -- scheduler-facing subresources ----------------------------------------

    def bind(self, namespace: str, name: str, node_name: str) -> None:
        """POST pods/<p>/binding: sets spec.nodeName (registry/core/pod/rest
        BindingREST semantics — 409 Conflict for ANY already-bound pod,
        including a re-bind to the same node: the real BindingREST fails
        whenever spec.nodeName is set. The SAME-node Conflict is the
        crash-restart plane's idempotency signal — a binder replaying a
        bind whose first attempt actually landed (process death between
        the POST and its bookkeeping) gets a 409 it can verify against
        the bound node and treat as success (client/informer.APIBinder);
        a DIFFERENT-node Conflict is a double-schedule and escalates.
        Binding also clears status.nominatedNodeName: the pod stopped
        being a pending nominee the moment it landed (the store-side
        half of the nomination wire round-trip)."""
        key = f"{namespace}/{name}"
        with self._lock:
            pods = self._objects.setdefault("pods", {})
            pod = pods.get(key)
            if pod is None:
                raise NotFoundError(key)
            if pod.node_name:
                raise ConflictError(f"pod {key} already bound to {pod.node_name}")
            prev = pod
            pod = copy.deepcopy(pod)
            pod.node_name = node_name
            pod.nominated_node_name = ""
            pod.resource_version = str(self._bump())
            pods[key] = pod
            if self._wal is not None:
                self._wal.append("PUT", "pods", key, self._current_rv, pod)
                self._wal.maybe_compact(self._objects, self._current_rv)
            self._emit("pods", MODIFIED, copy.deepcopy(pod), self._current_rv, old=prev)

    def update_pod_status(self, namespace: str, name: str, *,
                          nominated_node_name: Optional[str] = None) -> Any:
        """PUT pods/<p>/status (the scheduler's preemption nomination
        write, scheduler.go:436-470 podPreemptor.SetNominatedNodeName):
        patches ONLY status fields — spec and labels are untouched, so a
        concurrent bind can never be clobbered by a racing nomination.
        The write is durable (WAL) and watched like any MODIFIED, which
        is what lets a restarted scheduler reconstruct the nominated-pod
        overlay from a plain relist."""
        key = f"{namespace}/{name}"
        with self._lock:
            pods = self._objects.setdefault("pods", {})
            pod = pods.get(key)
            if pod is None:
                raise NotFoundError(key)
            prev = pod
            pod = copy.deepcopy(pod)
            if nominated_node_name is not None:
                pod.nominated_node_name = nominated_node_name
            pod.resource_version = str(self._bump())
            pods[key] = pod
            if self._wal is not None:
                self._wal.append("PUT", "pods", key, self._current_rv, pod)
                self._wal.maybe_compact(self._objects, self._current_rv)
            self._emit("pods", MODIFIED, copy.deepcopy(pod), self._current_rv, old=prev)
            return copy.deepcopy(pod)
