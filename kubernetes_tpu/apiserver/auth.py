"""Authentication + RBAC authorization for the apiserver HTTP front door.

The reference routes every request through authentication → authorization
before admission (DefaultBuildHandlerChain,
staging/src/k8s.io/apiserver/pkg/server/config.go:539). This module is
that filter pair, TPU-framework-sized:

* `TokenAuthenticator` — bearer-token authn
  (staging/src/k8s.io/apiserver/pkg/authentication/token/tokenfile):
  a token maps to a `UserInfo` (name + groups). No token or an unknown
  token → 401 (no anonymous fallthrough — the deny-by-default posture).
* `RBACAuthorizer` — plugin/pkg/auth/authorizer/rbac/rbac.go:74
  VisitRulesFor semantics: ClusterRoleBindings grant their ClusterRole's
  rules everywhere; RoleBindings grant their Role's (or referenced
  ClusterRole's) rules inside the binding's namespace. A request is
  allowed iff some bound rule matches (verb, resource) with '*'
  wildcards; everything else is DENIED.

Identity conventions follow the reference's bootstrap policy
(plugin/pkg/auth/authorizer/rbac/bootstrappolicy/policy.go): the
scheduler runs as `system:kube-scheduler`, the controller-manager as
`system:kube-controller-manager`, kubelets in group `system:nodes`, and
cluster operators in group `system:masters` (bound to cluster-admin).
`install_bootstrap_rbac` seeds those roles/bindings at startup the way
the reference's PostStartHook reconciles bootstrap policy.

Verbs: get, list, watch, create, update, delete; the pods/binding
subresource authorizes as resource "pods/binding", verb "create"
(the registry's BindingREST is a create on the binding subresource).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterable, Optional, Tuple

from ..analysis.lockorder import audited_lock
from ..api.types import (
    ClusterRole,
    ClusterRoleBinding,
    PolicyRule,
    Role,
    RoleRef,
    Subject,
)

class UnauthorizedError(Exception):
    """401: request carried no (or an unknown) bearer token."""


class ForbiddenError(Exception):
    """403: authenticated, but RBAC denies the (verb, resource)."""


GROUP_MASTERS = "system:masters"
GROUP_NODES = "system:nodes"
GROUP_AUTHENTICATED = "system:authenticated"
USER_SCHEDULER = "system:kube-scheduler"
USER_CONTROLLER_MANAGER = "system:kube-controller-manager"


@dataclass(frozen=True)
class UserInfo:
    """authentication/user.Info subset: name + groups."""

    name: str
    groups: Tuple[str, ...] = ()

    def all_groups(self) -> Tuple[str, ...]:
        # every authenticated user is in system:authenticated
        # (group_adder.go AuthenticatedGroupAdder)
        return self.groups + (GROUP_AUTHENTICATED,)


class TokenAuthenticator:
    """Static bearer-token table (tokenfile authenticator)."""

    def __init__(self, tokens: Optional[Dict[str, UserInfo]] = None):
        self._tokens: Dict[str, UserInfo] = dict(tokens or {})
        self._lock = audited_lock("apiserver-auth")

    def add(self, token: str, user: UserInfo) -> None:
        with self._lock:
            self._tokens[token] = user

    def authenticate(self, authorization: Optional[str]) -> Optional[UserInfo]:
        """`Authorization` header value → UserInfo, or None (→ 401)."""
        if not authorization or not authorization.startswith("Bearer "):
            return None
        token = authorization[len("Bearer "):].strip()
        if not token:
            return None
        with self._lock:
            return self._tokens.get(token)


def _subject_matches(s: Subject, user: UserInfo) -> bool:
    if s.kind == "User":
        return s.name == user.name
    if s.kind == "Group":
        return s.name in user.all_groups()
    if s.kind == "ServiceAccount":
        # serviceaccount usernames follow the apiserver convention
        return user.name == f"system:serviceaccount:{s.namespace}:{s.name}"
    return False


def _rule_allows(rule: PolicyRule, verb: str, resource: str) -> bool:
    # rbac.go VerbMatches / ResourceMatches: exact or '*'; a rule naming
    # the bare resource also covers it, but subresources ("pods/binding")
    # must be named explicitly or wildcarded (ResourceMatches only
    # wildcards the whole string or via "pods/*")
    if "*" not in rule.verbs and verb not in rule.verbs:
        return False
    for r in rule.resources:
        if r == "*" or r == resource:
            return True
        if r.endswith("/*") and resource.startswith(r[:-1]):
            return True
    return False


class RBACAuthorizer:
    """Evaluate (user, verb, resource, namespace) against stored RBAC
    kinds on every request — deny unless some binding's rule allows."""

    def __init__(self, store):
        self.store = store

    def _cluster_rules(self, user: UserInfo) -> Iterable[PolicyRule]:
        try:
            bindings, _ = self.store.list("clusterrolebindings")
        except Exception:
            return
        for b in bindings:
            if not any(_subject_matches(s, user) for s in b.subjects):
                continue
            try:
                role: ClusterRole = self.store.get("clusterroles", b.role_ref.name)
            except KeyError:
                continue
            yield from role.rules

    def _namespace_rules(self, user: UserInfo, namespace: str) -> Iterable[PolicyRule]:
        try:
            bindings, _ = self.store.list("rolebindings")
        except Exception:
            return
        for b in bindings:
            if b.namespace != namespace:
                continue
            if not any(_subject_matches(s, user) for s in b.subjects):
                continue
            try:
                if b.role_ref.kind == "ClusterRole":
                    role = self.store.get("clusterroles", b.role_ref.name)
                else:
                    role = self.store.get("roles", f"{b.namespace}/{b.role_ref.name}")
            except KeyError:
                continue
            yield from role.rules

    def authorize(self, user: UserInfo, verb: str, resource: str,
                  namespace: Optional[str]) -> bool:
        for rule in self._cluster_rules(user):
            if _rule_allows(rule, verb, resource):
                return True
        if namespace:
            for rule in self._namespace_rules(user, namespace):
                if _rule_allows(rule, verb, resource):
                    return True
        return False


def install_bootstrap_rbac(store) -> None:
    """Seed bootstrap policy (bootstrappolicy/policy.go subset): the
    cluster-admin role + system component roles and their bindings.
    Idempotent, like the reference's bootstrap reconciler."""
    from .store import ConflictError

    def _put(kind, obj):
        try:
            store.create(kind, obj)
        except ConflictError:
            pass

    _put("clusterroles", ClusterRole(
        name="cluster-admin",
        rules=[PolicyRule(verbs=["*"], resources=["*"])],
    ))
    _put("clusterrolebindings", ClusterRoleBinding(
        name="cluster-admin",
        role_ref=RoleRef(kind="ClusterRole", name="cluster-admin"),
        subjects=[Subject(kind="Group", name=GROUP_MASTERS)],
    ))
    # scheduler: read everything scheduling-visible; write binds, pod
    # status/nominations, events, leader-election leases
    # (bootstrappolicy/policy.go "system:kube-scheduler")
    _put("clusterroles", ClusterRole(
        name="system:kube-scheduler",
        rules=[
            PolicyRule(verbs=["get", "list", "watch"], resources=["*"]),
            PolicyRule(verbs=["create"], resources=["pods/binding", "events"]),
            PolicyRule(verbs=["update", "delete"], resources=["pods"]),
            PolicyRule(verbs=["create", "update"], resources=["leases"]),
        ],
    ))
    _put("clusterrolebindings", ClusterRoleBinding(
        name="system:kube-scheduler",
        role_ref=RoleRef(kind="ClusterRole", name="system:kube-scheduler"),
        subjects=[Subject(kind="User", name=USER_SCHEDULER)],
    ))
    # kubelets: read their world, heartbeat nodes/leases, report pod
    # status ("system:node" — without the per-node restriction of the
    # NodeAuthorizer, which the reference layers on separately)
    _put("clusterroles", ClusterRole(
        name="system:node",
        rules=[
            PolicyRule(verbs=["get", "list", "watch"],
                       resources=["pods", "nodes", "services", "endpoints"]),
            PolicyRule(verbs=["create", "update"],
                       resources=["nodes", "leases", "events", "podmetrics",
                                  "nodemetrics"]),
            PolicyRule(verbs=["update", "delete"], resources=["pods"]),
        ],
    ))
    _put("clusterrolebindings", ClusterRoleBinding(
        name="system:node",
        role_ref=RoleRef(kind="ClusterRole", name="system:node"),
        subjects=[Subject(kind="Group", name=GROUP_NODES)],
    ))
    # controller-manager: the reference grants each controller a scoped
    # role; collapsed here to full access under one identity
    _put("clusterrolebindings", ClusterRoleBinding(
        name="system:kube-controller-manager",
        role_ref=RoleRef(kind="ClusterRole", name="cluster-admin"),
        subjects=[Subject(kind="User", name=USER_CONTROLLER_MANAGER)],
    ))
