"""Simulated API server: ObjectTracker-style store, resourceVersion watch
streams with 410-compaction, pods/binding subresource."""

from .admission import (
    AdmissionChain,
    AdmissionError,
    Authorizer,
    DefaultTolerationSeconds,
    LimitRangerAdmission,
    PriorityAdmission,
    ResourceQuotaAdmission,
    default_admission_chain,
    install_system_priority_classes,
)
from .auth import (
    ForbiddenError,
    RBACAuthorizer,
    TokenAuthenticator,
    UnauthorizedError,
    UserInfo,
    install_bootstrap_rbac,
)
from .http import APIServerHTTP
from .store import (
    ADDED,
    DELETED,
    MODIFIED,
    ConflictError,
    FakeAPIServer,
    GoneError,
    NotFoundError,
    Watcher,
    WatchEvent,
)

__all__ = [
    "ADDED",
    "AdmissionChain",
    "AdmissionError",
    "Authorizer",
    "DefaultTolerationSeconds",
    "LimitRangerAdmission",
    "PriorityAdmission",
    "ResourceQuotaAdmission",
    "default_admission_chain",
    "install_system_priority_classes",
    "ForbiddenError",
    "RBACAuthorizer",
    "TokenAuthenticator",
    "UnauthorizedError",
    "UserInfo",
    "install_bootstrap_rbac",
    "APIServerHTTP",
    "DELETED",
    "MODIFIED",
    "ConflictError",
    "FakeAPIServer",
    "GoneError",
    "NotFoundError",
    "Watcher",
    "WatchEvent",
]
