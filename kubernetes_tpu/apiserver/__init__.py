"""Simulated API server: ObjectTracker-style store, resourceVersion watch
streams with 410-compaction, pods/binding subresource."""

from .http import APIServerHTTP
from .store import (
    ADDED,
    DELETED,
    MODIFIED,
    ConflictError,
    FakeAPIServer,
    GoneError,
    NotFoundError,
    Watcher,
    WatchEvent,
)

__all__ = [
    "ADDED",
    "APIServerHTTP",
    "DELETED",
    "MODIFIED",
    "ConflictError",
    "FakeAPIServer",
    "GoneError",
    "NotFoundError",
    "Watcher",
    "WatchEvent",
]
