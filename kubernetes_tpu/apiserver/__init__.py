"""Simulated API server: ObjectTracker-style store, resourceVersion watch
streams with 410-compaction, pods/binding subresource."""

from .store import (
    ADDED,
    DELETED,
    MODIFIED,
    ConflictError,
    FakeAPIServer,
    GoneError,
    NotFoundError,
    Watcher,
    WatchEvent,
)

__all__ = [
    "ADDED",
    "DELETED",
    "MODIFIED",
    "ConflictError",
    "FakeAPIServer",
    "GoneError",
    "NotFoundError",
    "Watcher",
    "WatchEvent",
]
