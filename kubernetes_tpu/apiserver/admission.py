"""Admission chain: mutating/validating hooks on apiserver writes.

The reference routes every write through authn → authz → admission
(staging/src/k8s.io/apiserver/pkg/server/config.go handler chain; ~25
plugins under /root/reference/plugin/pkg/admission/). This is the
scheduling-relevant core of that chain:

* `PriorityAdmission` — plugin/pkg/admission/priority/admission.go:137:
  resolves pod.spec.priorityClassName → spec.priority at CREATE (empty
  name → the globalDefault class if one exists, else 0; unknown name →
  reject), and protects the `system-` PriorityClass name prefix
  (admission.go:105-134 — only the two built-in system classes may use
  it).
* `DefaultTolerationSeconds` —
  plugin/pkg/admission/defaulttolerationseconds/admission.go:76: every
  created/updated pod gets NoExecute tolerations for node.kubernetes.io/
  not-ready and /unreachable with tolerationSeconds=300, unless the pod
  already tolerates that taint (this is what gives evictions their 5min
  grace by default; the nodelifecycle controller honors it).

Plugins run in order; each may MUTATE (return a replacement object) or
REJECT (raise AdmissionError → HTTP 422). Authn/authz are modeled as an
always-allow seam (`Authorizer`) — the chain position exists; deployments
needing real policy plug in there.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional

from ..api.types import (
    Pod,
    PriorityClass,
    SYSTEM_PRIORITY_CLASSES,
    Toleration,
)

DEFAULT_NOT_READY_TOLERATION_SECONDS = 300
TAINT_NODE_NOT_READY = "node.kubernetes.io/not-ready"
TAINT_NODE_UNREACHABLE = "node.kubernetes.io/unreachable"


class AdmissionError(Exception):
    """Write rejected by an admission plugin (HTTP 422 on the wire)."""


class Authorizer:
    """authn/authz seam (always-allow): the chain position of the
    reference's authentication/authorization filters. Replace `allow` to
    enforce policy."""

    def allow(self, kind: str, op: str, obj: Any) -> bool:
        return True


class AdmissionChain:
    def __init__(self, plugins: Optional[List] = None, authorizer: Optional[Authorizer] = None):
        self.plugins = list(plugins or [])
        self.authorizer = authorizer or Authorizer()

    def admit(self, store, kind: str, op: str, obj: Any) -> Any:
        """Run the chain for one write; returns the (possibly mutated)
        object or raises AdmissionError. `store` gives plugins read access
        (PriorityClass lookups)."""
        if not self.authorizer.allow(kind, op, obj):
            raise AdmissionError(f"{op} {kind} forbidden")
        for p in self.plugins:
            out = p.admit(store, kind, op, obj)
            if out is not None:
                obj = out
        return obj


class PriorityAdmission:
    """priorityClassName → pod.priority resolution + system- protection."""

    def admit(self, store, kind: str, op: str, obj: Any):
        if kind == "priorityclasses":
            pc: PriorityClass = obj
            if pc.name.startswith("system-") and pc.name not in SYSTEM_PRIORITY_CLASSES:
                raise AdmissionError(
                    f"priority class name {pc.name}: the system- prefix is reserved"
                )
            return None
        if kind != "pods" or op != "CREATE":
            return None
        pod: Pod = obj
        name = pod.priority_class_name
        if not name:
            # no class named: use the global default if one exists
            # (admission.go:160-176), else priority 0 — never override an
            # explicitly-set priority
            if pod.priority is None:
                default = self._global_default(store)
                pod.priority = default.value if default is not None else 0
            return pod
        value = SYSTEM_PRIORITY_CLASSES.get(name)
        if value is None:
            try:
                pc = store.get("priorityclasses", name)
                value = pc.value
            except KeyError:
                raise AdmissionError(f"no PriorityClass with name {name} was found")
        pod.priority = value
        return pod

    @staticmethod
    def _global_default(store) -> Optional[PriorityClass]:
        try:
            items, _ = store.list("priorityclasses")
        except Exception:
            return None
        for pc in items:
            if pc.global_default:
                return pc
        return None


class DefaultTolerationSeconds:
    """Add the default NoExecute not-ready/unreachable tolerations."""

    def __init__(self, seconds: int = DEFAULT_NOT_READY_TOLERATION_SECONDS):
        self.seconds = seconds

    def admit(self, store, kind: str, op: str, obj: Any):
        if kind != "pods" or op not in ("CREATE", "UPDATE"):
            return None
        pod: Pod = obj
        has_not_ready = has_unreachable = False
        for t in pod.tolerations:
            # only a toleration that covers the NoExecute effect counts
            # (admission.go:87-99 checks effect NoExecute or empty) — a
            # NoSchedule-only toleration must not suppress the default
            if t.effect not in ("", "NoExecute"):
                continue
            if t.operator == "Exists" and not t.key:
                has_not_ready = has_unreachable = True  # tolerates everything
            if t.key == TAINT_NODE_NOT_READY:
                has_not_ready = True
            if t.key == TAINT_NODE_UNREACHABLE:
                has_unreachable = True
        for key, present in (
            (TAINT_NODE_NOT_READY, has_not_ready),
            (TAINT_NODE_UNREACHABLE, has_unreachable),
        ):
            if not present:
                pod.tolerations = pod.tolerations + [
                    Toleration(
                        key=key,
                        operator="Exists",
                        effect="NoExecute",
                        toleration_seconds=self.seconds,
                    )
                ]
        return pod


def default_admission_chain() -> AdmissionChain:
    """The default-on scheduling-relevant plugin set (the reference enables
    Priority and DefaultTolerationSeconds in its recommended plugins,
    kubeapiserver/options/plugins.go)."""
    return AdmissionChain([PriorityAdmission(), DefaultTolerationSeconds()])


def install_system_priority_classes(store) -> None:
    """Seed the built-in system classes (the reference's scheduling REST
    PostStartHook creates them at startup)."""
    from ..apiserver.store import ConflictError

    for name, value in SYSTEM_PRIORITY_CLASSES.items():
        try:
            store.create(
                "priorityclasses",
                PriorityClass(name=name, value=value, description="built-in"),
            )
        except ConflictError:
            pass
