"""Admission chain: mutating/validating hooks on apiserver writes.

The reference routes every write through authn → authz → admission
(staging/src/k8s.io/apiserver/pkg/server/config.go handler chain; ~25
plugins under /root/reference/plugin/pkg/admission/). This is the
scheduling-relevant core of that chain:

* `PriorityAdmission` — plugin/pkg/admission/priority/admission.go:137:
  resolves pod.spec.priorityClassName → spec.priority at CREATE (empty
  name → the globalDefault class if one exists, else 0; unknown name →
  reject), and protects the `system-` PriorityClass name prefix
  (admission.go:105-134 — only the two built-in system classes may use
  it).
* `DefaultTolerationSeconds` —
  plugin/pkg/admission/defaulttolerationseconds/admission.go:76: every
  created/updated pod gets NoExecute tolerations for node.kubernetes.io/
  not-ready and /unreachable with tolerationSeconds=300, unless the pod
  already tolerates that taint (this is what gives evictions their 5min
  grace by default; the nodelifecycle controller honors it).

Plugins run in order; each may MUTATE (return a replacement object) or
REJECT (raise AdmissionError → HTTP 422). Authn/authz are modeled as an
always-allow seam (`Authorizer`) — the chain position exists; deployments
needing real policy plug in there.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

from ..api.types import (
    LimitRange,
    Pod,
    PriorityClass,
    ResourceQuota,
    SYSTEM_PRIORITY_CLASSES,
    Toleration,
    _request_value,
)

DEFAULT_NOT_READY_TOLERATION_SECONDS = 300
TAINT_NODE_NOT_READY = "node.kubernetes.io/not-ready"
TAINT_NODE_UNREACHABLE = "node.kubernetes.io/unreachable"


class AdmissionError(Exception):
    """Write rejected by an admission plugin (HTTP 422 on the wire)."""


class Authorizer:
    """In-process authz seam (always-allow). REAL authn/authz lives in
    the HTTP front door (apiserver/auth.py: TokenAuthenticator +
    RBACAuthorizer wired into APIServerHTTP), matching the reference
    where authentication/authorization are handler-chain filters, not
    admission plugins. This seam remains for in-process (loopback)
    callers, which the reference also exempts via the loopback client's
    system:masters identity."""

    def allow(self, kind: str, op: str, obj: Any) -> bool:
        return True


class AdmissionChain:
    def __init__(self, plugins: Optional[List] = None, authorizer: Optional[Authorizer] = None):
        self.plugins = list(plugins or [])
        self.authorizer = authorizer or Authorizer()

    def admit(self, store, kind: str, op: str, obj: Any,
              undo: Optional[List[Callable[[], None]]] = None) -> Any:
        """Run the chain for one write; returns the (possibly mutated)
        object or raises AdmissionError. `store` gives plugins read access
        (PriorityClass lookups). Plugins with external side effects (quota
        charges) declare `supports_undo = True` and append rollback
        callables to `undo` — the store runs them (reversed) when the
        write itself fails AFTER admission (duplicate-name ConflictError),
        so a rejected create can't strand quota usage."""
        if not self.authorizer.allow(kind, op, obj):
            raise AdmissionError(f"{op} {kind} forbidden")
        for p in self.plugins:
            if undo is not None and getattr(p, "supports_undo", False):
                out = p.admit(store, kind, op, obj, undo=undo)
            else:
                out = p.admit(store, kind, op, obj)
            if out is not None:
                obj = out
        return obj


class PriorityAdmission:
    """priorityClassName → pod.priority resolution + system- protection."""

    def admit(self, store, kind: str, op: str, obj: Any):
        if kind == "priorityclasses":
            pc: PriorityClass = obj
            if pc.name.startswith("system-") and pc.name not in SYSTEM_PRIORITY_CLASSES:
                raise AdmissionError(
                    f"priority class name {pc.name}: the system- prefix is reserved"
                )
            return None
        if kind != "pods" or op != "CREATE":
            return None
        pod: Pod = obj
        name = pod.priority_class_name
        if not name:
            # no class named: use the global default if one exists
            # (admission.go:160-176), else priority 0 — never override an
            # explicitly-set priority
            if pod.priority is None:
                default = self._global_default(store)
                pod.priority = default.value if default is not None else 0
            return pod
        value = SYSTEM_PRIORITY_CLASSES.get(name)
        if value is None:
            try:
                pc = store.get("priorityclasses", name)
                value = pc.value
            except KeyError:
                raise AdmissionError(f"no PriorityClass with name {name} was found")
        pod.priority = value
        return pod

    @staticmethod
    def _global_default(store) -> Optional[PriorityClass]:
        try:
            items, _ = store.list("priorityclasses")
        except Exception:
            return None
        for pc in items:
            if pc.global_default:
                return pc
        return None


class DefaultTolerationSeconds:
    """Add the default NoExecute not-ready/unreachable tolerations."""

    def __init__(self, seconds: int = DEFAULT_NOT_READY_TOLERATION_SECONDS):
        self.seconds = seconds

    def admit(self, store, kind: str, op: str, obj: Any):
        if kind != "pods" or op not in ("CREATE", "UPDATE"):
            return None
        pod: Pod = obj
        has_not_ready = has_unreachable = False
        for t in pod.tolerations:
            # only a toleration that covers the NoExecute effect counts
            # (admission.go:87-99 checks effect NoExecute or empty) — a
            # NoSchedule-only toleration must not suppress the default
            if t.effect not in ("", "NoExecute"):
                continue
            if t.operator == "Exists" and not t.key:
                has_not_ready = has_unreachable = True  # tolerates everything
            if t.key == TAINT_NODE_NOT_READY:
                has_not_ready = True
            if t.key == TAINT_NODE_UNREACHABLE:
                has_unreachable = True
        for key, present in (
            (TAINT_NODE_NOT_READY, has_not_ready),
            (TAINT_NODE_UNREACHABLE, has_unreachable),
        ):
            if not present:
                pod.tolerations = pod.tolerations + [
                    Toleration(
                        key=key,
                        operator="Exists",
                        effect="NoExecute",
                        toleration_seconds=self.seconds,
                    )
                ]
        return pod


class LimitRangerAdmission:
    """LimitRanger (plugin/pkg/admission/limitranger/admission.go:77):
    at pod CREATE, apply each namespace LimitRange's Container-type
    defaults (defaultRequest → requests, default → limits; a defaulted
    limit also backs an absent request, matching the API defaulting the
    reference gets from pkg/apis/core/v1/defaults.go), then enforce
    min/max. Defaulted requests CHANGE WHAT THE SCHEDULER SEES — a pod
    with no requests in a defaulting namespace is scheduled at the
    defaults, not at zero."""

    def admit(self, store, kind: str, op: str, obj: Any):
        if kind != "pods" or op != "CREATE":
            return None
        pod: Pod = obj
        try:
            ranges, _ = store.list("limitranges")
        except Exception:
            return None
        ranges = [lr for lr in ranges if lr.namespace == pod.namespace]
        if not ranges:
            return None
        mutated = False
        for lr in ranges:
            for item in lr.limits:
                if item.type != "Container":
                    continue
                for c in list(pod.containers) + list(pod.init_containers):
                    for r, q in item.default.items():
                        if r not in c.limits:
                            c.limits[r] = q
                            mutated = True
                    for r, q in item.default_request.items():
                        if r not in c.requests:
                            c.requests[r] = q
                            mutated = True
                    # no defaultRequest for r but a limit (given or
                    # defaulted) exists → request defaults to the limit
                    for r, q in c.limits.items():
                        if r not in c.requests:
                            c.requests[r] = q
                            mutated = True
                    # min binds requests AND limits, exactly like max below
                    # (the reference's limitranger minConstraint checks
                    # both; an explicit limit under min must reject)
                    for r, q in item.min.items():
                        lo = _request_value(r, q)
                        for which, d in (("request", c.requests), ("limit", c.limits)):
                            got = d.get(r)
                            if got is not None and _request_value(r, got) < lo:
                                raise AdmissionError(
                                    f"minimum {r} usage per Container is {lo}, "
                                    f"but {which} is {_request_value(r, got)}"
                                )
                    for r, q in item.max.items():
                        hi = _request_value(r, q)
                        for which, d in (("request", c.requests), ("limit", c.limits)):
                            got = d.get(r)
                            if got is not None and _request_value(r, got) > hi:
                                raise AdmissionError(
                                    f"maximum {r} usage per Container is {hi}, "
                                    f"but {which} is {_request_value(r, got)}"
                                )
        if mutated:
            # requests changed after a possible resource_request() memo on
            # this copy — drop stale memos so the scheduler sees defaults
            pod.__dict__.pop("_req_cache", None)
        return pod


class ResourceQuotaAdmission:
    """ResourceQuota admission (plugin/pkg/admission/resourcequota/
    admission.go + controller.go checkQuotas): a CREATE that would push a
    matching quota's usage over spec.hard is REJECTED before the object
    exists — the scheduler never sees it. Admitted usage is charged to
    quota.status.used synchronously (the reference's quota admission
    writes status through the API the same way); the resourcequota
    controller's full recompute corrects drift and replenishes on delete.
    Charges are compare-and-swap on resourceVersion so concurrent creates
    can't both squeeze through the last unit of quota."""

    #: kinds whose CREATE is never quota-checked (quota objects themselves,
    #: and status-ish kinds the reference's evaluator registry skips)
    _EXEMPT = {"resourcequotas", "events", "podmetrics", "leases"}

    #: external side effects (status.used charges) need rollback when the
    #: store rejects the write after admission (AdmissionChain.admit undo)
    supports_undo = True

    def admit(self, store, kind: str, op: str, obj: Any,
              undo: Optional[List[Callable[[], None]]] = None):
        if op != "CREATE" or kind in self._EXEMPT:
            return None
        ns = getattr(obj, "namespace", None)
        if not ns:
            return None
        try:
            quotas, _ = store.list("resourcequotas")
        except Exception:
            return None
        # two-phase (compute-all, check-all, then charge) so a rejection by
        # a LATER matching quota — or by the store's duplicate-name check —
        # never strands usage on an earlier one (the reference's admission
        # evaluates every matching quota atomically, checkQuotas)
        charges: List[tuple] = []
        for quota in quotas:
            if quota.namespace != ns:
                continue
            delta = self._delta(quota, kind, obj)
            if delta:
                charges.append((quota.key(), delta))
        applied: List[tuple] = []
        try:
            for quota_key, delta in charges:
                self._charge(store, quota_key, delta)
                applied.append((quota_key, delta))
        except AdmissionError:
            for quota_key, delta in reversed(applied):
                self._uncharge(store, quota_key, delta)
            raise
        if undo is not None:
            for quota_key, delta in applied:
                undo.append(
                    lambda qk=quota_key, d=delta: self._uncharge(store, qk, d)
                )
        return None

    @staticmethod
    def _delta(quota: ResourceQuota, kind: str, obj: Any) -> Dict[str, int]:
        delta: Dict[str, int] = {}
        if kind == "pods":
            if "pods" in quota.hard:
                delta["pods"] = 1
            req = None
            for k in quota.hard:
                if k.startswith("requests."):
                    if req is None:
                        req = obj.resource_request()
                    delta[k] = req.get(k.split(".", 1)[1], 0)
        ck = f"count/{kind}"
        if ck in quota.hard:
            delta[ck] = 1
        return {k: v for k, v in delta.items() if v}

    @staticmethod
    def _charge(store, quota_key: str, delta: Dict[str, int]) -> None:
        from .store import ConflictError, NotFoundError

        for _ in range(16):  # CAS retry under concurrent admissions
            try:
                live: ResourceQuota = store.get("resourcequotas", quota_key)
            except NotFoundError:
                return  # quota deleted mid-admission: nothing to enforce
            new_used = dict(live.used)
            for k, d in delta.items():
                new_used[k] = new_used.get(k, 0) + d
                if new_used[k] > live.hard.get(k, 0):
                    raise AdmissionError(
                        f"exceeded quota: {quota_key.split('/', 1)[1]}, "
                        f"requested: {k}={d}, used: {k}={live.used.get(k, 0)}, "
                        f"limited: {k}={live.hard[k]}"
                    )
            live.used = new_used
            try:
                store.update("resourcequotas", live, check_rv=True)
                return
            except ConflictError:
                continue  # another admission charged first — re-read
        raise AdmissionError(f"quota {quota_key}: charge contention, retry")

    @staticmethod
    def _uncharge(store, quota_key: str, delta: Dict[str, int]) -> None:
        """CAS-decrement a previous charge (floored at 0 — the controller's
        full recompute is the drift backstop). Best-effort: a vanished
        quota needs no refund."""
        from .store import ConflictError, NotFoundError

        for _ in range(16):
            try:
                live: ResourceQuota = store.get("resourcequotas", quota_key)
            except NotFoundError:
                return
            new_used = dict(live.used)
            for k, d in delta.items():
                new_used[k] = max(new_used.get(k, 0) - d, 0)
            live.used = new_used
            try:
                store.update("resourcequotas", live, check_rv=True)
                return
            except ConflictError:
                continue


def default_admission_chain() -> AdmissionChain:
    """The default-on scheduling-relevant plugin set (the reference enables
    Priority, DefaultTolerationSeconds, LimitRanger and ResourceQuota in
    its recommended plugins, kubeapiserver/options/plugins.go; quota runs
    LAST so it charges post-mutation values)."""
    return AdmissionChain([
        PriorityAdmission(),
        DefaultTolerationSeconds(),
        LimitRangerAdmission(),
        ResourceQuotaAdmission(),
    ])


def install_system_priority_classes(store) -> None:
    """Seed the built-in system classes (the reference's scheduling REST
    PostStartHook creates them at startup)."""
    from ..apiserver.store import ConflictError

    for name, value in SYSTEM_PRIORITY_CLASSES.items():
        try:
            store.create(
                "priorityclasses",
                PriorityClass(name=name, value=value, description="built-in"),
            )
        except ConflictError:
            pass
