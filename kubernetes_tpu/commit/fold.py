"""Fold planner: a committed batch's state deltas as device control data.

The resident-state plane's host half. Given the final placed set of a
batch — (pod, node row) pairs the driver is about to bulk-assume — this
builds the padded control arrays ops/fold.fold_commit_banks scatters into
the resident device banks: per-pod request vectors, non-zero scoring
requests, signature rows, and (for affinity carriers) pattern-count
triples. Every value comes from the SAME memoized source the host delta
path reads (state/tensors._req_slot_pairs, oracle.pod_non_zero_request,
SigBank/PatternBank interning), so the fold is bit-identical to the host
scatter it replaces.

Signatures/patterns are PRE-interned here (SigBank.prepare_row /
PatternBank.prepare_pod_rows): the row indices must exist before the fold
dispatches, and new rows' metadata rides the normal dirty-row patch while
the counts arrive by fold. Any bank overflow (sig/pattern/key-slot) makes
plan_fold return None — the caller falls back to the host scatter path
for the batch and the mirror's next sync rebuilds bigger, exactly as it
would have anyway.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..oracle.nodeinfo import pod_non_zero_request
from ..state.tensors import KeySlotOverflow, _bucket, _req_slot_pairs


@dataclass
class FoldProgram:
    """Padded control arrays for ONE fold_commit_banks dispatch. Index
    sentinels (row=N, sig=S, pattern=PT) mark padding; the kernel drops
    out-of-bounds scatters."""

    rows: np.ndarray     # [B] int32 node row
    req: np.ndarray      # [B, R] int64
    nz: np.ndarray       # [B, 2] int64
    cnt: np.ndarray      # [B] int32
    sig: np.ndarray      # [B] int32
    pat_row: np.ndarray  # [T] int32
    pat_col: np.ndarray  # [T] int32
    pat_cnt: np.ndarray  # [T] int16
    pods: int            # real (unpadded) commit count

    @property
    def pat_bucket(self) -> int:
        return int(self.pat_row.shape[0])

    @property
    def nbytes(self) -> int:
        """Host→device control bytes this fold ships (the whole wire cost
        of the batch's bank update)."""
        return sum(
            a.nbytes
            for a in (
                self.rows, self.req, self.nz, self.cnt, self.sig,
                self.pat_row, self.pat_col, self.pat_cnt,
            )
        )


# ktpu: hot-path fold planning runs between solve fetch and commit submit
def plan_fold(
    mirror,
    pairs: Sequence[Tuple[object, int]],
    row_bucket: int,
    pat_bucket: int,
) -> Optional[FoldProgram]:
    """Build a FoldProgram for `pairs` = [(pod, node_row)] against
    `mirror`'s current bank shapes. `row_bucket` must be a ladder rung ≥
    len(pairs) (the driver's monotone batch bucket); `pat_bucket` is the
    caller's current pattern-triple rung — grown to the next rung here
    when the batch carries more pattern instances (the caller keeps the
    returned program's pat_bucket as its new monotone floor). Returns
    None on any bank overflow (caller falls back to the host scatter)."""
    n = len(pairs)
    if n == 0 or n > row_bucket:
        return None
    nodes = mirror.nodes
    n_cap = nodes.capacity
    width = nodes.requested.shape[1]
    s_cap = mirror.eps.capacity
    p_cap = mirror.pats.capacity
    rows = np.full(row_bucket, n_cap, np.int32)
    req = np.zeros((row_bucket, width), np.int64)
    nz = np.zeros((row_bucket, 2), np.int64)
    cnt = np.zeros(row_bucket, np.int32)
    sig = np.full(row_bucket, s_cap, np.int32)
    triples: List[Tuple[int, int]] = []
    vocab = mirror.vocab
    # ONE DELTA SOURCE (state/columns.py): with the columnar cache
    # attached, the per-pod request/non-zero vectors GATHER from the same
    # interned spec rows the host columns scatter by — the device fold
    # and the host cache advance from literally the same integers
    # (INVARIANTS.md one-delta-source rule). Without columns, the legacy
    # per-pod build from the same memoized sources.
    cols = getattr(mirror.cache, "_columns", None)
    if cols is not None and cols.vocab is not mirror.vocab:
        # columns rebuilt on another scheduler's Vocab (attach_columns
        # re-attach): its spec rows are in a different resource-slot
        # order — gathering them would scatter wrong-slot matrices into
        # THIS mirror's banks. Fall back to the per-pod build.
        cols = None
    try:
        if cols is not None:
            req_m, nz_m = cols.delta_mats([p for p, _ in pairs], width)
            req[:n] = req_m
            nz[:n] = nz_m
        for i, (pod, row) in enumerate(pairs):
            rows[i] = row
            if cols is None:
                for s, v in _req_slot_pairs(vocab, pod):
                    if s >= width:
                        raise KeySlotOverflow()
                    req[i, s] = v
                c, m = pod_non_zero_request(pod)
                nz[i, 0] = c
                nz[i, 1] = m
            cnt[i] = 1
            sig[i] = mirror.eps.prepare_row(pod)
            for prow in mirror.pats.prepare_pod_rows(pod):
                triples.append((row, prow))
    except KeySlotOverflow:
        # covers SigOverflow/PatternOverflow subclasses: the banks are
        # full — the host path raises the same way and rebuilds bigger
        return None
    t_bucket = max(pat_bucket, _bucket(max(len(triples), 1)))
    pat_row = np.full(t_bucket, n_cap, np.int32)
    pat_col = np.full(t_bucket, p_cap, np.int32)
    pat_cnt = np.zeros(t_bucket, np.int16)
    for j, (prow, pcol) in enumerate(triples):
        pat_row[j] = prow
        pat_col[j] = pcol
        pat_cnt[j] = 1
    prog = FoldProgram(
        rows=rows, req=req, nz=nz, cnt=cnt, sig=sig,
        pat_row=pat_row, pat_col=pat_col, pat_cnt=pat_cnt, pods=n,
    )
    return prog
