"""Commit plane: device-arbitrated intra-batch conflict resolution,
columnar bulk apply, and solve/commit pipelining (ISSUE 2 tentpole).

The solve half of the cycle was converted to one vectorized device
program in the seed; this package converts the COMMIT half:

* `arbiter`  — jitted sequential-equivalent verdict pass over the solve's
  assignment rows (place / defer-to-next-batch), bit-identical to the
  host recheck walk (`host_arbitrate` is the executable spec).
* `apply`    — columnar bulk apply: one cache assume + one nomination
  clear + chunked lean binds per batch; single rollback record per gang.
* `pipeline` — double-buffered apply worker with ≤1-batch-stale
  backpressure, overlapping batch N's apply with batch N+1's solve fetch.
* `fold`     — resident-state plane planner (ISSUE 3 tentpole): a
  committed batch's state deltas as padded device control data for
  ops/fold's donated scatter-adds, so covered batches' solve inputs stop
  crossing the host↔device wire entirely.
"""

from .apply import ApplyResult, ColumnarApply, GangRollbackRecord
from .fold import FoldProgram, plan_fold
from .arbiter import (
    ARBITER_COVERED_KINDS,
    V_DEFER,
    V_NOFIT,
    V_PLACE,
    arbitrate,
    host_arbitrate,
    kinds_covered,
)
from .pipeline import CommitPipeline

__all__ = [
    "ARBITER_COVERED_KINDS",
    "ApplyResult",
    "ColumnarApply",
    "CommitPipeline",
    "FoldProgram",
    "GangRollbackRecord",
    "plan_fold",
    "V_DEFER",
    "V_NOFIT",
    "V_PLACE",
    "arbitrate",
    "host_arbitrate",
    "kinds_covered",
]
