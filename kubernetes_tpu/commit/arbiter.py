"""Device commit arbiter: the sequential-equivalent verdict pass.

The solve (ops/solver.py) picks nodes against batch-START state; the host
commit loop then re-validates each pick against the commits made EARLIER
in the same batch (scheduler/driver.py LIGHT/FULL rechecks +
_BatchConflictIndex) — a per-pod Python walk that dominates commit wall on
term-heavy batches. This module moves that walk onto the device: one
jitted scan over the solve's assignment rows, in exactly the queue's pop
order, emitting a per-pod VERDICT:

  V_PLACE  — the device pick survives every earlier in-batch commit:
             capacity, pod count, required anti-affinity (both
             directions), host ports, and DoNotSchedule topology spread.
  V_DEFER  — an earlier commit invalidated the pick (or a -1 became
             potentially feasible because a commit raised a hard-spread
             domain minimum): the pod retries NEXT batch, where a fresh
             solve sees the committed state in its mask. Defer-to-next-
             batch replaces the legacy in-batch oracle re-place — the
             placement arrives one cycle later but through the exact
             device mask instead of an O(cluster) host scan.
  V_NOFIT  — the solve's -1 stands (the feasible set only shrinks within
             a batch for everything the arbiter tracks).

Bit-exactness contract: the verdicts equal what a host walk would decide
re-checking each pod, in pop order, against a snapshot that assumes every
earlier V_PLACE pod (tests/test_commit_plane.py pins this against
`host_arbitrate`, the pure-oracle reference walk below). The state the
arbiter carries mirrors the solver's in-batch tracking (ca/cb/cs) plus a
hard-spread delta table replaying exactly spread_filter's merged
per-(term, topology-value) counts.

Coverage: the arbiter handles batches whose PRESENT term kinds are all in
ARBITER_COVERED_KINDS. Required pod AFFINITY (aff_req) is excluded — an
in-batch commit can make an affinity pod's -1 feasible (the anchor case,
predicates.go:1269) in ways that need the host oracle's re-placement, and
its FULL recheck can also move a placement rather than just veto it.
Score-only kinds (soft spread, preferred affinity, selector spread) never
invalidate a commit and are covered by construction.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from ..state.terms import SPREAD_HARD

Arrays = Dict[str, jnp.ndarray]

V_PLACE = 0
V_DEFER = 1
V_NOFIT = 2

_BIG = 2**30

#: term kinds whose intra-batch interactions the device arbiter resolves
#: exactly; a batch presenting any OTHER kind takes the legacy host loop.
#: Score-only kinds (spread_soft, pref, sel_spread, et_score) shift scores,
#: never validity — batch-stale scores are the accepted batching contract.
#: et_anti (EXISTING pods' anti terms) is static within a batch: the
#: batch-start mask covers it, and commits' own anti terms are tracked.
ARBITER_COVERED_KINDS = frozenset({
    "anti_req", "spread_hard", "spread_soft", "pref", "sel_spread",
    "et_anti", "et_score",
})


def kinds_covered(present_kinds) -> bool:
    """True when every term kind PRESENT in a batch is arbiter-covered."""
    return frozenset(present_kinds) <= ARBITER_COVERED_KINDS


def _spread_tables(na, pa, ea, ta, bucket_n, haskey_n, V: int):
    """Pre-batch DoNotSchedule-spread metadata for the verdict scan —
    EXACTLY ops/topology.spread_filter's merged per-(term, topology-value)
    match counts (same helpers), shared by the single-device and the
    sharded arbiter so the two can never disagree. All outputs are either
    replicated [TT, V]/[TT]/[U]-shaped tables or the node-major cand_t
    [TT, N] (sharded on a mesh)."""
    from ..ops import filters as F
    from ..ops.topology import (
        _merge_same_key,
        _scatter_and,
        _seg_sum,
        _sig_cnt_node,
        match_terms,
    )

    U = pa["valid"].shape[0]
    hard = ta["valid"] & (ta["kind"] == SPREAD_HARD)
    owner = ta["owner"].astype(jnp.int32)
    sel = F.pod_match_node_selector(na, pa)  # [U, N]
    all_keys = _scatter_and(haskey_n, ta["owner"], hard, U)
    cand = sel & all_keys & na["valid"][None, :]
    m_sig = (
        match_terms(ta, ea["label_vals"], ea["ns_id"])
        & ea["valid"][None, :]
        & hard[:, None]
    )
    cnt_node = _sig_cnt_node(m_sig, ea["counts"])  # [TT, N]
    cand_t = cand[ta["owner"]]  # [TT, N]
    pair_cnt = _seg_sum(jnp.where(cand_t, cnt_node, 0), bucket_n, V)
    pair_present = (
        _seg_sum((cand_t & haskey_n).astype(jnp.int32), bucket_n, V) > 0
    )
    merged_cnt0 = _merge_same_key(ta, hard, pair_cnt).astype(jnp.int32)
    merged_present = (
        _merge_same_key(ta, hard, pair_present.astype(jnp.int32)) > 0
    )
    any_pair_t = jnp.any(merged_present, axis=1)
    any_pair_u = (
        jnp.zeros(U + 1, bool)
        .at[jnp.where(hard, ta["owner"], U)]
        .max(any_pair_t & hard)[:U]
    )
    # batch-spec match per hard term (for commit deltas and the -1
    # could-fit rule): term ns_ids were compiled to [owner namespace],
    # so this is exactly "same namespace AND selector matches"
    m_batch_hard = (
        match_terms(ta, pa["label_vals"], pa["ns_id"]) & hard[:, None]
    )  # [TT, U]
    # terms sharing (owner, topology key) share one merged count table
    # (metadata.go tpPairToMatchNum): group-sum the per-term matches so
    # one scatter per commit updates the merged table directly (group
    # members share bucket_n rows — same topo_slot)
    same = (
        hard[:, None]
        & hard[None, :]
        & (owner[:, None] == owner[None, :])
        & (ta["topo_slot"][:, None] == ta["topo_slot"][None, :])
    )
    gm = jnp.matmul(
        same.astype(jnp.float32),
        m_batch_hard.astype(jnp.float32),
        precision=jax.lax.Precision.HIGHEST,
    ).astype(jnp.int32)  # [TT, U]
    return {
        "hard": hard,
        "owner": owner,
        "cand_t": cand_t,
        "merged_cnt0": merged_cnt0,
        "merged_present": merged_present,
        "any_pair_u": any_pair_u,
        "m_batch_hard": m_batch_hard,
        "gm": gm,
        "self_m": ta["self_match"].astype(jnp.int32),
        "skew": ta["weight"].astype(jnp.int32),
    }


# ktpu: admitted(KIND_ARBITER) dispatched by the driver only after
# _arbiter_spec admission; both carry variants warmed in lockstep with the
# solve ladder (compile/warmup)
@partial(jax.jit, static_argnames=("term_kinds", "n_buckets"))
def arbitrate(
    na: Arrays,   # NodeBank arrays (same dict the solve consumed)
    pa: Arrays,   # PodBatch arrays (unique-spec rows)
    ea: Arrays,   # SigBank arrays (existing-pod signatures, spread counts)
    ta: Arrays,   # batch TermBank arrays (host-compiled or term-plane gathered)
    ids: Arrays,  # interned constants (filters.make_ids)
    assign: jnp.ndarray,  # [B] the solve's node row per pod (-1 = no fit)
    pb: Arrays,   # per-pod axis: sig/valid/priority [B]
    carry: Optional[Tuple] = None,  # same residual carry the solve ran on
    term_kinds: Optional[frozenset] = None,
    n_buckets: Optional[int] = None,
) -> jnp.ndarray:
    """Verdict [B] (V_PLACE / V_DEFER / V_NOFIT) per batch position.

    Sequential by construction: a lax.scan walks the pods in pop order
    (the same pop_order the solver used), each step checking the pod's
    assigned node against the state left by every earlier V_PLACE step,
    then folding its own commit in. The per-step work is a handful of
    [TT]/[N]-sized gathers — B serial steps of tiny kernels, milliseconds
    where the host walk it replaces was seconds. `carry` must be the SAME
    residual tuple the solve dispatched against (speculative pipelining),
    so the arbiter replays from the state the assignment was computed on.
    """
    from ..ops.pipeline import _inbatch_tensors, apply_carry
    from ..ops.solver import pop_order

    na = apply_carry(na, carry)
    sig = pb["sig"]
    pod_valid = pb["valid"]
    B = sig.shape[0]
    U = pa["valid"].shape[0]
    N = na["valid"].shape[0]
    V = n_buckets or N
    order = pop_order(pb["priority"], jnp.arange(B, dtype=jnp.int32), pod_valid)

    free0 = na["alloc"] - na["requested"]
    count0 = na["pod_count"].astype(free0.dtype)
    allowed = na["allowed_pods"].astype(free0.dtype)
    req = pa["req"]
    req_any = pa["req_any"]

    # anti-affinity + host-port tracking tensors — the SAME builder the
    # solver's in-batch tracking uses, so the two can never disagree
    inb = _inbatch_tensors(na, pa, ta, ids, n_buckets)
    t_anti = inb["anti"]
    t_owner = inb["owner"]
    m_bb = inb["m_bb"] & t_anti[:, None]  # [TT, U]
    bucket_n = inb["bucket_n"]  # [TT, N]
    haskey_n = inb["haskey_n"]
    pconf = inb["port_conflict"]  # [U, U]
    TT = t_anti.shape[0]
    t_rows = jnp.arange(TT, dtype=jnp.int32)

    have_spread = term_kinds is None or "spread_hard" in term_kinds
    if have_spread:
        # pre-batch merged per-(term, topology-value) match counts —
        # EXACTLY ops/topology.spread_filter's metadata (same helpers), so
        # check-time arithmetic below reproduces its skew predicate with
        # the counts advanced by this batch's commits
        sp = _spread_tables(na, pa, ea, ta, bucket_n, haskey_n, V)
        hard, owner, cand_t = sp["hard"], sp["owner"], sp["cand_t"]
        merged_cnt0, merged_present = sp["merged_cnt0"], sp["merged_present"]
        any_pair_u, m_batch_hard = sp["any_pair_u"], sp["m_batch_hard"]
        gm, self_m, skew = sp["gm"], sp["self_m"], sp["skew"]

    one = jnp.float32(1.0)

    def step(carry, p):
        free, count, ca, cb, cs, md, mh = carry
        u = sig[p]
        n = assign[p]
        pv = pod_valid[p]
        is_m1 = n < 0
        ncl = jnp.maximum(n, 0)
        r_q = req[u]
        # PodFitsResources against the state earlier V_PLACE commits left
        # (defense in depth: the solver's carry already sequentialized
        # resources, and defers only RELEASE capacity, so this cannot fire
        # on a healthy replay — but the host walk checks it, so the
        # verdict contract does too)
        cap_ok = ((~req_any[u]) | jnp.all(r_q <= free[ncl])) & (
            count[ncl] + 1 <= allowed[ncl]
        )
        buck = bucket_n[:, ncl]  # [TT]
        hk = haskey_n[:, ncl]
        own_u = (t_owner == u) & t_anti
        # required anti-affinity, both directions (predicates.go:1284
        # within the batch): my terms vs matching earlier commits (ca),
        # earlier commits' terms vs me (cb) — same tables as the solver
        block_a = jnp.any(own_u & hk & (ca[t_rows, buck] > 0))
        block_b = jnp.any(m_bb[:, u] & hk & (cb[t_rows, buck] > 0))
        block_p = jnp.any(pconf[u] & (cs[:, ncl] > 0))
        if have_spread:
            own_h = hard & (owner == u)
            cnt = merged_cnt0 + md  # [TT, V]
            min_t = jnp.min(
                jnp.where(merged_present, cnt, jnp.int32(_BIG)), axis=1
            )  # [TT]
            at_b = jnp.where(
                merged_present[t_rows, buck], cnt[t_rows, buck], 0
            )
            skew_ok_t = hk & (at_b + self_m - min_t <= skew)
            sp_ok = jnp.all(jnp.where(own_h, skew_ok_t, True)) | ~any_pair_u[u]
            # -1 could-fit (driver._minus_one_could_fit, spread half): an
            # earlier commit matching one of my hard constraints raised the
            # domain minimum — the feasible set may have WIDENED
            couldfit = jnp.any(own_h & (mh > 0))
        else:
            sp_ok = jnp.bool_(True)
            couldfit = jnp.bool_(False)
        ok = cap_ok & ~block_a & ~block_b & ~block_p & sp_ok
        commit = pv & ~is_m1 & ok
        verdict = jnp.where(
            ~pv,
            V_NOFIT,
            jnp.where(
                is_m1,
                jnp.where(couldfit, V_DEFER, V_NOFIT),
                jnp.where(ok, V_PLACE, V_DEFER),
            ),
        ).astype(jnp.int32)
        # fold this commit into the tracked state (scatter index V/N/U on
        # non-commits — dropped)
        tgt = jnp.where(commit, ncl, N)
        free = free.at[tgt].add(-(r_q * commit), mode="drop")
        count = count.at[tgt].add(commit.astype(count.dtype), mode="drop")
        hkc = hk & commit
        ca = ca.at[t_rows, jnp.where(m_bb[:, u] & hkc, buck, V)].add(
            one, mode="drop"
        )
        cb = cb.at[t_rows, jnp.where(own_u & hkc, buck, V)].add(
            one, mode="drop"
        )
        cs = cs.at[jnp.where(commit, u, U), ncl].add(one, mode="drop")
        if have_spread:
            contrib = jnp.where(hard & commit & cand_t[:, ncl], gm[:, u], 0)
            md = md.at[t_rows, jnp.where(contrib > 0, buck, V)].add(
                contrib, mode="drop"
            )
            mh = mh + jnp.where(commit, m_batch_hard[:, u], False).astype(
                mh.dtype
            )
        return (free, count, ca, cb, cs, md, mh), verdict

    carry0 = (
        free0,
        count0,
        jnp.zeros((TT, V), jnp.float32),
        jnp.zeros((TT, V), jnp.float32),
        jnp.zeros((U, N), jnp.float32),
        jnp.zeros((TT, V), jnp.int32),
        jnp.zeros((TT,), jnp.int32),
    )
    _, verdicts = jax.lax.scan(step, carry0, order)
    out = jnp.full((B,), V_NOFIT, jnp.int32)
    return out.at[order].set(verdicts)


# ---------------------------------------------------------------------------
# multi-chip arbiter: the same sequential verdict scan over node-sharded
# banks (the commit plane's half of the "ship control, not state"
# discipline on the mesh the paper targets)
# ---------------------------------------------------------------------------


def _arbiter_body_sharded(
    free0,      # [Nl, R] shard-local residuals
    count0,     # [Nl]
    allowed,    # [Nl]
    assign,     # [B] replicated
    sig,        # [B]
    pod_valid,  # [B]
    order,      # [B]
    req,        # [U, R] replicated
    req_any,    # [U]
    t_anti,     # [TT] replicated
    t_owner,    # [TT]
    m_bb,       # [TT, U] replicated (already masked by t_anti)
    bucket_nl,  # [TT, Nl] shard-local node columns
    haskey_nl,  # [TT, Nl]
    pconf,      # [U, U] replicated
    spread,     # dict of replicated tables + shard-local cand_t, or None
    *,
    n_local: int,
    V: int,
):
    """shard_map body: the multi-chip twin of `arbitrate`'s scan. Per-node
    state (free/count residuals, the cs port table, the bucket/haskey
    columns) stays SHARD-LOCAL; the [TT, V] anti/spread delta tables are
    replicated (their updates are pure functions of broadcast commit
    data). Each step pays exactly ONE packed pmax: the assigned node's
    owner shard contributes (cap_ok, port-block, per-term bucket/haskey/
    candidate bits) and every other shard contributes the sentinel —
    the same few-collective-rounds discipline as parallel.sharded's
    solver election. Verdicts come out replicated, bit-identical to the
    single-device scan by construction (same adds, same compares, exact
    integer broadcasts)."""
    from ..parallel.mesh import AXIS_NODES

    shard = jax.lax.axis_index(AXIS_NODES)
    base = (shard * n_local).astype(jnp.int32)
    U = req.shape[0]
    TT = t_anti.shape[0]
    t_rows = jnp.arange(TT, dtype=jnp.int32)
    have_spread = bool(spread)  # {} when the batch has no hard spread
    one = jnp.float32(1.0)

    def step(carry, p):
        free, count, ca, cb, cs, md, mh = carry
        u = sig[p]
        n = assign[p]
        pv = pod_valid[p]
        is_m1 = n < 0
        local = (n >= base) & (n < base + n_local)
        lidx = jnp.where(local, n - base, 0)
        r_q = req[u]
        # owner-shard facts, packed into ONE int32 pmax: [cap_ok,
        # block_p, hk[TT], buck[TT], cand[TT]] — non-owners contribute
        # the identity of max (0 / 0 / 0 / -1 / 0)
        cap_ok_l = (
            local
            & ((~req_any[u]) | jnp.all(r_q <= free[lidx]))
            & (count[lidx] + 1 <= allowed[lidx])
        )
        block_p_l = local & jnp.any(pconf[u] & (cs[:, lidx] > 0))
        hk_l = jnp.where(local, haskey_nl[:, lidx], False)
        buck_l = jnp.where(local, bucket_nl[:, lidx].astype(jnp.int32), -1)
        if have_spread:
            cand_l = jnp.where(local, spread["cand_t"][:, lidx], False)
            packed = jnp.concatenate([
                jnp.stack([cap_ok_l.astype(jnp.int32), block_p_l.astype(jnp.int32)]),
                hk_l.astype(jnp.int32), buck_l, cand_l.astype(jnp.int32),
            ])
        else:
            packed = jnp.concatenate([
                jnp.stack([cap_ok_l.astype(jnp.int32), block_p_l.astype(jnp.int32)]),
                hk_l.astype(jnp.int32), buck_l,
            ])
        packed = jax.lax.pmax(packed, AXIS_NODES)
        cap_ok = packed[0] > 0
        block_p = packed[1] > 0
        hk = packed[2 : 2 + TT] > 0
        buck = packed[2 + TT : 2 + 2 * TT]
        buck_c = jnp.maximum(buck, 0)  # -1 only where hk is False
        own_u = (t_owner == u) & t_anti
        # required anti-affinity, both directions — replicated tables
        # indexed by the broadcast bucket (identical math to `arbitrate`)
        block_a = jnp.any(own_u & hk & (ca[t_rows, buck_c] > 0))
        block_b = jnp.any(m_bb[:, u] & hk & (cb[t_rows, buck_c] > 0))
        if have_spread:
            cand_b = packed[2 + 2 * TT :] > 0
            hard = spread["hard"]
            owner = spread["owner"]
            own_h = hard & (owner == u)
            cnt = spread["merged_cnt0"] + md  # [TT, V]
            min_t = jnp.min(
                jnp.where(spread["merged_present"], cnt, jnp.int32(_BIG)),
                axis=1,
            )
            at_b = jnp.where(
                spread["merged_present"][t_rows, buck_c],
                cnt[t_rows, buck_c],
                0,
            )
            skew_ok_t = hk & (at_b + spread["self_m"] - min_t <= spread["skew"])
            sp_ok = (
                jnp.all(jnp.where(own_h, skew_ok_t, True))
                | ~spread["any_pair_u"][u]
            )
            couldfit = jnp.any(own_h & (mh > 0))
        else:
            sp_ok = jnp.bool_(True)
            couldfit = jnp.bool_(False)
        ok = cap_ok & ~block_a & ~block_b & ~block_p & sp_ok
        commit = pv & ~is_m1 & ok
        verdict = jnp.where(
            ~pv,
            V_NOFIT,
            jnp.where(
                is_m1,
                jnp.where(couldfit, V_DEFER, V_NOFIT),
                jnp.where(ok, V_PLACE, V_DEFER),
            ),
        ).astype(jnp.int32)
        # shard-local folds: owner only (sentinel n_local/U — dropped)
        mine = commit & local
        tgt = jnp.where(mine, lidx, n_local)
        free = free.at[tgt].add(-(r_q * mine), mode="drop")
        count = count.at[tgt].add(mine.astype(count.dtype), mode="drop")
        cs = cs.at[jnp.where(mine, u, U), jnp.where(mine, lidx, 0)].add(
            one * mine, mode="drop"
        )
        # replicated folds: pure functions of the broadcast commit data
        hkc = hk & commit
        ca = ca.at[t_rows, jnp.where(m_bb[:, u] & hkc, buck_c, V)].add(
            one, mode="drop"
        )
        cb = cb.at[t_rows, jnp.where(own_u & hkc, buck_c, V)].add(
            one, mode="drop"
        )
        if have_spread:
            contrib = jnp.where(hard & commit & cand_b, spread["gm"][:, u], 0)
            md = md.at[t_rows, jnp.where(contrib > 0, buck_c, V)].add(
                contrib, mode="drop"
            )
            mh = mh + jnp.where(
                commit, spread["m_batch_hard"][:, u], False
            ).astype(mh.dtype)
        return (free, count, ca, cb, cs, md, mh), verdict

    carry0 = (
        free0,
        count0,
        jnp.zeros((TT, V), jnp.float32),
        jnp.zeros((TT, V), jnp.float32),
        jnp.zeros((U, n_local), jnp.float32),
        jnp.zeros((TT, V), jnp.int32),
        jnp.zeros((TT,), jnp.int32),
    )
    _, verdicts = jax.lax.scan(step, carry0, order)
    return verdicts


# ktpu: admitted(KIND_ARBITER) memoized per mesh; the driver admits every
# dispatch as a SolveSpec(kind=KIND_ARBITER, shards=...) and warmup realizes
# the same memoized instance, so programs built here are never unplanned
def make_sharded_arbiter(mesh):
    """Build the mesh-bound verdict pass: full signature parity with
    `arbitrate` so the driver can route covered sharded batches through it
    unchanged. The prep (in-batch tensors + spread metadata) runs under
    GSPMD with the node-major arrays pinned to the mesh's "nodes" axis —
    the same annotate-and-let-XLA-place recipe as the sharded solve's
    mask/score stage — and the sequential scan runs under shard_map with
    one packed broadcast per pod."""
    from functools import partial as _partial

    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel.mesh import AXIS_NODES, shard_map

    n_shards = mesh.shape[AXIS_NODES]

    def _c(x, *spec):
        return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P(*spec)))

    @_partial(jax.jit, static_argnames=("term_kinds", "n_buckets"))
    def arbitrate_sharded(
        na, pa, ea, ta, ids, assign, pb,
        carry=None, term_kinds=None, n_buckets=None,
    ):
        from ..ops.pipeline import _inbatch_tensors, apply_carry
        from ..ops.solver import pop_order

        na = {k: _c(v, AXIS_NODES) for k, v in na.items()}
        if carry is not None:
            carry = tuple(_c(x, AXIS_NODES) for x in carry)
        na = apply_carry(na, carry)
        if "counts" in ea:
            ea = {**ea, "counts": _c(ea["counts"], AXIS_NODES)}
        sig = pb["sig"]
        pod_valid = pb["valid"]
        B = sig.shape[0]
        N = na["valid"].shape[0]
        V = n_buckets or N
        assert N % n_shards == 0, (
            f"node capacity {N} not divisible by {n_shards} shards"
        )
        n_local = N // n_shards
        order = pop_order(
            pb["priority"], jnp.arange(B, dtype=jnp.int32), pod_valid
        )
        free0 = na["alloc"] - na["requested"]
        count0 = na["pod_count"].astype(free0.dtype)
        allowed = na["allowed_pods"].astype(free0.dtype)
        inb = _inbatch_tensors(na, pa, ta, ids, n_buckets)
        t_anti = inb["anti"]
        m_bb = inb["m_bb"] & t_anti[:, None]
        bucket_n = _c(inb["bucket_n"], None, AXIS_NODES)
        haskey_n = _c(inb["haskey_n"], None, AXIS_NODES)
        have_spread = term_kinds is None or "spread_hard" in term_kinds
        spread = {}
        spread_specs = {}
        if have_spread:
            spread = _spread_tables(na, pa, ea, ta, bucket_n, haskey_n, V)
            spread = {
                k: (_c(v, None, AXIS_NODES) if k == "cand_t" else _c(v))
                for k, v in spread.items()
            }
            spread_specs = {
                k: (P(None, AXIS_NODES) if k == "cand_t" else P())
                for k in spread
            }
        body = shard_map(
            _partial(_arbiter_body_sharded, n_local=n_local, V=V),
            mesh=mesh,
            in_specs=(
                P(AXIS_NODES),        # free0
                P(AXIS_NODES),        # count0
                P(AXIS_NODES),        # allowed
                P(), P(), P(), P(),   # assign, sig, pod_valid, order
                P(), P(),             # req, req_any
                P(), P(), P(),        # t_anti, t_owner, m_bb
                P(None, AXIS_NODES),  # bucket_n
                P(None, AXIS_NODES),  # haskey_n
                P(),                  # pconf
                spread_specs,         # spread tables (or None)
            ),
            out_specs=P(),            # verdicts (replicated)
        )
        verdicts = body(
            free0, count0, allowed, assign, sig, pod_valid, order,
            pa["req"], pa["req_any"], t_anti,
            inb["owner"], m_bb, bucket_n, haskey_n, inb["port_conflict"],
            spread,
        )
        out = jnp.full((B,), V_NOFIT, jnp.int32)
        return out.at[order].set(verdicts)

    return arbitrate_sharded


# ---------------------------------------------------------------------------
# host reference walk (the bit-identity oracle; tests pin arbitrate to it)
# ---------------------------------------------------------------------------

def host_arbitrate(
    pods,
    assign_rows,
    node_name_of_row,
    snapshot,
    order: Optional[List[int]] = None,
) -> List[int]:
    """The sequential host-recheck walk the device arbiter must reproduce
    bit-for-bit: pods in pop order (priority desc, batch position asc),
    each placed pick re-validated by the FULL oracle predicate chain
    against a scratch snapshot that assumes every earlier V_PLACE pod;
    failures defer, -1s defer only when an earlier commit matched one of
    the pod's hard spread constraints (the could-fit rule). Returns the
    verdict list indexed by batch position.

    This is the executable spec of the commit plane — intentionally the
    slow, obviously-correct oracle formulation (it re-derives predicate
    metadata per pod against the live scratch state).
    """
    from ..api.selectors import match_label_selector
    from ..oracle.nodeinfo import Snapshot
    from ..oracle.predicates import (
        compute_predicate_metadata,
        get_hard_spread_constraints,
        pod_fits_on_node,
    )

    if order is None:
        order = sorted(
            range(len(pods)), key=lambda i: (-pods[i].get_priority(), i)
        )
    snap = Snapshot(
        [ni.node for ni in snapshot.node_infos.values()],
        [p for ni in snapshot.node_infos.values() for p in ni.pods],
    )
    verdicts = [V_NOFIT] * len(pods)
    commits: List = []
    for i in order:
        pod = pods[i]
        row = int(assign_rows[i])
        if row < 0:
            hard = get_hard_spread_constraints(pod)
            couldfit = any(
                c.namespace == pod.namespace
                and match_label_selector(con.label_selector, c.labels)
                for con in hard
                for c in commits
            )
            verdicts[i] = V_DEFER if couldfit else V_NOFIT
            continue
        node_name = node_name_of_row(row)
        ni = snap.get(node_name) if node_name is not None else None
        if ni is None:
            verdicts[i] = V_DEFER
            continue
        meta = compute_predicate_metadata(pod, snap)
        ok, _ = pod_fits_on_node(pod, ni, meta=meta, snapshot=snap)
        if ok:
            verdicts[i] = V_PLACE
            bound = pod.with_node(node_name)
            ni.add_pod(bound)
            commits.append(bound)
        else:
            verdicts[i] = V_DEFER
    return verdicts
