"""Device commit arbiter: the sequential-equivalent verdict pass.

The solve (ops/solver.py) picks nodes against batch-START state; the host
commit loop then re-validates each pick against the commits made EARLIER
in the same batch (scheduler/driver.py LIGHT/FULL rechecks +
_BatchConflictIndex) — a per-pod Python walk that dominates commit wall on
term-heavy batches. This module moves that walk onto the device: one
jitted scan over the solve's assignment rows, in exactly the queue's pop
order, emitting a per-pod VERDICT:

  V_PLACE  — the device pick survives every earlier in-batch commit:
             capacity, pod count, required anti-affinity (both
             directions), host ports, and DoNotSchedule topology spread.
  V_DEFER  — an earlier commit invalidated the pick (or a -1 became
             potentially feasible because a commit raised a hard-spread
             domain minimum): the pod retries NEXT batch, where a fresh
             solve sees the committed state in its mask. Defer-to-next-
             batch replaces the legacy in-batch oracle re-place — the
             placement arrives one cycle later but through the exact
             device mask instead of an O(cluster) host scan.
  V_NOFIT  — the solve's -1 stands (the feasible set only shrinks within
             a batch for everything the arbiter tracks).

Bit-exactness contract: the verdicts equal what a host walk would decide
re-checking each pod, in pop order, against a snapshot that assumes every
earlier V_PLACE pod (tests/test_commit_plane.py pins this against
`host_arbitrate`, the pure-oracle reference walk below). The state the
arbiter carries mirrors the solver's in-batch tracking (ca/cb/cs) plus a
hard-spread delta table replaying exactly spread_filter's merged
per-(term, topology-value) counts.

Coverage: the arbiter handles batches whose PRESENT term kinds are all in
ARBITER_COVERED_KINDS. Required pod AFFINITY (aff_req) is excluded — an
in-batch commit can make an affinity pod's -1 feasible (the anchor case,
predicates.go:1269) in ways that need the host oracle's re-placement, and
its FULL recheck can also move a placement rather than just veto it.
Score-only kinds (soft spread, preferred affinity, selector spread) never
invalidate a commit and are covered by construction.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..state.terms import SPREAD_HARD

Arrays = Dict[str, jnp.ndarray]

V_PLACE = 0
V_DEFER = 1
V_NOFIT = 2

_BIG = 2**30

#: term kinds whose intra-batch interactions the device arbiter resolves
#: exactly; a batch presenting any OTHER kind takes the legacy host loop.
#: Score-only kinds (spread_soft, pref, sel_spread, et_score) shift scores,
#: never validity — batch-stale scores are the accepted batching contract.
#: et_anti (EXISTING pods' anti terms) is static within a batch: the
#: batch-start mask covers it, and commits' own anti terms are tracked.
ARBITER_COVERED_KINDS = frozenset({
    "anti_req", "spread_hard", "spread_soft", "pref", "sel_spread",
    "et_anti", "et_score",
})


def kinds_covered(present_kinds) -> bool:
    """True when every term kind PRESENT in a batch is arbiter-covered."""
    return frozenset(present_kinds) <= ARBITER_COVERED_KINDS


@partial(jax.jit, static_argnames=("term_kinds", "n_buckets"))
def arbitrate(
    na: Arrays,   # NodeBank arrays (same dict the solve consumed)
    pa: Arrays,   # PodBatch arrays (unique-spec rows)
    ea: Arrays,   # SigBank arrays (existing-pod signatures, spread counts)
    ta: Arrays,   # batch TermBank arrays
    ids: Arrays,  # interned constants (filters.make_ids)
    assign: jnp.ndarray,  # [B] the solve's node row per pod (-1 = no fit)
    pb: Arrays,   # per-pod axis: sig/valid/priority [B]
    carry: Optional[Tuple] = None,  # same residual carry the solve ran on
    term_kinds: Optional[frozenset] = None,
    n_buckets: Optional[int] = None,
) -> jnp.ndarray:
    """Verdict [B] (V_PLACE / V_DEFER / V_NOFIT) per batch position.

    Sequential by construction: a lax.scan walks the pods in pop order
    (the same pop_order the solver used), each step checking the pod's
    assigned node against the state left by every earlier V_PLACE step,
    then folding its own commit in. The per-step work is a handful of
    [TT]/[N]-sized gathers — B serial steps of tiny kernels, milliseconds
    where the host walk it replaces was seconds. `carry` must be the SAME
    residual tuple the solve dispatched against (speculative pipelining),
    so the arbiter replays from the state the assignment was computed on.
    """
    from ..ops import filters as F
    from ..ops.pipeline import _inbatch_tensors, apply_carry
    from ..ops.solver import pop_order
    from ..ops.topology import (
        _bucket_of,
        _merge_same_key,
        _scatter_and,
        _seg_sum,
        _sig_cnt_node,
        match_terms,
    )

    na = apply_carry(na, carry)
    sig = pb["sig"]
    pod_valid = pb["valid"]
    B = sig.shape[0]
    U = pa["valid"].shape[0]
    N = na["valid"].shape[0]
    V = n_buckets or N
    order = pop_order(pb["priority"], jnp.arange(B, dtype=jnp.int32), pod_valid)

    free0 = na["alloc"] - na["requested"]
    count0 = na["pod_count"].astype(free0.dtype)
    allowed = na["allowed_pods"].astype(free0.dtype)
    req = pa["req"]
    req_any = pa["req_any"]

    # anti-affinity + host-port tracking tensors — the SAME builder the
    # solver's in-batch tracking uses, so the two can never disagree
    inb = _inbatch_tensors(na, pa, ta, ids, n_buckets)
    t_anti = inb["anti"]
    t_owner = inb["owner"]
    m_bb = inb["m_bb"] & t_anti[:, None]  # [TT, U]
    bucket_n = inb["bucket_n"]  # [TT, N]
    haskey_n = inb["haskey_n"]
    pconf = inb["port_conflict"]  # [U, U]
    TT = t_anti.shape[0]
    t_rows = jnp.arange(TT, dtype=jnp.int32)

    have_spread = term_kinds is None or "spread_hard" in term_kinds
    if have_spread:
        # pre-batch merged per-(term, topology-value) match counts —
        # EXACTLY ops/topology.spread_filter's metadata (same helpers), so
        # check-time arithmetic below reproduces its skew predicate with
        # the counts advanced by this batch's commits
        hard = ta["valid"] & (ta["kind"] == SPREAD_HARD)
        owner = ta["owner"].astype(jnp.int32)
        sel = F.pod_match_node_selector(na, pa)  # [U, N]
        all_keys = _scatter_and(haskey_n, ta["owner"], hard, U)
        cand = sel & all_keys & na["valid"][None, :]
        m_sig = (
            match_terms(ta, ea["label_vals"], ea["ns_id"])
            & ea["valid"][None, :]
            & hard[:, None]
        )
        cnt_node = _sig_cnt_node(m_sig, ea["counts"])  # [TT, N]
        cand_t = cand[ta["owner"]]  # [TT, N]
        pair_cnt = _seg_sum(jnp.where(cand_t, cnt_node, 0), bucket_n, V)
        pair_present = (
            _seg_sum((cand_t & haskey_n).astype(jnp.int32), bucket_n, V) > 0
        )
        merged_cnt0 = _merge_same_key(ta, hard, pair_cnt).astype(jnp.int32)
        merged_present = (
            _merge_same_key(ta, hard, pair_present.astype(jnp.int32)) > 0
        )
        any_pair_t = jnp.any(merged_present, axis=1)
        any_pair_u = (
            jnp.zeros(U + 1, bool)
            .at[jnp.where(hard, ta["owner"], U)]
            .max(any_pair_t & hard)[:U]
        )
        # batch-spec match per hard term (for commit deltas and the -1
        # could-fit rule): term ns_ids were compiled to [owner namespace],
        # so this is exactly "same namespace AND selector matches"
        m_batch_hard = (
            match_terms(ta, pa["label_vals"], pa["ns_id"]) & hard[:, None]
        )  # [TT, U]
        # terms sharing (owner, topology key) share one merged count table
        # (metadata.go tpPairToMatchNum): group-sum the per-term matches so
        # one scatter per commit updates the merged table directly (group
        # members share bucket_n rows — same topo_slot)
        same = (
            hard[:, None]
            & hard[None, :]
            & (owner[:, None] == owner[None, :])
            & (ta["topo_slot"][:, None] == ta["topo_slot"][None, :])
        )
        gm = jnp.matmul(
            same.astype(jnp.float32),
            m_batch_hard.astype(jnp.float32),
            precision=jax.lax.Precision.HIGHEST,
        ).astype(jnp.int32)  # [TT, U]
        self_m = ta["self_match"].astype(jnp.int32)
        skew = ta["weight"].astype(jnp.int32)

    one = jnp.float32(1.0)

    def step(carry, p):
        free, count, ca, cb, cs, md, mh = carry
        u = sig[p]
        n = assign[p]
        pv = pod_valid[p]
        is_m1 = n < 0
        ncl = jnp.maximum(n, 0)
        r_q = req[u]
        # PodFitsResources against the state earlier V_PLACE commits left
        # (defense in depth: the solver's carry already sequentialized
        # resources, and defers only RELEASE capacity, so this cannot fire
        # on a healthy replay — but the host walk checks it, so the
        # verdict contract does too)
        cap_ok = ((~req_any[u]) | jnp.all(r_q <= free[ncl])) & (
            count[ncl] + 1 <= allowed[ncl]
        )
        buck = bucket_n[:, ncl]  # [TT]
        hk = haskey_n[:, ncl]
        own_u = (t_owner == u) & t_anti
        # required anti-affinity, both directions (predicates.go:1284
        # within the batch): my terms vs matching earlier commits (ca),
        # earlier commits' terms vs me (cb) — same tables as the solver
        block_a = jnp.any(own_u & hk & (ca[t_rows, buck] > 0))
        block_b = jnp.any(m_bb[:, u] & hk & (cb[t_rows, buck] > 0))
        block_p = jnp.any(pconf[u] & (cs[:, ncl] > 0))
        if have_spread:
            own_h = hard & (owner == u)
            cnt = merged_cnt0 + md  # [TT, V]
            min_t = jnp.min(
                jnp.where(merged_present, cnt, jnp.int32(_BIG)), axis=1
            )  # [TT]
            at_b = jnp.where(
                merged_present[t_rows, buck], cnt[t_rows, buck], 0
            )
            skew_ok_t = hk & (at_b + self_m - min_t <= skew)
            sp_ok = jnp.all(jnp.where(own_h, skew_ok_t, True)) | ~any_pair_u[u]
            # -1 could-fit (driver._minus_one_could_fit, spread half): an
            # earlier commit matching one of my hard constraints raised the
            # domain minimum — the feasible set may have WIDENED
            couldfit = jnp.any(own_h & (mh > 0))
        else:
            sp_ok = jnp.bool_(True)
            couldfit = jnp.bool_(False)
        ok = cap_ok & ~block_a & ~block_b & ~block_p & sp_ok
        commit = pv & ~is_m1 & ok
        verdict = jnp.where(
            ~pv,
            V_NOFIT,
            jnp.where(
                is_m1,
                jnp.where(couldfit, V_DEFER, V_NOFIT),
                jnp.where(ok, V_PLACE, V_DEFER),
            ),
        ).astype(jnp.int32)
        # fold this commit into the tracked state (scatter index V/N/U on
        # non-commits — dropped)
        tgt = jnp.where(commit, ncl, N)
        free = free.at[tgt].add(-(r_q * commit), mode="drop")
        count = count.at[tgt].add(commit.astype(count.dtype), mode="drop")
        hkc = hk & commit
        ca = ca.at[t_rows, jnp.where(m_bb[:, u] & hkc, buck, V)].add(
            one, mode="drop"
        )
        cb = cb.at[t_rows, jnp.where(own_u & hkc, buck, V)].add(
            one, mode="drop"
        )
        cs = cs.at[jnp.where(commit, u, U), ncl].add(one, mode="drop")
        if have_spread:
            contrib = jnp.where(hard & commit & cand_t[:, ncl], gm[:, u], 0)
            md = md.at[t_rows, jnp.where(contrib > 0, buck, V)].add(
                contrib, mode="drop"
            )
            mh = mh + jnp.where(commit, m_batch_hard[:, u], False).astype(
                mh.dtype
            )
        return (free, count, ca, cb, cs, md, mh), verdict

    carry0 = (
        free0,
        count0,
        jnp.zeros((TT, V), jnp.float32),
        jnp.zeros((TT, V), jnp.float32),
        jnp.zeros((U, N), jnp.float32),
        jnp.zeros((TT, V), jnp.int32),
        jnp.zeros((TT,), jnp.int32),
    )
    _, verdicts = jax.lax.scan(step, carry0, order)
    out = jnp.full((B,), V_NOFIT, jnp.int32)
    return out.at[order].set(verdicts)


# ---------------------------------------------------------------------------
# host reference walk (the bit-identity oracle; tests pin arbitrate to it)
# ---------------------------------------------------------------------------

def host_arbitrate(
    pods,
    assign_rows,
    node_name_of_row,
    snapshot,
    order: Optional[List[int]] = None,
) -> List[int]:
    """The sequential host-recheck walk the device arbiter must reproduce
    bit-for-bit: pods in pop order (priority desc, batch position asc),
    each placed pick re-validated by the FULL oracle predicate chain
    against a scratch snapshot that assumes every earlier V_PLACE pod;
    failures defer, -1s defer only when an earlier commit matched one of
    the pod's hard spread constraints (the could-fit rule). Returns the
    verdict list indexed by batch position.

    This is the executable spec of the commit plane — intentionally the
    slow, obviously-correct oracle formulation (it re-derives predicate
    metadata per pod against the live scratch state).
    """
    from ..api.selectors import match_label_selector
    from ..oracle.nodeinfo import Snapshot
    from ..oracle.predicates import (
        compute_predicate_metadata,
        get_hard_spread_constraints,
        pod_fits_on_node,
    )

    if order is None:
        order = sorted(
            range(len(pods)), key=lambda i: (-pods[i].get_priority(), i)
        )
    snap = Snapshot(
        [ni.node for ni in snapshot.node_infos.values()],
        [p for ni in snapshot.node_infos.values() for p in ni.pods],
    )
    verdicts = [V_NOFIT] * len(pods)
    commits: List = []
    for i in order:
        pod = pods[i]
        row = int(assign_rows[i])
        if row < 0:
            hard = get_hard_spread_constraints(pod)
            couldfit = any(
                c.namespace == pod.namespace
                and match_label_selector(con.label_selector, c.labels)
                for con in hard
                for c in commits
            )
            verdicts[i] = V_DEFER if couldfit else V_NOFIT
            continue
        node_name = node_name_of_row(row)
        ni = snap.get(node_name) if node_name is not None else None
        if ni is None:
            verdicts[i] = V_DEFER
            continue
        meta = compute_predicate_metadata(pod, snap)
        ok, _ = pod_fits_on_node(pod, ni, meta=meta, snapshot=snap)
        if ok:
            verdicts[i] = V_PLACE
            bound = pod.with_node(node_name)
            ni.add_pod(bound)
            commits.append(bound)
        else:
            verdicts[i] = V_DEFER
    return verdicts
