"""Commit pipelining: double-buffer the bulk apply against the next solve.

The driver's cycle used to be strictly serial on the host: solve-fetch →
commit → (dispatch next) → solve-fetch → ... The solve side already
pipelines (speculative dispatch + copy_to_host_async); this module gives
the COMMIT side the same treatment: batch N's columnar apply + lean-bind
submission runs on a single worker thread while the main thread fetches
batch N+1's already-dispatched solve result (a device/tunnel wait that
needs no host CPU) and runs its pre-commit phases.

Backpressure is the invariant: at most ONE batch's apply may be in flight
(`submit` drains the previous one first), and the driver drains before
touching anything the apply mutates — the cache/queue/mirror sync, the
speculative-chain validity check (cache.mutation_count equality), and the
end-of-batch preemption pass. The tensor mirror therefore never runs more
than one batch stale, and never stale at the moment a batch begins
committing.

The submitted closure owns its own failure handling (per-pod fail paths,
reject accounting); an escaped exception is remembered and re-raised at
the next drain so a broken apply surfaces in the driver's per-batch error
path instead of dying silently on the worker.
"""

from __future__ import annotations

import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Callable, Dict, Optional

from ..analysis.lockorder import audited_lock, register_thread_role


class CommitPipeline:
    def __init__(self):
        self._pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="commit-apply"
        )
        self._lock = audited_lock("commit-pipeline")
        self._inflight: Optional[Future] = None  # ktpu: guarded-by(self._lock)
        # mutated by BOTH the worker (_run's apply_s) and the caller
        # (submit/drain) — KTPU003 found the worker-side writes unlocked
        self.stats: Dict[str, float] = {  # ktpu: guarded-by(self._lock)
            "submitted": 0,
            "drain_wait_s": 0.0,  # host time actually BLOCKED on an apply
            "apply_s": 0.0,  # worker wall inside submitted closures
        }
        # worker→driver stat handoff: the submitted closure's counter
        # contributions (apply seconds, reject counts) accumulate HERE
        # under the lock and are merged into the scheduler's own stats
        # dict by the DRIVER at drain — KTPU006 found the closure writing
        # Scheduler.stats directly from the worker (a cross-thread
        # read-modify-write the single-writer stats dict never signed
        # up for)
        self._worker_stats: Dict[str, float] = {}  # ktpu: guarded-by(self._lock)

    def submit(self, fn: Callable[[], None]) -> None:
        """Run `fn` on the worker; blocks first if a previous apply is
        still in flight (the ≤1-batch-stale backpressure)."""
        self.drain()
        with self._lock:
            self.stats["submitted"] += 1
            self._inflight = self._pool.submit(self._run, fn)

    # ktpu: thread-entry(commit-apply) every submitted closure (the
    # driver's apply_batch) runs inside this wrapper on the worker
    def _run(self, fn: Callable[[], None]) -> None:
        register_thread_role("commit-apply")
        t0 = time.perf_counter()
        try:
            fn()
        finally:
            with self._lock:
                self.stats["apply_s"] += time.perf_counter() - t0

    def note_stat(self, key: str, val: float) -> None:
        """Worker-side counter contribution (called from the submitted
        closure): accumulated under the lock, merged into the driver's
        stats at the next take_worker_stats()."""
        with self._lock:
            self._worker_stats[key] = self._worker_stats.get(key, 0) + val

    def take_worker_stats(self) -> Dict[str, float]:
        """Drain-and-clear the worker's pending stat contributions —
        DRIVER-side half of the handoff (call after drain())."""
        with self._lock:
            out, self._worker_stats = self._worker_stats, {}
            return out

    def drain(self) -> None:
        """Wait for the in-flight apply (no-op when idle). Re-raises the
        closure's escaped exception, if any, on the caller's thread."""
        with self._lock:
            f, self._inflight = self._inflight, None
        if f is None:
            return
        t0 = time.perf_counter()
        try:
            f.result()
        finally:
            with self._lock:
                self.stats["drain_wait_s"] += time.perf_counter() - t0

    def census(self) -> Dict[str, object]:
        """One lock-disciplined snapshot for the health plane
        (obs/introspect): whether an apply is in flight plus the
        submitted/wait/apply counters. Never blocks on the worker."""
        with self._lock:
            return {
                "in_flight": self._inflight is not None,
                "stats": dict(self.stats),
            }

    def close(self) -> None:
        try:
            self.drain()
        finally:
            self._pool.shutdown(wait=True)
