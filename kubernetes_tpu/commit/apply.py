"""Columnar bulk apply: one state update per batch instead of per-pod.

The legacy commit loop paid, per pod: a CycleState, an RLock round-trip
into the cache, a nomination-index lock, and a closure submission. For a
batch the arbiter fully resolved, all of that collapses to column passes:
clone every placed pod with its node, ONE bulk cache assume (single lock),
ONE bulk nomination clear, and chunked lean-bind submissions. The tensor
mirror needs no special treatment — assume_pods pushes per-pod deltas the
mirror's next sync() applies as vectorized scatters (apply_adds_bulk).

Gang groups get a single rollback record: every prepared member is held in
one GangRollbackRecord, and rolling the group back is one bulk cache
forget plus the per-member unreserve/volume bookkeeping — one object to
reason about instead of per-member unwind calls scattered through the
driver.
"""

from __future__ import annotations

import time
from typing import Callable, List, Optional, Tuple


class ApplyResult:
    """Outcome of one columnar apply."""

    __slots__ = ("placed", "rejected", "seconds")

    def __init__(self, placed, rejected, seconds):
        self.placed = placed  # [(info, assumed_pod, node_name)]
        self.rejected = rejected  # [(info, node_name)] already-assumed keys
        self.seconds = seconds


class ColumnarApply:
    """Bulk assume + nomination clears for a fully-arbitrated batch."""

    def __init__(self, cache, queue):
        self.cache = cache
        self.queue = queue

    def apply(self, batch: List[Tuple], folded: bool = False) -> ApplyResult:
        """`batch` is [(PodInfo, node_name)] in commit order. Returns the
        placed triples (for bind submission) and the rejected pairs (pod
        key already in the cache — the caller fails those individually,
        exactly assume_pod's ValueError contract). `folded` tags the
        assume deltas as already device-folded (resident-state plane);
        the caller handles rejected pairs' fold correction."""
        t0 = time.perf_counter()
        assumed = [info.pod.with_node(node) for info, node in batch]
        rejected_idx = set(self.cache.assume_pods(assumed, folded=folded))
        placed = []
        rejected = []
        for j, (info, node) in enumerate(batch):
            if j in rejected_idx:
                rejected.append((info, node))
            else:
                placed.append((info, assumed[j], node))
        if placed and self.queue.has_nominations():
            # DeleteNominatedPodIfExists at assume time (scheduler.go:529),
            # batched — committed pods stop reserving their nominated nodes
            self.queue.clear_nominations([p[0].pod.key() for p in placed])
        return ApplyResult(placed, rejected, time.perf_counter() - t0)


class GangRollbackRecord:
    """One rollback record per gang group: the staged members and the one
    call that unwinds them all. `forget_pods` undoes every member's cache
    assume under a single lock; unreserve/volume-forget stay per member
    (plugin contracts are per pod)."""

    __slots__ = ("group", "members")

    def __init__(self, group: str):
        self.group = group
        self.members: List[Tuple] = []  # (info, assumed, node_name, state)

    def stage(self, info, assumed, node_name, state) -> None:
        self.members.append((info, assumed, node_name, state))

    def __len__(self) -> int:
        return len(self.members)

    def rollback(
        self,
        cache,
        framework,
        volume_binder,
        fail: Callable,
        cycle: int,
        msg: str,
        on_member: Optional[Callable] = None,
    ) -> int:
        """Unwind every staged member: bulk cache forget, then per-member
        volume-forget + unreserve + fail. `on_member(info)` runs per member
        for caller-side bookkeeping (conflict-index tombstones, counters).
        Returns the number of members rolled back."""
        members, self.members = self.members, []
        cache.forget_pods([m[1] for m in members])
        for info, assumed, node_name, state in members:
            if volume_binder is not None:
                volume_binder.forget_pod_volumes(info.pod)
            framework.run_unreserve(state, info.pod, node_name)
            fail(info, cycle, msg)
            if on_member is not None:
                on_member(info)
        return len(members)
