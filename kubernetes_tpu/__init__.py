"""kubernetes_tpu — a TPU-native batch scheduling framework.

A from-scratch re-design of the Kubernetes scheduler (reference: upstream
v1.16-era kube-scheduler, see SURVEY.md) for TPU hardware: cluster state is
mirrored into device-resident tensors, incrementally patched from a
list+watch event stream, and scheduling decisions are computed as vectorized
pods x nodes boolean-mask / score matrices in JAX/XLA, finished by a batched
assignment solve.

Layout (mirrors SURVEY.md section 7 build plan):
  api/        typed Pod/Node objects, resource.Quantity, label selectors
  state/      interner, cluster cache (assumed-pod state machine), queue,
              tensorization layer (generation-patched device arrays)
  ops/        device kernels: filters (predicates), scores (priorities),
              topology (spread + inter-pod affinity), solver (assignment)
  parallel/   device-mesh sharding of the solve (shard_map over node axis)
  framework/  plugin extension points (QueueSort..PostBind, CycleState)
  scheduler/  driver loop, event handlers, factory/config, preemption
  apiserver/  in-process fake apiserver with list+watch, informer client
  extender/   HTTP SchedulerExtender server (extender/v1 wire format)
  metrics/    Prometheus-text metrics registry + scheduler series
  utils/      trace, backoff, leader election, feature gates
  models/     workload/cluster generators (scheduler_perf & kubemark style)
  oracle/     scalar Python reference semantics used for parity testing
"""

__version__ = "0.1.0"
