"""Columnar scheduler cache: the hot state of `SchedulerCache` as
contiguous node-major numpy columns, patched by vectorized scatter-adds.

The last host-Python wall of the covered commit path (PERF rounds 3-4,
ROADMAP item 2) was the per-pod OBJECT work inside bulk assume/forget:
every committed pod walked `NodeInfo._account` — Quantity-derived dict
arithmetic, affinity list upkeep, port tuples — once per pod, under the
cache lock, on the commit worker. The six device-residency planes
amortized everything around it; this module removes it:

* `CacheColumns` — the columns every hot read/write touches (per-node
  `requested` in resource-slot space, non-zero scoring requests, pod
  count, affinity-carrier count, used-host-port counts, zone/topology
  pod counts) live in contiguous arrays indexed by a cache-owned node
  row. Bulk assume/forget becomes ONE gather of memoized per-spec delta
  rows + a handful of `np.add.at` scatters — O(batch) vectorized, zero
  per-pod `NodeInfo`/Quantity updates.
* ONE DELTA SOURCE: the per-spec delta rows (`spec_req`/`spec_nz`,
  interned content-keyed from the same memoized `_req_slot_pairs` /
  `pod_non_zero_request` values) feed BOTH the host columns and the
  fold plane's device control arrays (`commit/fold.plan_fold` gathers
  them via `delta_mats`), so host and device banks advance from
  literally the same integers (INVARIANTS.md: one-delta-source rule).
* LAZY VIEW: the per-name `NodeInfo` object cache is demoted to a
  generation-tagged view for plugins, extenders, the volume binder,
  preemption, and API reads. Bulk ops journal `(sign, pod)` per node
  row instead of mutating objects; the first object read after a
  columnar write replays the row's journal (`materialize`), bumping the
  view's generation to the row's column generation. The covered commit
  path never materializes (pinned by perf_smoke's `columnar` mode).
* `AssumedDeadlines` — the assumed-pod TTL clock as a column, so
  `cleanup_expired` is one vectorized compare per cycle instead of a
  per-pod walk under the cache lock.
* `LazyNodeInfos` — a dict subclass standing in for
  `Snapshot.node_infos`: keyed/iterated access stays raw (keys are
  never stale); value access resolves staleness first.

Thread discipline: the columns share the cache's RLock. Every guarded
attribute is declared `# ktpu: guarded-by(self._lock)` and accessed
only from `*_locked` methods (caller — `SchedulerCache` — holds the
lock) or inside an explicit `with self._lock:` block; ktpu-lint KTPU003
machine-checks this (fixture pair: tests/fixtures/lint/
ktpu003_columns.py). `KTPU_COLUMNAR_CACHE=0` is the operational kill
switch (the driver simply never attaches columns; every legacy path is
intact).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from ..api.types import RESOURCE_PODS
from ..oracle.nodeinfo import (
    DEFAULT_BIND_ALL_HOST_IP,
    NodeInfo,
    pod_has_affinity_constraints,
    pod_non_zero_request,
)
from .tensors import KeySlotOverflow, _bucket, _node_bucket, _req_slot_pairs, _zone_key

#: per-row journal length that forces a materialization right after the
#: bulk call (SchedulerCache drains `_overgrown`): the lazy view's
#: deferral must stay an optimization, never an unbounded memory leak on
#: a node nothing ever reads
JOURNAL_BOUND = 2048


class LazyNodeInfos(dict):
    """`Snapshot.node_infos` stand-in: value reads resolve lazy-view
    staleness first; key-only operations (`in`, `len`, iteration) stay
    raw dict speed — node NAMES are never stale, only the NodeInfo
    objects behind them. `_resolve(name_or_None)` is the cache's
    materializer (None = every stale row)."""

    _resolve: Optional[Callable[[Optional[str]], None]] = None

    def __getitem__(self, name):
        r = self._resolve
        if r is not None:
            r(name)
        return dict.__getitem__(self, name)

    def get(self, name, default=None):
        r = self._resolve
        if r is not None:
            r(name)
        return dict.get(self, name, default)

    def pop(self, name, *default):
        # pop hands the OBJECT out (remove_node iterates its pods) — it
        # must be current before it leaves the map
        r = self._resolve
        if r is not None:
            r(name)
        return dict.pop(self, name, *default)

    def values(self):
        r = self._resolve
        if r is not None:
            r(None)
        return dict.values(self)

    def items(self):
        r = self._resolve
        if r is not None:
            r(None)
        return dict.items(self)


class AssumedDeadlines:
    """The assumed-pod TTL clock as a column: one float64 slot per pod
    whose binding finished (`+inf` = no deadline armed). cleanup_expired
    scans `deadline < now` as ONE vectorized compare instead of walking
    every assumed pod per cycle. Shares the cache's lock."""

    def __init__(self, lock, capacity: int = 64):
        self._lock = lock
        cap = _bucket(capacity)
        self.deadline = np.full(cap, np.inf)  # ktpu: guarded-by(self._lock)
        self.key_of = [None] * cap  # ktpu: guarded-by(self._lock)
        self.slot_of: Dict[str, int] = {}  # ktpu: guarded-by(self._lock)
        self._free = list(range(cap - 1, -1, -1))  # ktpu: guarded-by(self._lock)

    def set_bulk_locked(self, keys: Sequence[str], deadline: float) -> None:
        for key in keys:
            slot = self.slot_of.get(key)
            if slot is None:
                if not self._free:
                    self._grow_locked()
                slot = self._free.pop()
                self.slot_of[key] = slot
                self.key_of[slot] = key
            self.deadline[slot] = deadline

    def _grow_locked(self) -> None:
        old = self.deadline.shape[0]
        cap = old * 2
        dl = np.full(cap, np.inf)
        dl[:old] = self.deadline
        self.deadline = dl
        self.key_of.extend([None] * old)
        self._free.extend(range(cap - 1, old - 1, -1))

    def discard_locked(self, key: str) -> None:
        slot = self.slot_of.pop(key, None)
        if slot is not None:
            self.deadline[slot] = np.inf
            self.key_of[slot] = None
            self._free.append(slot)

    def expired_locked(self, now: float) -> List[str]:
        idx = np.nonzero(self.deadline < now)[0]
        return [self.key_of[int(i)] for i in idx]


class CacheColumns:
    """Contiguous hot columns of a `SchedulerCache`, node-major, indexed
    by a cache-owned row (free-list discipline mirroring the tensor
    mirror's). All mutation is vectorized over interned per-spec delta
    rows; the NodeInfo objects behind `Snapshot` become a journal-backed
    lazy view (see module docstring)."""

    def __init__(self, vocab, lock, capacity: int = 1):
        self._lock = lock  # THE SchedulerCache RLock, shared
        self.vocab = vocab
        cap = _node_bucket(capacity)
        self.capacity = cap  # ktpu: guarded-by(self._lock)
        width = vocab.config.resource_slots
        # --- hot columns (node-major) -----------------------------------
        self.requested = np.zeros((cap, width), np.int64)  # ktpu: guarded-by(self._lock)
        self.nonzero_req = np.zeros((cap, 2), np.int64)  # ktpu: guarded-by(self._lock)
        self.pod_count = np.zeros(cap, np.int32)  # ktpu: guarded-by(self._lock)
        self.aff_count = np.zeros(cap, np.int32)  # ktpu: guarded-by(self._lock)
        # used host ports: (proto, ip, port) triples interned to dense
        # port columns; counts per (node, port column)
        self.port_counts = np.zeros((cap, 8), np.int16)  # ktpu: guarded-by(self._lock)
        self._port_col: Dict[Tuple[str, str, int], int] = {}  # ktpu: guarded-by(self._lock)
        # zone/topology occupancy: dense zone id per node row + pods per
        # zone (GetZoneKey identity — the multi-host snapshot's spread
        # column)
        self.zone_dense = np.full(cap, -1, np.int32)  # ktpu: guarded-by(self._lock)
        self.zone_pods = np.zeros(8, np.int64)  # ktpu: guarded-by(self._lock)
        self._zone_ids: Dict[str, int] = {}  # ktpu: guarded-by(self._lock)
        # --- row bookkeeping --------------------------------------------
        self.row_of: Dict[str, int] = {}  # ktpu: guarded-by(self._lock)
        self.name_of_row: List[Optional[str]] = [None] * cap  # ktpu: guarded-by(self._lock)
        self._free_rows = list(range(cap - 1, -1, -1))  # ktpu: guarded-by(self._lock)
        # --- interned per-spec delta rows (the ONE delta source) --------
        self.spec_req = np.zeros((16, width), np.int64)  # ktpu: guarded-by(self._lock)
        self.spec_nz = np.zeros((16, 2), np.int64)  # ktpu: guarded-by(self._lock)
        self.spec_aff = np.zeros(16, bool)  # ktpu: guarded-by(self._lock)
        self.spec_has_ports = np.zeros(16, bool)  # ktpu: guarded-by(self._lock)
        self._spec_ports: List[Tuple[int, ...]] = [()] * 16  # ktpu: guarded-by(self._lock)
        self._slot_of: Dict[tuple, int] = {}  # ktpu: guarded-by(self._lock)
        # --- lazy-view journal + generations ----------------------------
        # per-row list of (sign, pod) not yet applied to the NodeInfo view
        self._pending: List[Optional[List[Tuple[int, object]]]] = [None] * cap  # ktpu: guarded-by(self._lock)
        self._stale_rows: Set[int] = set()  # ktpu: guarded-by(self._lock)
        # rows whose journal outgrew JOURNAL_BOUND: the cache materializes
        # them right after the bulk call — a never-read node's journal
        # must not grow without bound across assume/forget churn
        self._overgrown: Set[int] = set()  # ktpu: guarded-by(self._lock)
        self._journal_since_check = 0  # ktpu: guarded-by(self._lock)
        self.generation = 0  # ktpu: guarded-by(self._lock)
        self.row_gen = np.zeros(cap, np.int64)  # ktpu: guarded-by(self._lock)
        self.stats: Dict[str, int] = {  # ktpu: guarded-by(self._lock)
            "bulk_batches": 0,
            "bulk_pods": 0,
            "scalar_pods": 0,
            "materializations": 0,
            "materialized_pods": 0,
            "spec_rows": 0,
        }
        # fault-plane injection hook (kubernetes_tpu/faults): armed by
        # the driver only when a FaultPlan is configured; None = one
        # attribute read per scatter (the zero-overhead contract)
        self.fault_hook = None

    # -- row management (caller holds the cache lock) ------------------------

    def add_node_locked(self, name: str, labels: Dict[str, str]) -> int:
        if not self._free_rows:
            self._grow_nodes_locked()
        row = self._free_rows.pop()
        self.row_of[name] = row
        self.name_of_row[row] = name
        self.zone_dense[row] = self._zone_dense_locked(labels)
        return row

    def set_zone_locked(self, name: str, labels: Dict[str, str]) -> None:
        """Node update: re-derive the zone column, migrating the row's
        pod occupancy between zone buckets when the labels moved it."""
        row = self.row_of.get(name)
        if row is None:
            return
        new = self._zone_dense_locked(labels)
        old = int(self.zone_dense[row])
        if new == old:
            return
        n = int(self.pod_count[row])
        if old >= 0:
            self.zone_pods[old] -= n
        if new >= 0:
            self.zone_pods[new] += n
        self.zone_dense[row] = new

    def remove_node_locked(self, name: str) -> None:
        row = self.row_of.pop(name, None)
        if row is None:
            return
        zd = int(self.zone_dense[row])
        if zd >= 0:
            self.zone_pods[zd] -= int(self.pod_count[row])
        self.requested[row] = 0
        self.nonzero_req[row] = 0
        self.pod_count[row] = 0
        self.aff_count[row] = 0
        self.port_counts[row] = 0
        self.zone_dense[row] = -1
        # a reused row must not inherit the dead node's generation — the
        # staleness-by-generation contract starts fresh with the row
        self.row_gen[row] = 0
        self.name_of_row[row] = None
        self._pending[row] = None
        self._stale_rows.discard(row)
        self._overgrown.discard(row)
        self._free_rows.append(row)

    def ingest_node_locked(self, row: int, ni: NodeInfo) -> None:
        """One-time adoption of an already-populated NodeInfo (columns
        attached to a non-empty cache): columns take the object's own
        incremental aggregates verbatim — no re-derivation to disagree
        with."""
        v = self.vocab
        for rname, amount in ni.requested().items():
            if rname == RESOURCE_PODS:
                # every delta consumer filters the 'pods' pseudo-resource
                # (_req_slot_pairs, NodeBank.set_node) — the adoption
                # pass must too, or the slot skews forever
                continue
            s = v.slot_of_resource(rname)
            if s >= self.requested.shape[1]:
                self._grow_width_locked(s + 1)
            self.requested[row, s] = amount
        nz_cpu, nz_mem = ni.non_zero_requested()
        self.nonzero_req[row, 0] = nz_cpu
        self.nonzero_req[row, 1] = nz_mem
        self.pod_count[row] = len(ni.pods)
        self.aff_count[row] = len(ni.pods_with_affinity())
        for t, n in ni._ports.items():
            # intern FIRST: _port_col_locked may reallocate port_counts
            col = self._port_col_locked(t)
            self.port_counts[row, col] = n
        zd = int(self.zone_dense[row])
        if zd >= 0:
            self.zone_pods[zd] += len(ni.pods)

    def _grow_nodes_locked(self) -> None:
        old = self.capacity
        cap = _node_bucket(old + 1)
        if cap <= old:
            cap = old * 2

        def grow(a, fill=0):
            shape = (cap,) + a.shape[1:]
            out = np.full(shape, fill, a.dtype) if fill else np.zeros(shape, a.dtype)
            out[:old] = a
            return out

        self.requested = grow(self.requested)
        self.nonzero_req = grow(self.nonzero_req)
        self.pod_count = grow(self.pod_count)
        self.aff_count = grow(self.aff_count)
        self.port_counts = grow(self.port_counts)
        self.zone_dense = grow(self.zone_dense, fill=-1)
        self.row_gen = grow(self.row_gen)
        self.name_of_row.extend([None] * (cap - old))
        self._pending.extend([None] * (cap - old))
        self._free_rows.extend(range(cap - 1, old - 1, -1))
        self.capacity = cap

    def _grow_width_locked(self, width: int) -> None:
        """Resource-slot growth (extended resources): the requested and
        spec-row matrices widen in LOCKSTEP — the scatter add relies on
        their widths matching."""
        w = _bucket(width, 8)
        for attr in ("requested", "spec_req"):
            a = getattr(self, attr)
            out = np.zeros((a.shape[0], w), np.int64)
            out[:, : a.shape[1]] = a
            setattr(self, attr, out)

    def _zone_dense_locked(self, labels: Dict[str, str]) -> int:
        zk = _zone_key(labels)
        if not zk:
            return -1
        idx = self._zone_ids.get(zk)
        if idx is None:
            idx = len(self._zone_ids)
            self._zone_ids[zk] = idx
            if idx >= self.zone_pods.shape[0]:
                out = np.zeros(self.zone_pods.shape[0] * 2, np.int64)
                out[: self.zone_pods.shape[0]] = self.zone_pods
                self.zone_pods = out
        return idx

    def _port_col_locked(self, triple: Tuple[str, str, int]) -> int:
        col = self._port_col.get(triple)
        if col is None:
            col = len(self._port_col)
            self._port_col[triple] = col
            if col >= self.port_counts.shape[1]:
                out = np.zeros(
                    (self.port_counts.shape[0], self.port_counts.shape[1] * 2),
                    np.int16,
                )
                out[:, : self.port_counts.shape[1]] = self.port_counts
                self.port_counts = out
        return col

    # -- per-spec delta rows (the one delta source) --------------------------

    def _slot_for_locked(self, pod) -> int:
        """Intern the pod's delta row (requested slots, non-zero request,
        ports, affinity flag) and return its slot. Memoized on the pod
        object — `with_node` clones carry it, so the fold planner's
        intern on the original pod is a free hit for the commit clone —
        and content-keyed underneath so every replica of a controller
        shares one row."""
        memo = pod.__dict__.get("_col_slot_memo")
        if memo is not None and memo[0] is self:
            return memo[1]
        pairs = _req_slot_pairs(self.vocab, pod)
        nz = pod_non_zero_request(pod)
        ports = tuple(pod.host_ports())
        aff = pod_has_affinity_constraints(pod)
        key = (pairs, nz, ports, aff)
        slot = self._slot_of.get(key)
        if slot is None:
            slot = len(self._slot_of)
            if slot >= self.spec_req.shape[0]:
                self._grow_specs_locked()
            for s, v in pairs:
                if s >= self.spec_req.shape[1]:
                    self._grow_width_locked(s + 1)
                self.spec_req[slot, s] = v
            self.spec_nz[slot, 0] = nz[0]
            self.spec_nz[slot, 1] = nz[1]
            self.spec_aff[slot] = aff
            self.spec_has_ports[slot] = bool(ports)
            self._spec_ports[slot] = tuple(
                self._port_col_locked(t) for t in ports
            )
            self._slot_of[key] = slot
            self.stats["spec_rows"] += 1
        pod.__dict__["_col_slot_memo"] = (self, slot)
        return slot

    def _grow_specs_locked(self) -> None:
        old = self.spec_req.shape[0]
        cap = old * 2

        def grow(a):
            out = np.zeros((cap,) + a.shape[1:], a.dtype)
            out[:old] = a
            return out

        self.spec_req = grow(self.spec_req)
        self.spec_nz = grow(self.spec_nz)
        self.spec_aff = grow(self.spec_aff)
        self.spec_has_ports = grow(self.spec_has_ports)
        self._spec_ports = self._spec_ports + [()] * (cap - old)

    def delta_mats_locked(
        self, pods: Sequence, width: int
    ) -> Tuple[np.ndarray, np.ndarray]:
        """(req[B, width], nz[B, 2]) delta matrices for `pods`, gathered
        from the interned spec rows — the SAME integers the columns were
        (or will be) scattered with. Raises KeySlotOverflow when any pod
        carries a resource slot beyond `width` (the caller's bank is too
        narrow — exactly the legacy per-pod path's overflow contract)."""
        n = len(pods)
        slots = np.empty(n, np.int64)
        slot_for = self._slot_for_locked
        for i, pod in enumerate(pods):
            slots[i] = slot_for(pod)
        req = self.spec_req[slots]
        if req.shape[1] > width:
            if req[:, width:].any():
                raise KeySlotOverflow()
            req = req[:, :width]
        elif req.shape[1] < width:
            out = np.zeros((n, width), np.int64)
            out[:, : req.shape[1]] = req
            req = out
        return req, self.spec_nz[slots]

    def delta_mats(self, pods: Sequence, width: int):
        """Locking wrapper of delta_mats_locked for off-cache-lock
        callers (the fold planner runs on the driver thread)."""
        with self._lock:
            return self.delta_mats_locked(pods, width)

    # -- bulk columnar mutation (caller holds the cache lock) ----------------

    def _scatter_locked(self, ridx: np.ndarray, slots: np.ndarray, sign: int) -> None:
        # fault-plane injection site (kubernetes_tpu/faults): the driver
        # arms `fault_hook` only when a FaultPlan is configured — one
        # attribute read otherwise. A raise here is handled by the cache
        # (inline detach: journal-before-scatter keeps object truth).
        hook = self.fault_hook
        if hook is not None:
            hook()
        # forget is the exact integer inverse: subtract.at instead of
        # negating (a negation copies the whole gathered delta matrix)
        scatter = np.add.at if sign > 0 else np.subtract.at
        scatter(self.requested, ridx, self.spec_req[slots])
        scatter(self.nonzero_req, ridx, self.spec_nz[slots])
        np.add.at(self.pod_count, ridx, sign)
        aff = self.spec_aff[slots]
        if aff.any():
            np.add.at(self.aff_count, ridx[aff], sign)
        zd = self.zone_dense[ridx]
        zm = zd >= 0
        if zm.any():
            np.add.at(self.zone_pods, zd[zm], sign)
        hp = self.spec_has_ports[slots]
        if hp.any():
            for i in np.nonzero(hp)[0]:
                for col in self._spec_ports[int(slots[i])]:
                    self.port_counts[int(ridx[i]), col] += sign

    def _bulk_locked(self, rows: Sequence[int], pods: Sequence, sign: int) -> None:
        n = len(pods)
        if n == 0:
            return
        # ONE tight loop per pod: memo-hit slot lookup (inlined — the
        # method call was a measurable slice at 4096-pod batches) + the
        # journal append; everything else is vectorized below
        slots_l: List[int] = []
        append_slot = slots_l.append
        slot_for = self._slot_for_locked
        pend = self._pending
        add = sign > 0
        for row, pod in zip(rows, pods):
            memo = pod.__dict__.get("_col_slot_memo")
            if memo is not None and memo[0] is self:
                append_slot(memo[1])
            else:
                append_slot(slot_for(pod))
            ops = pend[row]
            if ops is None:
                ops = pend[row] = []
            # journal encoding: an ADD is the pod itself (the common
            # case, no tuple alloc); a REMOVE is a 1-tuple wrapper
            ops.append(pod if add else (pod,))
        slots = np.asarray(slots_l, np.int64)
        ridx = np.asarray(rows, np.int64)
        self._scatter_locked(ridx, slots, sign)
        self._stale_rows.update(rows)
        self.generation += 1
        self.row_gen[ridx] = self.generation
        # journal bound, amortized: scan the stale set only once per
        # JOURNAL_BOUND journaled ops instead of checking every append
        self._journal_since_check += n
        if self._journal_since_check >= JOURNAL_BOUND:
            self._journal_since_check = 0
            for row in self._stale_rows:
                if len(pend[row]) >= JOURNAL_BOUND:
                    self._overgrown.add(row)
        self.stats["bulk_batches"] += 1
        self.stats["bulk_pods"] += n

    def assume_bulk_locked(self, rows: Sequence[int], pods: Sequence) -> None:
        """Bulk assume: vectorized column scatter + per-row view journal.
        ZERO NodeInfo/Quantity object updates — the view catches up on
        first read (materialize)."""
        self._bulk_locked(rows, pods, 1)

    def forget_bulk_locked(self, rows: Sequence[int], pods: Sequence) -> None:
        """Bulk forget (gang rollback / bind failure): exact integer
        inverse of assume_bulk, journaled the same way."""
        self._bulk_locked(rows, pods, -1)

    def apply_one_locked(self, row: int, pod, sign: int) -> None:
        """Scalar twin for the eager object paths (informer events,
        scalar assume/forget): the object cache was already updated by
        the caller — the columns advance by the same interned delta row
        so column truth never forks from object truth."""
        slot = self._slot_for_locked(pod)
        self.requested[row] += sign * self.spec_req[slot]
        self.nonzero_req[row] += sign * self.spec_nz[slot]
        self.pod_count[row] += sign
        if self.spec_aff[slot]:
            self.aff_count[row] += sign
        zd = int(self.zone_dense[row])
        if zd >= 0:
            self.zone_pods[zd] += sign
        for col in self._spec_ports[slot]:
            self.port_counts[row, col] += sign
        self.generation += 1
        self.row_gen[row] = self.generation
        self.stats["scalar_pods"] += 1

    # -- lazy view materialization (caller holds the cache lock) -------------

    def row_stale_locked(self, row: int) -> bool:
        return row in self._stale_rows

    def materialize_into_locked(self, name: str, ni: NodeInfo) -> int:
        """Replay the row's journal into its NodeInfo view, in journal
        order (bit-identical pod-list order to the eager path), and tag
        the view with the row's column generation. Returns the number of
        ops replayed."""
        row = self.row_of.get(name)
        if row is None:
            return 0
        ops = self._pending[row]
        if not ops:
            return 0
        self._pending[row] = []
        self._stale_rows.discard(row)
        self._overgrown.discard(row)
        for e in ops:
            # journal encoding (see _bulk_locked): bare pod = add,
            # 1-tuple = remove
            if type(e) is tuple:
                ni.remove_pod_key(e[0].key())
            else:
                ni.add_pod(e)
        ni.generation = int(self.row_gen[row])
        self.stats["materializations"] += 1
        self.stats["materialized_pods"] += len(ops)
        return len(ops)

    def host_port_conflict(self, name: str, pod) -> bool:
        """HostPortInfo.CheckConflict over the port COLUMNS — the commit
        path's staleness probe for ported pods, bit-identical to
        NodeInfo.host_port_conflict without materializing the lazy view.
        Takes the lock itself (driver-thread caller)."""
        with self._lock:
            row = self.row_of.get(name)
            if row is None:
                return False
            pc = self.port_counts
            col_of = self._port_col
            for proto, ip, port in pod.host_ports():
                if port <= 0:
                    continue
                if ip == DEFAULT_BIND_ALL_HOST_IP:
                    for (uproto, _uip, uport), c in col_of.items():
                        if uport == port and uproto == proto and pc[row, c] > 0:
                            return True
                else:
                    for cand in (
                        (proto, ip, port),
                        (proto, DEFAULT_BIND_ALL_HOST_IP, port),
                    ):
                        c = col_of.get(cand)
                        if c is not None and pc[row, c] > 0:
                            return True
            return False

    # -- probes --------------------------------------------------------------

    def usage_divergence_locked(self, mirror_row_of: Dict[str, int], bank) -> List[str]:
        """Vectorized cross-check of the columns against a mirror
        NodeBank's HOST usage arrays (requested / nonzero_req /
        pod_count): the columnar half of the device-divergence probe.
        Meaningful only when the mirror is fully synced (the caller
        gates on an empty delta log)."""
        out: List[str] = []
        common = [
            (mrow, self.row_of[nm])
            for nm, mrow in mirror_row_of.items()
            if nm in self.row_of
        ]
        if len(common) != len(self.row_of):
            out.append("columns.row_of:node-set-mismatch")
        if not common:
            return out
        midx = np.asarray([c[0] for c in common], np.int64)
        cidx = np.asarray([c[1] for c in common], np.int64)
        w = min(self.requested.shape[1], bank.requested.shape[1])
        if not np.array_equal(self.requested[cidx, :w], bank.requested[midx, :w]):
            out.append("columns.requested")
        if self.requested.shape[1] > w and self.requested[cidx, w:].any():
            out.append("columns.requested:width-overflow")
        if not np.array_equal(self.nonzero_req[cidx], bank.nonzero_req[midx]):
            out.append("columns.nonzero_req")
        if not np.array_equal(
            self.pod_count[cidx], bank.pod_count[midx].astype(np.int32)
        ):
            out.append("columns.pod_count")
        return out

    def object_divergence(self, node_infos: Dict[str, NodeInfo]) -> List[str]:
        """Names of nodes whose MATERIALIZED object aggregates disagree
        with the columns — the parity probe the microbench and the test
        suite assert empty. Takes the lock itself (debug API). Rows with
        a pending journal are compared against object + journal by
        materializing first (via plain replay — callers pass the raw
        dict, so resolution is explicit here)."""
        out: List[str] = []
        with self._lock:
            # snapshot the slot map under ITS lock (the informer-thread
            # ingest encode interns new resources concurrently; iterating
            # the live dict could see a half-assigned slot or raise)
            with self.vocab._slot_lock:
                res_slots = dict(self.vocab.resource_slot)
            for name, ni in node_infos.items():
                row = self.row_of.get(name)
                if row is None:
                    out.append(f"{name}:no-row")
                    continue
                if self.row_stale_locked(row):
                    self.materialize_into_locked(name, ni)
                req = {}
                for rname, s in res_slots.items():
                    if s < self.requested.shape[1] and self.requested[row, s]:
                        req[rname] = int(self.requested[row, s])
                want = {
                    k: v for k, v in ni.requested().items()
                    if k != RESOURCE_PODS  # columns never track it
                }
                if req != want:
                    out.append(f"{name}:requested")
                if (
                    int(self.nonzero_req[row, 0]),
                    int(self.nonzero_req[row, 1]),
                ) != ni.non_zero_requested():
                    out.append(f"{name}:nonzero_req")
                if int(self.pod_count[row]) != len(ni.pods):
                    out.append(f"{name}:pod_count")
                if int(self.aff_count[row]) != len(ni.pods_with_affinity()):
                    out.append(f"{name}:aff_count")
                ports = {
                    t: int(self.port_counts[row, c])
                    for t, c in self._port_col.items()
                    if self.port_counts[row, c]
                }
                if ports != ni._ports:
                    out.append(f"{name}:ports")
        return out

    def stats_snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self.stats)

    def census_locked(self) -> Dict[str, object]:
        """The columns' steady-state health block (obs/introspect),
        caller holds the cache lock: row occupancy, the lazy-view journal
        depth (total pending ops behind unmaterialized NodeInfo views),
        stale/overgrown row counts, and the interned-spec-row census.
        Counters and metadata only."""
        pend = self._pending
        journal = 0
        for row in self._stale_rows:
            ops = pend[row]
            if ops:
                journal += len(ops)
        return {
            "capacity": int(self.capacity),
            "rows": len(self.row_of),
            "free_rows": len(self._free_rows),
            "stale_rows": len(self._stale_rows),
            "journal_depth": journal,
            "overgrown_rows": len(self._overgrown),
            "spec_rows": len(self._slot_of),
            "generation": int(self.generation),
            "stats": dict(self.stats),
        }
