"""Cache debugger (pkg/scheduler/internal/cache/debugger/): dump the
cache/queue state and compare the cache against the informer's view.

The reference wires these to SIGUSR2 (debugger/signal.go); install_signal
does the same here. The comparer is the drift detector: cache contents are
DERIVED state (rebuilt from the watch stream) and must match the
informers' authoritative lists.
"""

from __future__ import annotations

import signal
import sys
from typing import Iterable, List, Tuple

from ..api.types import Node, Pod


class CacheDumper:
    """debugger/dumper.go: log the cache + queue state."""

    def __init__(self, cache, queue=None):
        self.cache = cache
        self.queue = queue

    def dump(self) -> str:
        lines: List[str] = ["Dump of cached NodeInfo:"]
        snap = self.cache.snapshot
        for name, ni in sorted(snap.node_infos.items()):
            req = ni.requested()
            lines.append(
                f"  node {name}: pods={len(ni.pods)} requested={req} "
                f"ports={len(ni.used_host_ports())}"
            )
            for p in ni.pods:
                mark = " (assumed)" if self.cache.is_assumed(p.key()) else ""
                lines.append(f"    pod {p.key()}{mark}")
        if self.queue is not None:
            a, b, u = self.queue.counts()
            lines.append(f"Scheduling queue: active={a} backoff={b} unschedulable={u}")
        return "\n".join(lines)


class CacheComparer:
    """debugger/comparer.go: cache vs informer lists → (missed, redundant)."""

    def __init__(self, cache):
        self.cache = cache

    def compare_nodes(self, informer_nodes: Iterable[Node]) -> Tuple[List[str], List[str]]:
        actual = set(self.cache.snapshot.node_infos)
        expected = {n.name for n in informer_nodes}
        return sorted(expected - actual), sorted(actual - expected)

    def compare_pods(self, informer_pods: Iterable[Pod]) -> Tuple[List[str], List[str]]:
        """Assigned pods the cache should know. Assumed-but-unconfirmed pods
        are cache-only by design and not counted redundant
        (comparer.go ComparePods: cached + assumed vs nodeinfo lists)."""
        cached = {
            p.key()
            for ni in self.cache.snapshot.node_infos.values()
            for p in ni.pods
        }
        expected = {p.key() for p in informer_pods if p.node_name}
        missed = sorted(expected - cached)
        redundant = sorted(
            k for k in cached - expected if not self.cache.is_assumed(k)
        )
        return missed, redundant


def install_signal(cache, queue=None, sig=signal.SIGUSR2, out=sys.stderr):
    """debugger/signal.go: SIGUSR2 → dump to stderr. Returns the handler."""
    dumper = CacheDumper(cache, queue)

    def handler(signum, frame):
        print(dumper.dump(), file=out, flush=True)

    signal.signal(sig, handler)
    return handler
