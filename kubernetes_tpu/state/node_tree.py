"""NodeTree: zone-interleaved round-robin node iteration
(pkg/scheduler/internal/cache/node_tree.go:31, Next() :162).

The reference iterates nodes zone-by-zone round-robin so that, combined
with adaptive sampling, feasible-node discovery (and therefore score ties)
spreads across zones. The batch solver evaluates the full matrix and
breaks ties uniformly at random (selectHost semantics), which already
de-biases zones — but the HOST paths (oracle re-placement, extender
/filter answering with ordered name lists) iterate nodes in some order,
and first-max-wins tie-breaks there inherit it. NodeTree supplies the
zone-interleaved order for those paths.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..analysis.lockorder import audited_lock
from ..api.types import Node
from ..oracle.nodeinfo import get_zone_key


class NodeTree:
    def __init__(self):
        self._lock = audited_lock("node-tree")
        self._tree: Dict[str, List[str]] = {}  # ktpu: guarded-by(self._lock) zone key -> node names
        self._zones: List[str] = []  # insertion-ordered zone keys
        self._zone_index = 0
        self._last_index: Dict[str, int] = {}  # ktpu: guarded-by(self._lock)
        self._rotation = 0  # order() starting offset (rotating tie de-bias)
        self.num_nodes = 0  # ktpu: guarded-by(self._lock)

    def add_node(self, node: Node) -> None:
        with self._lock:
            zone = get_zone_key(node)
            arr = self._tree.get(zone)
            if arr is None:
                self._tree[zone] = [node.name]
                self._zones.append(zone)
            elif node.name not in arr:
                arr.append(node.name)
            else:
                return
            self.num_nodes += 1

    def remove_node(self, node: Node) -> None:
        with self._lock:
            zone = get_zone_key(node)
            arr = self._tree.get(zone)
            if arr is None or node.name not in arr:
                return
            arr.remove(node.name)
            self.num_nodes -= 1
            if not arr:
                del self._tree[zone]
                self._zones.remove(zone)
                self._last_index.pop(zone, None)

    def update_node(self, old: Optional[Node], new: Node) -> None:
        if old is not None and get_zone_key(old) != get_zone_key(new):
            self.remove_node(old)
        # always (re-)register: headless placeholders promoted to real nodes
        # were never added, and add_node dedups known names
        self.add_node(new)

    def next(self) -> Optional[str]:
        """Next(): one node name, round-robining across zones; a zone's
        nodes are consumed one per visit (node_tree.go:162-186)."""
        with self._lock:
            if not self._zones:
                return None
            for _ in range(len(self._zones)):
                zone = self._zones[self._zone_index % len(self._zones)]
                self._zone_index += 1
                idx = self._last_index.get(zone, 0)
                arr = self._tree[zone]
                if idx >= len(arr):
                    self._last_index[zone] = 0
                    idx = 0
                self._last_index[zone] = idx + 1
                return arr[idx]
            return None

    def order(self) -> List[str]:
        """One full zone-interleaved pass over every node — the iteration
        order host-side placement loops should use. Successive calls rotate
        the starting point (the stateful-Next round-robin de-bias,
        node_tree.go:162) so first-max-wins tie-breaks don't hotspot the
        same node every cycle."""
        with self._lock:
            if not self._zones:
                return []
            out: List[str] = []
            idx = 0
            remaining = True
            while remaining:
                remaining = False
                for zone in self._zones:
                    arr = self._tree[zone]
                    if idx < len(arr):
                        out.append(arr[idx])
                        if idx + 1 < len(arr):
                            remaining = True
                idx += 1
            r = self._rotation % len(out)
            self._rotation = (self._rotation + 1) % len(out)
            return out[r:] + out[:r]
