"""Flat term tables: the sparse encoding of (anti-)affinity terms, topology
spread constraints, and spreading selectors.

The reference precomputes per-pod topology-pair maps (predicates/metadata.go
topologyPairsMaps, evenPodsSpreadMetadata) with nested hash maps. Here every
term — an (owner, topology-key-slot, namespace-set, label-selector) tuple —
becomes one ROW of a padded table; matching a term against all existing pods
or the whole incoming batch is then a single broadcasted integer-compare, and
per-topology-value aggregation is a segment_sum keyed by the dense value
index (NodeBank.label_dense). Affinity terms are rare relative to pods, so
the tables stay small (sparse encoding of a quadratic problem).

Term kinds:
  incoming batch:  AFF_REQ, ANTI_REQ (Filter), AFF_PREF, ANTI_PREF (Score),
                   SPREAD_HARD (Filter), SPREAD_SOFT (Score), SEL_SPREAD
  existing pods:   same AFF_*/ANTI_* kinds with owner = the hosting node's NodeBank row
                   (the symmetric side: existing pods' terms matched against
                   the incoming pod)
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..api.types import LabelSelector, Pod, PodAffinityTerm
from ..api.selectors import match_label_selector
from ..oracle.nodeinfo import Snapshot
from ..oracle.predicates import (
    get_hard_spread_constraints,
    get_pod_affinity_terms,
    get_pod_anti_affinity_terms,
    get_soft_spread_constraints,
    pod_matches_all_term_properties,
)
from .tensors import (
    KeySlotOverflow,
    OP_DOES_NOT_EXIST,
    OP_EXISTS,
    OP_IN,
    OP_NEVER,
    OP_NOT_IN,
    Vocab,
    _bucket,
)

# term kinds
AFF_REQ = 1
ANTI_REQ = 2
AFF_PREF = 3
ANTI_PREF = 4
SPREAD_HARD = 5
SPREAD_SOFT = 6
SEL_SPREAD = 7


@dataclass
class TermBank:
    """Padded term rows + compiled label selectors."""

    vocab: Vocab
    capacity: int
    ns_cap: int = 4  # namespaces per term
    ml_cap: int = 4  # matchLabels pairs per selector
    ex_cap: int = 4  # matchExpressions per selector
    val_cap: int = 6  # values per expression

    def __post_init__(self):
        t = self.capacity
        self.key_capacity = self.vocab.config.key_slots
        self.valid = np.zeros(t, bool)
        self.kind = np.zeros(t, np.int32)
        self.owner = np.zeros(t, np.int32)
        self.weight = np.zeros(t, np.int32)  # pref weight / maxSkew
        self.topo_slot = np.full(t, -1, np.int32)
        self.self_match = np.zeros(t, bool)  # spread: selector matches owner pod
        self.ns_any = np.zeros(t, bool)
        self.ns_ids = np.zeros((t, self.ns_cap), np.int32)
        self.has_selector = np.zeros(t, bool)  # nil selector matches nothing
        self.ml_slot = np.full((t, self.ml_cap), -1, np.int32)
        self.ml_val = np.zeros((t, self.ml_cap), np.int32)
        self.ex_op = np.zeros((t, self.ex_cap), np.int32)
        self.ex_slot = np.full((t, self.ex_cap), -1, np.int32)
        self.ex_vals = np.full((t, self.ex_cap, self.val_cap), -1, np.int32)
        self.count = 0
        self.overflow_owners: set = set()

    def _compile_selector(self, row: int, sel: Optional[LabelSelector]) -> None:
        v = self.vocab
        if sel is None:
            self.has_selector[row] = False
            return
        self.has_selector[row] = True
        ml = list(sel.match_labels.items())
        if len(ml) > self.ml_cap:
            self.overflow_owners.add(int(self.owner[row]))
        for j, (k, val) in enumerate(ml[: self.ml_cap]):
            s = v.slot_of_key(k)
            if s >= self.key_capacity:
                raise KeySlotOverflow()
            self.ml_slot[row, j] = s
            self.ml_val[row, j] = v.id(val)
        exprs = sel.match_expressions
        if len(exprs) > self.ex_cap:
            self.overflow_owners.add(int(self.owner[row]))
        op_map = {"In": OP_IN, "NotIn": OP_NOT_IN, "Exists": OP_EXISTS, "DoesNotExist": OP_DOES_NOT_EXIST}
        for j, e in enumerate(exprs[: self.ex_cap]):
            op = op_map.get(e.operator, OP_NEVER)
            # In/NotIn with no values is invalid (selector parse error →
            # matches nothing, LabelSelectorAsSelector error path)
            if op in (OP_IN, OP_NOT_IN) and not e.values:
                op = OP_NEVER
            self.ex_op[row, j] = op
            s = v.slot_of_key(e.key)
            if s >= self.key_capacity:
                raise KeySlotOverflow()
            self.ex_slot[row, j] = s
            if len(e.values) > self.val_cap:
                self.overflow_owners.add(int(self.owner[row]))
            for k_idx, val in enumerate(e.values[: self.val_cap]):
                self.ex_vals[row, j, k_idx] = v.id(val)

    def add(
        self,
        kind: int,
        owner: int,
        topo_key: str,
        selector: Optional[LabelSelector],
        namespaces: Sequence[str] = (),
        ns_any: bool = False,
        weight: int = 0,
        self_match: bool = False,
    ) -> int:
        row = self.count
        if row >= self.capacity:
            self.overflow_owners.add(owner)
            return -1
        self.count += 1
        self.set_row(row, kind, owner, topo_key, selector, namespaces, ns_any, weight, self_match)
        return row

    def set_row(
        self,
        row: int,
        kind: int,
        owner: int,
        topo_key: str,
        selector: Optional[LabelSelector],
        namespaces: Sequence[str] = (),
        ns_any: bool = False,
        weight: int = 0,
        self_match: bool = False,
    ) -> None:
        """Encode one term at an explicit row (PatternBank reuses this with
        its own free-list row allocation)."""
        v = self.vocab
        self.valid[row] = True
        self.kind[row] = kind
        self.owner[row] = owner
        self.weight[row] = weight
        self.self_match[row] = self_match
        if topo_key:
            s = v.slot_of_key(topo_key)
            if s >= self.key_capacity:
                raise KeySlotOverflow()
            self.topo_slot[row] = s
        self.ns_any[row] = ns_any
        if not ns_any:
            nss = list(namespaces)
            if len(nss) > self.ns_cap:
                self.overflow_owners.add(owner)
            for j, ns in enumerate(nss[: self.ns_cap]):
                self.ns_ids[row, j] = v.id(ns)
        self._compile_selector(row, selector)

    def clear_row(self, row: int) -> None:
        """Reset a row to padding (every kernel gates on `valid`; the other
        fields are reset so re-use starts from a clean slate)."""
        self.valid[row] = False
        self.kind[row] = 0
        self.owner[row] = 0
        self.weight[row] = 0
        self.self_match[row] = False
        self.topo_slot[row] = -1
        self.ns_any[row] = False
        self.ns_ids[row] = 0
        self.has_selector[row] = False
        self.ml_slot[row] = -1
        self.ml_val[row] = 0
        self.ex_op[row] = 0
        self.ex_slot[row] = -1
        self.ex_vals[row] = -1

    def arrays(self) -> Dict[str, np.ndarray]:
        return {
            "valid": self.valid,
            "kind": self.kind,
            "owner": self.owner,
            "weight": self.weight,
            "topo_slot": self.topo_slot,
            "self_match": self.self_match,
            "ns_any": self.ns_any,
            "ns_ids": self.ns_ids,
            "has_selector": self.has_selector,
            "ml_slot": self.ml_slot,
            "ml_val": self.ml_val,
            "ex_op": self.ex_op,
            "ex_slot": self.ex_slot,
            "ex_vals": self.ex_vals,
        }


def _term_namespaces(owner_pod: Pod, term: PodAffinityTerm) -> List[str]:
    return list(term.namespaces) if term.namespaces else [owner_pod.namespace]


def compile_batch_terms(
    vocab: Vocab,
    pods: Sequence[Pod],
    spread_selectors: Optional[Dict[int, List[LabelSelector]]] = None,
    capacity: Optional[int] = None,
    b_capacity: Optional[int] = None,
) -> Tuple[TermBank, Dict[str, np.ndarray]]:
    """Compile all topology-coupled structure of a pending-pod batch into one
    TermBank + per-pod aux arrays:
      self_aff_match[b]: pod matches its own required affinity terms' props
                         (the first-pod-in-series escape hatch)
      has_aff[b] / has_anti[b]: pod has required (anti-)affinity terms
      n_sel_spread[b]: number of spreading selectors (0 → score 0 rule)
    """
    n_terms = 0
    for p in pods:
        n_terms += len(get_hard_spread_constraints(p)) + len(get_soft_spread_constraints(p))
        n_terms += len(get_pod_affinity_terms(p.affinity)) + len(get_pod_anti_affinity_terms(p.affinity))
        if p.affinity is not None and p.affinity.pod_affinity is not None:
            n_terms += len(p.affinity.pod_affinity.preferred)
        if p.affinity is not None and p.affinity.pod_anti_affinity is not None:
            n_terms += len(p.affinity.pod_anti_affinity.preferred)
        if spread_selectors:
            n_terms += len(spread_selectors.get(id(p), []) or [])
    bank = TermBank(vocab, capacity or _bucket(max(n_terms, 1)))
    b_count = b_capacity or _bucket(len(pods))
    self_aff_match = np.zeros(b_count, bool)
    has_aff = np.zeros(b_count, bool)
    has_anti = np.zeros(b_count, bool)
    n_sel_spread = np.zeros(b_count, np.int32)

    for b, p in enumerate(pods):
        for c in get_hard_spread_constraints(p):
            bank.add(
                SPREAD_HARD,
                b,
                c.topology_key,
                c.label_selector,
                namespaces=[p.namespace],
                weight=c.max_skew,
                self_match=match_label_selector(c.label_selector, p.labels),
            )
        for c in get_soft_spread_constraints(p):
            # the soft-spread priority counts matching pods in ALL namespaces
            # (even_pods_spread.go quirk, see oracle.priorities)
            bank.add(
                SPREAD_SOFT,
                b,
                c.topology_key,
                c.label_selector,
                ns_any=True,
                weight=c.max_skew,
                self_match=match_label_selector(c.label_selector, p.labels),
            )
        aff_terms = get_pod_affinity_terms(p.affinity)
        if aff_terms:
            has_aff[b] = True
            self_aff_match[b] = pod_matches_all_term_properties(p, p, aff_terms)
        for t in aff_terms:
            bank.add(AFF_REQ, b, t.topology_key, t.label_selector, _term_namespaces(p, t))
        anti_terms = get_pod_anti_affinity_terms(p.affinity)
        if anti_terms:
            has_anti[b] = True
        for t in anti_terms:
            bank.add(ANTI_REQ, b, t.topology_key, t.label_selector, _term_namespaces(p, t))
        if p.affinity is not None and p.affinity.pod_affinity is not None:
            for w in p.affinity.pod_affinity.preferred:
                if w.weight and w.pod_affinity_term.topology_key:
                    t = w.pod_affinity_term
                    bank.add(AFF_PREF, b, t.topology_key, t.label_selector, _term_namespaces(p, t), weight=w.weight)
        if p.affinity is not None and p.affinity.pod_anti_affinity is not None:
            for w in p.affinity.pod_anti_affinity.preferred:
                if w.weight and w.pod_affinity_term.topology_key:
                    t = w.pod_affinity_term
                    bank.add(ANTI_PREF, b, t.topology_key, t.label_selector, _term_namespaces(p, t), weight=-w.weight)
        for sel in (spread_selectors or {}).get(id(p), []) or []:
            bank.add(SEL_SPREAD, b, "", sel, namespaces=[p.namespace])
            n_sel_spread[b] += 1
    aux = {
        "self_aff_match": self_aff_match,
        "has_aff": has_aff,
        "has_anti": has_anti,
        "n_sel_spread": n_sel_spread,
    }
    return bank, aux


class PatternOverflow(KeySlotOverflow):
    """Pattern bank out of rows — rebuild at the next bucket size."""


@dataclass
class PatternBank:
    """Existing pods' (anti-)affinity terms collapsed to distinct PATTERNS
    with per-node instance counts — the term-side analogue of
    state.tensors.SigBank.

    The old encoding gave every (existing pod, term) pair its own TermBank
    row (owner = hosting node), so affinity-heavy clusters grew the ET axis
    with pod count: each growth bucket was a full solve recompile, every
    batch that committed an affinity pod re-walked ALL pods with terms
    (O(pods) host time) and re-uploaded the whole bank. But the kernels
    only ever need (a) whether a term matches the incoming pod and (b) how
    many instances of it live in each topology bucket — both functions of
    the term's CONTENT, not its owner. Distinct term contents are few
    (one per controller spec, not per replica), so rows become patterns
    interned by (kind, topology key, namespaces, weight, selector), and
    ownership becomes `counts[node, pattern]`, patched incrementally by
    dirty node rows exactly like SigBank.counts.

    Wire format (`arrays()`): the TermBank fields (valid/kind/topo_slot/
    weight/ns_*/selector tables; `owner` is the row's own index and unused
    by the pattern kernels) + `counts` [N, PT] int16.
    """

    vocab: Vocab
    capacity: int  # PT
    node_capacity: int  # N rows of the counts matrix
    hard_pod_affinity_weight: int = 1  # interpod_affinity.go:131

    def __post_init__(self):
        self.bank = TermBank(self.vocab, self.capacity)
        self.counts = np.zeros((self.node_capacity, self.capacity), np.int16)
        self._row_of: Dict[tuple, int] = {}
        self._key_of_row: Dict[int, tuple] = {}
        self._refs = np.zeros(self.capacity, np.int64)
        self._free = list(range(self.capacity - 1, -1, -1))
        self.dirty_pattern_rows: set = set()
        self.overflow_rows: set = set()

    # numpy views used by the driver's term-kind gating
    @property
    def valid(self) -> np.ndarray:
        return self.bank.valid

    @property
    def kind(self) -> np.ndarray:
        return self.bank.kind

    def _pod_patterns(self, pod: Pod) -> List[tuple]:
        """One pod's term contents as intern keys' raw args — the same row
        set the per-pod encoding used to produce."""
        aff = pod.affinity
        if aff is None:
            return []
        out = []
        for t in get_pod_anti_affinity_terms(aff):
            out.append((ANTI_REQ, t.topology_key, t.label_selector, _term_namespaces(pod, t), 0))
        hw = self.hard_pod_affinity_weight
        for t in get_pod_affinity_terms(aff):
            if hw > 0 and t.topology_key:
                out.append((AFF_REQ, t.topology_key, t.label_selector, _term_namespaces(pod, t), hw))
        if aff.pod_affinity is not None:
            for w in aff.pod_affinity.preferred:
                if w.weight and w.pod_affinity_term.topology_key:
                    t = w.pod_affinity_term
                    out.append((AFF_PREF, t.topology_key, t.label_selector, _term_namespaces(pod, t), w.weight))
        if aff.pod_anti_affinity is not None:
            for w in aff.pod_anti_affinity.preferred:
                if w.weight and w.pod_affinity_term.topology_key:
                    t = w.pod_affinity_term
                    out.append((ANTI_PREF, t.topology_key, t.label_selector, _term_namespaces(pod, t), -w.weight))
        return out

    @staticmethod
    def _key(kind: int, topo_key: str, selector, namespaces, weight: int) -> tuple:
        return (kind, topo_key, tuple(sorted(namespaces)), weight, repr(selector))

    def _intern(self, kind: int, topo_key: str, selector, namespaces, weight: int) -> int:
        key = self._key(kind, topo_key, selector, namespaces, weight)
        row = self._row_of.get(key)
        if row is None:
            if not self._free:
                raise PatternOverflow()
            row = self._free.pop()
            self.bank.clear_row(row)
            self.bank.overflow_owners.discard(row)
            self.bank.set_row(row, kind, row, topo_key, selector, namespaces, weight=weight)
            if row in self.bank.overflow_owners:
                # truncated selector: under/over-matches on device — the
                # driver must route affected batches through the oracle
                self.overflow_rows.add(row)
            self._row_of[key] = row
            self._key_of_row[row] = key
            self.dirty_pattern_rows.add(row)
        return row

    def prepare_pod_rows(self, pod: Pod) -> List[int]:
        """Intern one pod's term patterns WITHOUT taking references — the
        device-fold planner's counterpart of SigBank.prepare_row: the
        returned rows are where the later apply_delta will count this pod,
        so the device fold can scatter the counts ahead of the host sync.
        Raises PatternOverflow/KeySlotOverflow like _intern (caller skips
        the fold for the batch)."""
        return [
            self._intern(kind, topo, sel, nss, w)
            for kind, topo, sel, nss, w in self._pod_patterns(pod)
        ]

    def _unref(self, row: int, n: int) -> None:
        self._refs[row] -= n
        if self._refs[row] <= 0:
            self._refs[row] = 0
            self.bank.clear_row(row)
            self.bank.overflow_owners.discard(row)
            self.overflow_rows.discard(row)
            key = self._key_of_row.pop(row, None)
            if key is not None:
                self._row_of.pop(key, None)
            self._free.append(row)
            self.dirty_pattern_rows.add(row)

    def release_node(self, node_row: int, held: Dict[int, int]) -> None:
        """Undo a node's contribution: `held` is its {pattern: count} map."""
        for row, n in held.items():
            self.counts[node_row, row] -= n
            self._unref(row, n)

    def apply_delta(self, node_row: int, pod: Pod, sign: int, held: Dict[int, int]) -> None:
        """O(1) single-pod term-instance change (the mirror's pod-delta
        path). Raises KeySlotOverflow/PatternOverflow like encode_node; a
        remove for an unknown pattern escalates to a rebuild."""
        for kind, topo, sel, nss, w in self._pod_patterns(pod):
            if sign > 0:
                row = self._intern(kind, topo, sel, nss, w)
                held[row] = held.get(row, 0) + 1
                self._refs[row] += 1
                self.counts[node_row, row] += 1
            else:
                row = self._row_of.get(self._key(kind, topo, sel, nss, w))
                if row is None or held.get(row, 0) <= 0:
                    raise PatternOverflow()  # inconsistent books: rebuild
                held[row] -= 1
                if held[row] == 0:
                    del held[row]
                self.counts[node_row, row] -= 1
                self._unref(row, 1)

    def encode_node(self, node_row: int, pods) -> Dict[int, int]:
        """Count a node's pods' term instances into patterns → the
        {pattern: count} map the caller keeps for the matching
        release_node. Raises KeySlotOverflow/PatternOverflow for the
        mirror's rebuild-bigger loop (partial refs rolled back first)."""
        held: Dict[int, int] = {}
        try:
            for pod in pods:
                for kind, topo, sel, nss, w in self._pod_patterns(pod):
                    row = self._intern(kind, topo, sel, nss, w)
                    held[row] = held.get(row, 0) + 1
                    self._refs[row] += 1
                    self.counts[node_row, row] += 1
        except KeySlotOverflow:
            self.release_node(node_row, held)
            raise
        return held

    def arrays(self) -> Dict[str, np.ndarray]:
        out = self.bank.arrays()
        out["counts"] = self.counts
        return out


def compile_existing_patterns(
    vocab: Vocab,
    snapshot: Snapshot,
    row_of: Dict[str, int],
    node_capacity: int,
    hard_pod_affinity_weight: int = 1,
) -> PatternBank:
    """One-shot snapshot → PatternBank (tests/tools; the scheduler maintains
    its bank incrementally through TensorMirror)."""
    min_pt = 32
    while True:
        try:
            pats = PatternBank(
                vocab, _bucket(min_pt), node_capacity,
                hard_pod_affinity_weight=hard_pod_affinity_weight,
            )
            for name, ni in snapshot.node_infos.items():
                pats.encode_node(row_of[name], ni.pods)
            return pats
        except PatternOverflow:
            min_pt *= 2
        except KeySlotOverflow:
            continue  # vocab grew; re-encode
