"""Flat term tables: the sparse encoding of (anti-)affinity terms, topology
spread constraints, and spreading selectors.

The reference precomputes per-pod topology-pair maps (predicates/metadata.go
topologyPairsMaps, evenPodsSpreadMetadata) with nested hash maps. Here every
term — an (owner, topology-key-slot, namespace-set, label-selector) tuple —
becomes one ROW of a padded table; matching a term against all existing pods
or the whole incoming batch is then a single broadcasted integer-compare, and
per-topology-value aggregation is a segment_sum keyed by the dense value
index (NodeBank.label_dense). Affinity terms are rare relative to pods, so
the tables stay small (sparse encoding of a quadratic problem).

Term kinds:
  incoming batch:  AFF_REQ, ANTI_REQ (Filter), AFF_PREF, ANTI_PREF (Score),
                   SPREAD_HARD (Filter), SPREAD_SOFT (Score), SEL_SPREAD
  existing pods:   same AFF_*/ANTI_* kinds with owner = the hosting node's NodeBank row
                   (the symmetric side: existing pods' terms matched against
                   the incoming pod)
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..api.types import LabelSelector, Pod, PodAffinityTerm
from ..api.selectors import match_label_selector
from ..oracle.nodeinfo import Snapshot
from ..oracle.predicates import (
    get_hard_spread_constraints,
    get_pod_affinity_terms,
    get_pod_anti_affinity_terms,
    get_soft_spread_constraints,
    pod_matches_all_term_properties,
)
from .tensors import (
    KeySlotOverflow,
    OP_DOES_NOT_EXIST,
    OP_EXISTS,
    OP_IN,
    OP_NEVER,
    OP_NOT_IN,
    Vocab,
    _bucket,
)

# term kinds
AFF_REQ = 1
ANTI_REQ = 2
AFF_PREF = 3
ANTI_PREF = 4
SPREAD_HARD = 5
SPREAD_SOFT = 6
SEL_SPREAD = 7


@dataclass
class TermBank:
    """Padded term rows + compiled label selectors."""

    vocab: Vocab
    capacity: int
    ns_cap: int = 4  # namespaces per term
    ml_cap: int = 4  # matchLabels pairs per selector
    ex_cap: int = 4  # matchExpressions per selector
    val_cap: int = 6  # values per expression

    def __post_init__(self):
        t = self.capacity
        self.key_capacity = self.vocab.config.key_slots
        self.valid = np.zeros(t, bool)
        self.kind = np.zeros(t, np.int32)
        self.owner = np.zeros(t, np.int32)
        self.weight = np.zeros(t, np.int32)  # pref weight / maxSkew
        self.topo_slot = np.full(t, -1, np.int32)
        self.self_match = np.zeros(t, bool)  # spread: selector matches owner pod
        self.ns_any = np.zeros(t, bool)
        self.ns_ids = np.zeros((t, self.ns_cap), np.int32)
        self.has_selector = np.zeros(t, bool)  # nil selector matches nothing
        self.ml_slot = np.full((t, self.ml_cap), -1, np.int32)
        self.ml_val = np.zeros((t, self.ml_cap), np.int32)
        self.ex_op = np.zeros((t, self.ex_cap), np.int32)
        self.ex_slot = np.full((t, self.ex_cap), -1, np.int32)
        self.ex_vals = np.full((t, self.ex_cap, self.val_cap), -1, np.int32)
        # ktpu: allow(KTPU006) per-instance value object: batch tables are
        # built and consumed on one thread; the terms_plane SLAB instance's
        # mutations run under TermStage._lock (holder-side discipline)
        self.count = 0
        self.overflow_owners: set = set()

    def _compile_selector(self, row: int, sel: Optional[LabelSelector]) -> None:
        v = self.vocab
        if sel is None:
            self.has_selector[row] = False
            return
        self.has_selector[row] = True
        ml = list(sel.match_labels.items())
        if len(ml) > self.ml_cap:
            self.overflow_owners.add(int(self.owner[row]))
        for j, (k, val) in enumerate(ml[: self.ml_cap]):
            s = v.slot_of_key(k)
            if s >= self.key_capacity:
                raise KeySlotOverflow()
            self.ml_slot[row, j] = s
            self.ml_val[row, j] = v.id(val)
        exprs = sel.match_expressions
        if len(exprs) > self.ex_cap:
            self.overflow_owners.add(int(self.owner[row]))
        op_map = {"In": OP_IN, "NotIn": OP_NOT_IN, "Exists": OP_EXISTS, "DoesNotExist": OP_DOES_NOT_EXIST}
        for j, e in enumerate(exprs[: self.ex_cap]):
            op = op_map.get(e.operator, OP_NEVER)
            # In/NotIn with no values is invalid (selector parse error →
            # matches nothing, LabelSelectorAsSelector error path)
            if op in (OP_IN, OP_NOT_IN) and not e.values:
                op = OP_NEVER
            self.ex_op[row, j] = op
            s = v.slot_of_key(e.key)
            if s >= self.key_capacity:
                raise KeySlotOverflow()
            self.ex_slot[row, j] = s
            if len(e.values) > self.val_cap:
                self.overflow_owners.add(int(self.owner[row]))
            for k_idx, val in enumerate(e.values[: self.val_cap]):
                self.ex_vals[row, j, k_idx] = v.id(val)

    def add(
        self,
        kind: int,
        owner: int,
        topo_key: str,
        selector: Optional[LabelSelector],
        namespaces: Sequence[str] = (),
        ns_any: bool = False,
        weight: int = 0,
        self_match: bool = False,
    ) -> int:
        row = self.count
        if row >= self.capacity:
            self.overflow_owners.add(owner)
            return -1
        self.count += 1
        self.set_row(row, kind, owner, topo_key, selector, namespaces, ns_any, weight, self_match)
        return row

    def set_row(
        self,
        row: int,
        kind: int,
        owner: int,
        topo_key: str,
        selector: Optional[LabelSelector],
        namespaces: Sequence[str] = (),
        ns_any: bool = False,
        weight: int = 0,
        self_match: bool = False,
    ) -> None:
        """Encode one term at an explicit row (PatternBank reuses this with
        its own free-list row allocation)."""
        v = self.vocab
        self.valid[row] = True
        self.kind[row] = kind
        self.owner[row] = owner
        self.weight[row] = weight
        self.self_match[row] = self_match
        if topo_key:
            s = v.slot_of_key(topo_key)
            if s >= self.key_capacity:
                raise KeySlotOverflow()
            self.topo_slot[row] = s
        self.ns_any[row] = ns_any
        if not ns_any:
            nss = list(namespaces)
            if len(nss) > self.ns_cap:
                self.overflow_owners.add(owner)
            for j, ns in enumerate(nss[: self.ns_cap]):
                self.ns_ids[row, j] = v.id(ns)
        self._compile_selector(row, selector)

    def clear_row(self, row: int) -> None:
        """Reset a row to padding (every kernel gates on `valid`; the other
        fields are reset so re-use starts from a clean slate)."""
        self.valid[row] = False
        self.kind[row] = 0
        self.owner[row] = 0
        self.weight[row] = 0
        self.self_match[row] = False
        self.topo_slot[row] = -1
        self.ns_any[row] = False
        self.ns_ids[row] = 0
        self.has_selector[row] = False
        self.ml_slot[row] = -1
        self.ml_val[row] = 0
        self.ex_op[row] = 0
        self.ex_slot[row] = -1
        self.ex_vals[row] = -1

    def arrays(self) -> Dict[str, np.ndarray]:
        return {
            "valid": self.valid,
            "kind": self.kind,
            "owner": self.owner,
            "weight": self.weight,
            "topo_slot": self.topo_slot,
            "self_match": self.self_match,
            "ns_any": self.ns_any,
            "ns_ids": self.ns_ids,
            "has_selector": self.has_selector,
            "ml_slot": self.ml_slot,
            "ml_val": self.ml_val,
            "ex_op": self.ex_op,
            "ex_slot": self.ex_slot,
            "ex_vals": self.ex_vals,
        }


def _term_namespaces(owner_pod: Pod, term: PodAffinityTerm) -> List[str]:
    return list(term.namespaces) if term.namespaces else [owner_pod.namespace]


def encode_pod_terms(
    pod: Pod, selectors: Optional[List[LabelSelector]] = None
) -> Tuple[List[tuple], Dict[str, int]]:
    """ONE pod's topology-coupled structure as explicit term-row argument
    tuples plus its aux bits — the single source both compile_batch_terms
    (the per-batch host path) and the term slab (terms_plane.stage,
    enqueue-time interning) encode from. Both paths emit rows in THIS
    canonical order, so an index-gathered batch term table is
    bit-identical to a host-compiled one by construction.

    Returns (rows, aux): rows is a list of
    (kind, topo_key, selector, namespaces, ns_any, weight, self_match)
    tuples — TermBank.set_row's arguments minus the row/owner — in order:
    hard spread, soft spread, required affinity, required anti-affinity,
    preferred affinity, preferred anti-affinity, spreading selectors.
    aux holds the per-pod scalars of compile_batch_terms's aux arrays."""
    rows: List[tuple] = []
    aux = {
        "self_aff_match": False,
        "has_aff": False,
        "has_anti": False,
        "n_sel_spread": 0,
    }
    for c in get_hard_spread_constraints(pod):
        rows.append((
            SPREAD_HARD, c.topology_key, c.label_selector, (pod.namespace,),
            False, c.max_skew,
            match_label_selector(c.label_selector, pod.labels),
        ))
    for c in get_soft_spread_constraints(pod):
        # the soft-spread priority counts matching pods in ALL namespaces
        # (even_pods_spread.go quirk, see oracle.priorities)
        rows.append((
            SPREAD_SOFT, c.topology_key, c.label_selector, (),
            True, c.max_skew,
            match_label_selector(c.label_selector, pod.labels),
        ))
    aff_terms = get_pod_affinity_terms(pod.affinity)
    if aff_terms:
        aux["has_aff"] = True
        aux["self_aff_match"] = pod_matches_all_term_properties(pod, pod, aff_terms)
    for t in aff_terms:
        rows.append((
            AFF_REQ, t.topology_key, t.label_selector,
            tuple(_term_namespaces(pod, t)), False, 0, False,
        ))
    anti_terms = get_pod_anti_affinity_terms(pod.affinity)
    if anti_terms:
        aux["has_anti"] = True
    for t in anti_terms:
        rows.append((
            ANTI_REQ, t.topology_key, t.label_selector,
            tuple(_term_namespaces(pod, t)), False, 0, False,
        ))
    a = pod.affinity
    if a is not None and a.pod_affinity is not None:
        for w in a.pod_affinity.preferred:
            if w.weight and w.pod_affinity_term.topology_key:
                t = w.pod_affinity_term
                rows.append((
                    AFF_PREF, t.topology_key, t.label_selector,
                    tuple(_term_namespaces(pod, t)), False, w.weight, False,
                ))
    if a is not None and a.pod_anti_affinity is not None:
        for w in a.pod_anti_affinity.preferred:
            if w.weight and w.pod_affinity_term.topology_key:
                t = w.pod_affinity_term
                rows.append((
                    ANTI_PREF, t.topology_key, t.label_selector,
                    tuple(_term_namespaces(pod, t)), False, -w.weight, False,
                ))
    for sel in selectors or ():
        rows.append((SEL_SPREAD, "", sel, (pod.namespace,), False, 0, False))
        aux["n_sel_spread"] += 1
    return rows, aux


def count_pod_terms(pod: Pod, selectors: Optional[List[LabelSelector]] = None) -> int:
    """Exact row count encode_pod_terms would produce, without the
    selector-match work — the driver sizes its monotone term bucket from
    this BEFORE compiling (which retired the old compile-then-recompile-
    at-the-bigger-bucket retry)."""
    n = len(get_hard_spread_constraints(pod)) + len(get_soft_spread_constraints(pod))
    n += len(get_pod_affinity_terms(pod.affinity))
    n += len(get_pod_anti_affinity_terms(pod.affinity))
    a = pod.affinity
    if a is not None and a.pod_affinity is not None:
        n += sum(
            1 for w in a.pod_affinity.preferred
            if w.weight and w.pod_affinity_term.topology_key
        )
    if a is not None and a.pod_anti_affinity is not None:
        n += sum(
            1 for w in a.pod_anti_affinity.preferred
            if w.weight and w.pod_affinity_term.topology_key
        )
    return n + len(selectors or ())


def count_batch_terms(
    pods: Sequence[Pod],
    spread_selectors: Optional[Dict[int, List[LabelSelector]]] = None,
) -> int:
    return sum(
        count_pod_terms(p, (spread_selectors or {}).get(id(p)) or None)
        for p in pods
    )


def compile_batch_terms(
    vocab: Vocab,
    pods: Sequence[Pod],
    spread_selectors: Optional[Dict[int, List[LabelSelector]]] = None,
    capacity: Optional[int] = None,
    b_capacity: Optional[int] = None,
) -> Tuple[TermBank, Dict[str, np.ndarray]]:
    """Compile all topology-coupled structure of a pending-pod batch into one
    TermBank + per-pod aux arrays:
      self_aff_match[b]: pod matches its own required affinity terms' props
                         (the first-pod-in-series escape hatch)
      has_aff[b] / has_anti[b]: pod has required (anti-)affinity terms
      n_sel_spread[b]: number of spreading selectors (0 → score 0 rule)
    """
    encoded = [
        encode_pod_terms(p, (spread_selectors or {}).get(id(p), []) or [])
        for p in pods
    ]
    n_terms = sum(len(rows) for rows, _ in encoded)
    # `capacity` is a floor, not a trust: a caller sizing it from
    # count_pod_terms that drifted out of sync with encode_pod_terms
    # would otherwise silently push the tail rows into overflow_owners
    # (scalar-oracle routing — correct but slow); clamping to the exact
    # count keeps the two walks honest
    bank = TermBank(vocab, max(capacity or 0, _bucket(max(n_terms, 1))))
    b_count = b_capacity or _bucket(len(pods))
    self_aff_match = np.zeros(b_count, bool)
    has_aff = np.zeros(b_count, bool)
    has_anti = np.zeros(b_count, bool)
    n_sel_spread = np.zeros(b_count, np.int32)
    for b, (rows, a) in enumerate(encoded):
        for kind, topo, sel, nss, ns_any, weight, sm in rows:
            bank.add(
                kind, b, topo, sel, namespaces=nss, ns_any=ns_any,
                weight=weight, self_match=sm,
            )
        self_aff_match[b] = a["self_aff_match"]
        has_aff[b] = a["has_aff"]
        has_anti[b] = a["has_anti"]
        n_sel_spread[b] = a["n_sel_spread"]
    aux = {
        "self_aff_match": self_aff_match,
        "has_aff": has_aff,
        "has_anti": has_anti,
        "n_sel_spread": n_sel_spread,
    }
    return bank, aux


class PatternOverflow(KeySlotOverflow):
    """Pattern bank out of rows — rebuild at the next bucket size."""


@dataclass
class PatternBank:
    """Existing pods' (anti-)affinity terms collapsed to distinct PATTERNS
    with per-node instance counts — the term-side analogue of
    state.tensors.SigBank.

    The old encoding gave every (existing pod, term) pair its own TermBank
    row (owner = hosting node), so affinity-heavy clusters grew the ET axis
    with pod count: each growth bucket was a full solve recompile, every
    batch that committed an affinity pod re-walked ALL pods with terms
    (O(pods) host time) and re-uploaded the whole bank. But the kernels
    only ever need (a) whether a term matches the incoming pod and (b) how
    many instances of it live in each topology bucket — both functions of
    the term's CONTENT, not its owner. Distinct term contents are few
    (one per controller spec, not per replica), so rows become patterns
    interned by (kind, topology key, namespaces, weight, selector), and
    ownership becomes `counts[node, pattern]`, patched incrementally by
    dirty node rows exactly like SigBank.counts.

    Wire format (`arrays()`): the TermBank fields (valid/kind/topo_slot/
    weight/ns_*/selector tables; `owner` is the row's own index and unused
    by the pattern kernels) + `counts` [N, PT] int16.
    """

    vocab: Vocab
    capacity: int  # PT
    node_capacity: int  # N rows of the counts matrix
    hard_pod_affinity_weight: int = 1  # interpod_affinity.go:131

    def __post_init__(self):
        self.bank = TermBank(self.vocab, self.capacity)
        self.counts = np.zeros((self.node_capacity, self.capacity), np.int16)
        self._row_of: Dict[tuple, int] = {}
        self._key_of_row: Dict[int, tuple] = {}
        self._refs = np.zeros(self.capacity, np.int64)
        self._free = list(range(self.capacity - 1, -1, -1))
        self.dirty_pattern_rows: set = set()
        self.overflow_rows: set = set()

    # numpy views used by the driver's term-kind gating
    @property
    def valid(self) -> np.ndarray:
        return self.bank.valid

    @property
    def kind(self) -> np.ndarray:
        return self.bank.kind

    def _pod_patterns(self, pod: Pod) -> List[tuple]:
        """One pod's term contents as intern keys' raw args — the same row
        set the per-pod encoding used to produce."""
        aff = pod.affinity
        if aff is None:
            return []
        out = []
        for t in get_pod_anti_affinity_terms(aff):
            out.append((ANTI_REQ, t.topology_key, t.label_selector, _term_namespaces(pod, t), 0))
        hw = self.hard_pod_affinity_weight
        for t in get_pod_affinity_terms(aff):
            if hw > 0 and t.topology_key:
                out.append((AFF_REQ, t.topology_key, t.label_selector, _term_namespaces(pod, t), hw))
        if aff.pod_affinity is not None:
            for w in aff.pod_affinity.preferred:
                if w.weight and w.pod_affinity_term.topology_key:
                    t = w.pod_affinity_term
                    out.append((AFF_PREF, t.topology_key, t.label_selector, _term_namespaces(pod, t), w.weight))
        if aff.pod_anti_affinity is not None:
            for w in aff.pod_anti_affinity.preferred:
                if w.weight and w.pod_affinity_term.topology_key:
                    t = w.pod_affinity_term
                    out.append((ANTI_PREF, t.topology_key, t.label_selector, _term_namespaces(pod, t), -w.weight))
        return out

    @staticmethod
    def _key(kind: int, topo_key: str, selector, namespaces, weight: int) -> tuple:
        return (kind, topo_key, tuple(sorted(namespaces)), weight, repr(selector))

    def _intern(self, kind: int, topo_key: str, selector, namespaces, weight: int) -> int:
        key = self._key(kind, topo_key, selector, namespaces, weight)
        row = self._row_of.get(key)
        if row is None:
            if not self._free:
                raise PatternOverflow()
            row = self._free.pop()
            self.bank.clear_row(row)
            self.bank.overflow_owners.discard(row)
            self.bank.set_row(row, kind, row, topo_key, selector, namespaces, weight=weight)
            if row in self.bank.overflow_owners:
                # truncated selector: under/over-matches on device — the
                # driver must route affected batches through the oracle
                self.overflow_rows.add(row)
            self._row_of[key] = row
            self._key_of_row[row] = key
            self.dirty_pattern_rows.add(row)
        return row

    def prepare_pod_rows(self, pod: Pod) -> List[int]:
        """Intern one pod's term patterns WITHOUT taking references — the
        device-fold planner's counterpart of SigBank.prepare_row: the
        returned rows are where the later apply_delta will count this pod,
        so the device fold can scatter the counts ahead of the host sync.
        Raises PatternOverflow/KeySlotOverflow like _intern (caller skips
        the fold for the batch)."""
        return [
            self._intern(kind, topo, sel, nss, w)
            for kind, topo, sel, nss, w in self._pod_patterns(pod)
        ]

    def _unref(self, row: int, n: int) -> None:
        self._refs[row] -= n
        if self._refs[row] <= 0:
            self._refs[row] = 0
            self.bank.clear_row(row)
            self.bank.overflow_owners.discard(row)
            self.overflow_rows.discard(row)
            key = self._key_of_row.pop(row, None)
            if key is not None:
                self._row_of.pop(key, None)
            self._free.append(row)
            self.dirty_pattern_rows.add(row)

    def release_node(self, node_row: int, held: Dict[int, int]) -> None:
        """Undo a node's contribution: `held` is its {pattern: count} map."""
        for row, n in held.items():
            self.counts[node_row, row] -= n
            self._unref(row, n)

    def apply_delta(self, node_row: int, pod: Pod, sign: int, held: Dict[int, int]) -> None:
        """O(1) single-pod term-instance change (the mirror's pod-delta
        path). Raises KeySlotOverflow/PatternOverflow like encode_node; a
        remove for an unknown pattern escalates to a rebuild."""
        for kind, topo, sel, nss, w in self._pod_patterns(pod):
            if sign > 0:
                row = self._intern(kind, topo, sel, nss, w)
                held[row] = held.get(row, 0) + 1
                self._refs[row] += 1
                self.counts[node_row, row] += 1
            else:
                row = self._row_of.get(self._key(kind, topo, sel, nss, w))
                if row is None or held.get(row, 0) <= 0:
                    raise PatternOverflow()  # inconsistent books: rebuild
                held[row] -= 1
                if held[row] == 0:
                    del held[row]
                self.counts[node_row, row] -= 1
                self._unref(row, 1)

    def encode_node(self, node_row: int, pods) -> Dict[int, int]:
        """Count a node's pods' term instances into patterns → the
        {pattern: count} map the caller keeps for the matching
        release_node. Raises KeySlotOverflow/PatternOverflow for the
        mirror's rebuild-bigger loop (partial refs rolled back first)."""
        held: Dict[int, int] = {}
        try:
            for pod in pods:
                for kind, topo, sel, nss, w in self._pod_patterns(pod):
                    row = self._intern(kind, topo, sel, nss, w)
                    held[row] = held.get(row, 0) + 1
                    self._refs[row] += 1
                    self.counts[node_row, row] += 1
        except KeySlotOverflow:
            self.release_node(node_row, held)
            raise
        return held

    def arrays(self) -> Dict[str, np.ndarray]:
        out = self.bank.arrays()
        out["counts"] = self.counts
        return out


def compile_existing_patterns(
    vocab: Vocab,
    snapshot: Snapshot,
    row_of: Dict[str, int],
    node_capacity: int,
    hard_pod_affinity_weight: int = 1,
) -> PatternBank:
    """One-shot snapshot → PatternBank (tests/tools; the scheduler maintains
    its bank incrementally through TensorMirror)."""
    min_pt = 32
    while True:
        try:
            pats = PatternBank(
                vocab, _bucket(min_pt), node_capacity,
                hard_pod_affinity_weight=hard_pod_affinity_weight,
            )
            for name, ni in snapshot.node_infos.items():
                pats.encode_node(row_of[name], ni.pods)
            return pats
        except PatternOverflow:
            min_pt *= 2
        except KeySlotOverflow:
            continue  # vocab grew; re-encode
